"""Admission control and fair queuing: the overload discipline.

Under sustained overload a FIFO waiting queue answers the wrong
question — it decides WHO waits by arrival accident, lets one chatty
tenant starve everyone, and burns queue slots on requests that are
already guaranteed to miss their TTFT SLO. This module is the policy
object the scheduler's waiting line delegates to:

- **priority classes** (``high`` / ``normal`` / ``best_effort``):
  strict-priority dequeue; on queue pressure the lowest class sheds
  first (an incoming request displaces a strictly lower-priority one
  before it is itself rejected).
- **per-tenant weighted deficit round-robin** inside each class, over
  *token budgets* (prompt + predicted decode tokens), not request
  counts — a tenant submitting 4k-token prompts drains its deficit 4×
  faster than one submitting 1k-token prompts.
- **TTFT-SLO-aware early rejection**: a queue model (token backlog at
  equal-or-higher priority ÷ observed prefill+decode throughput from
  the dispatch histograms) predicts the queue wait; a request predicted
  to miss ``TPU_TTFT_SLO_MS`` is rejected at submit with a computed
  Retry-After instead of timing out after wasting a slot.
- **per-tenant decode-token rate limits**: a token bucket per tenant;
  best-effort requests of an over-rate tenant are throttled mid-stream
  (preempt + delayed resume on the same output stream).

All of this is host-side scheduler state. It must never enter the
multi-host broadcast stream (runtime/follower.py): followers replay
engine calls only, and the engine call sequence already encodes every
admission decision this module makes.

Knobs (all env; request options and Modelfile defaults override where
noted):

    TPU_DEFAULT_PRIORITY        default class (options.priority >
                                Modelfile ``priority`` > this; "normal")
    TPU_TTFT_SLO_MS             TTFT SLO for early rejection
                                (options.ttft_slo_ms > Modelfile > env;
                                0/unset disables)
    TPU_TENANT_WEIGHTS          "teamA=2,teamB=1" WDRR weights
                                (default weight 1)
    TPU_WDRR_QUANTUM            deficit top-up per round, tokens (256)
    TPU_TENANT_MAX_QUEUED       per-tenant queued-request cap → HTTP 429
                                (0/unset disables)
    TPU_TENANT_TOKEN_RATE       decode tokens/s per tenant (0 disables);
                                per-tenant overrides via
                                TPU_TENANT_LIMITS="teamA=50,teamB=100"
    TPU_TENANT_BURST_S          token-bucket burst depth, seconds of
                                rate (2.0)
    TPU_ADMIT_THROUGHPUT_TPS    fixed throughput estimate override for
                                the queue model (tests/bench; unset =
                                derive from dispatch histograms)
"""

from __future__ import annotations

import hashlib
import math
import os
import re
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ..server.metrics import GLOBAL as METRICS
from .errors import BadRequest
from .faults import FAULTS

# strict-priority order: rank 0 dequeues first, rank 2 sheds first
PRIORITIES: Tuple[str, ...] = ("high", "normal", "best_effort")
PRIORITY_RANK: Dict[str, int] = {p: i for i, p in enumerate(PRIORITIES)}
DEFAULT_TENANT = "default"

# shed causes exported on tpu_model_shed_total{class,cause} (metrics.py
# pre-seeds every class × cause combination at 0)
SHED_CAUSES: Tuple[str, ...] = ("queue_full", "deadline", "slo_predict",
                                "tenant_cap")

_TENANT_RE = re.compile(r"^[A-Za-z0-9._:-]{1,64}$")


class TenantRateLimited(RuntimeError):
    """A tenant exceeded its admission cap; maps to HTTP 429.

    Distinct from SchedulerBusy (503): the server has capacity, this
    caller specifically is over its share — backing off other tenants
    would not help, so load balancers must not treat it as backpressure.
    """

    def __init__(self, msg: str, *, retry_after_s: int = 1):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


def shed_labels(priority: str, cause: str) -> str:
    """Canonical label string for tpu_model_shed_total — keys sorted so
    reads (``METRICS.get``) and pre-seeds hit the same series."""
    return f'{{class="{priority}",cause="{cause}"}}'


# ----------------------------------------------------------------------
# option resolution (service.py side-channel pattern: merge_options
# drops unknown keys, so these read the raw dicts with the same
# request > Modelfile > env precedence as deadline_ms)
# ----------------------------------------------------------------------

def resolve_priority(defaults: Optional[Dict],
                     options: Optional[Dict]) -> str:
    o = dict(defaults or {})
    o.update(options or {})
    raw = o.get("priority")
    if raw is None:
        raw = os.environ.get("TPU_DEFAULT_PRIORITY") or None
    if raw is None:
        return "normal"
    p = str(raw).strip().lower()
    if p not in PRIORITY_RANK:
        raise BadRequest(
            f"invalid priority {raw!r}; expected one of "
            f"{'/'.join(PRIORITIES)}")
    return p


def resolve_tenant(options: Optional[Dict]) -> str:
    """``options.tenant`` (the HTTP layer injects one derived from the
    API-key header when the body carries none), sanitised so it is safe
    as a Prometheus label value; everyone else shares the default
    bucket."""
    raw = (options or {}).get("tenant")
    if raw is None or raw == "":
        return DEFAULT_TENANT
    t = str(raw)
    if _TENANT_RE.match(t):
        return t
    # unprintable/oversised names still deserve a stable bucket — hash
    # instead of rejecting (a tenant id is routing state, not an error)
    return "t-" + hashlib.sha256(t.encode()).hexdigest()[:12]


def tenant_from_key(header_value: str) -> str:
    """API-key/Authorization header → stable anonymous tenant id. The
    key itself must never appear in metrics labels or logs."""
    v = header_value.strip()
    for prefix in ("Bearer ", "Basic "):
        if v.startswith(prefix):
            v = v[len(prefix):].strip()
    if not v:
        return DEFAULT_TENANT
    return "key-" + hashlib.sha256(v.encode()).hexdigest()[:12]


def resolve_ttft_slo_s(defaults: Optional[Dict],
                       options: Optional[Dict]) -> Optional[float]:
    """TTFT SLO in seconds for early rejection, or None when disabled.
    Precedence: request ``ttft_slo_ms`` > Modelfile > TPU_TTFT_SLO_MS."""
    o = dict(defaults or {})
    o.update(options or {})
    raw = o.get("ttft_slo_ms")
    if raw is None:
        raw = os.environ.get("TPU_TTFT_SLO_MS") or None
    if raw is None:
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError) as e:
        raise BadRequest(f"invalid ttft_slo_ms: {raw!r}") from e
    if ms < 0:
        raise BadRequest("ttft_slo_ms must be >= 0")
    return ms / 1000.0 if ms > 0 else None


def _parse_kv_floats(env: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for part in os.environ.get(env, "").split(","):
        part = part.strip()
        if not part:
            continue
        k, _, v = part.partition("=")
        try:
            out[k.strip()] = float(v)
        except ValueError:
            continue   # a malformed weight must not take the server down
    return out


# ----------------------------------------------------------------------
# queue model: predicted queue wait for early rejection
# ----------------------------------------------------------------------

def observed_throughput_tps(tokens_done: float) -> float:
    """Tokens/s the engine has actually sustained: total tokens through
    the engine ÷ total device busy-time from the dispatch-latency
    histograms (every prefill and decode dispatch observes into
    tpu_model_dispatch_seconds). 0.0 = no signal yet (cold server) —
    callers must admit optimistically on 0."""
    env = os.environ.get("TPU_ADMIT_THROUGHPUT_TPS", "")
    if env:
        try:
            return max(float(env), 0.0)
        except ValueError:
            pass
    _n, busy_s = METRICS.hist_totals("tpu_model_dispatch_seconds")
    if busy_s <= 0.05 or tokens_done <= 0:
        return 0.0
    return tokens_done / busy_s


def predict_queue_wait_s(backlog_tokens: float,
                         tokens_done: float) -> float:
    """Queue model: tokens queued ahead (at equal-or-higher priority) ÷
    observed throughput. Deliberately simple — it only has to be right
    about requests that are OBVIOUSLY doomed; borderline calls are
    admitted and covered by the deadline machinery."""
    FAULTS.check("admission.predict")
    if backlog_tokens <= 0:
        return 0.0
    tps = observed_throughput_tps(tokens_done)
    if tps <= 0:
        return 0.0
    return backlog_tokens / tps


def retry_after_s(predicted_wait_s: float, slo_s: float,
                  tps: float) -> int:
    """Computed Retry-After: when the backlog ahead should have drained
    enough for a fresh arrival to fit inside the SLO. Monotone in the
    predicted wait, clamped to [1, 120]."""
    excess = max(predicted_wait_s - max(slo_s, 0.0), 0.0)
    return int(min(max(math.ceil(excess + 1e-9), 1), 120))


# ----------------------------------------------------------------------
# per-tenant decode-token rate limiting (mid-stream throttling)
# ----------------------------------------------------------------------

class TenantRateLimiter:
    """Token bucket per tenant over DECODE tokens. ``debit`` is called
    from the scheduler's fan-out as tokens are delivered; a bucket in
    debt answers a positive ``debt_delay`` and the scheduler throttle-
    preempts that tenant's best-effort slots until the bucket refills.
    Disabled (zero overhead beyond one attribute check) unless
    TPU_TENANT_TOKEN_RATE is set."""

    def __init__(self, rate_tps: float = 0.0,
                 overrides: Optional[Dict[str, float]] = None,
                 burst_s: float = 2.0):
        self.rate = max(rate_tps, 0.0)
        self.overrides = dict(overrides or {})
        self.burst_s = max(burst_s, 0.1)
        self.enabled = self.rate > 0 or any(
            v > 0 for v in self.overrides.values())
        self._lock = threading.Lock()
        # tenant → (tokens available, last refill stamp)
        self._buckets: Dict[str, Tuple[float, float]] = {}

    @classmethod
    def from_env(cls) -> "TenantRateLimiter":
        try:
            rate = float(os.environ.get("TPU_TENANT_TOKEN_RATE", "0") or 0)
        except ValueError:
            rate = 0.0
        try:
            burst = float(os.environ.get("TPU_TENANT_BURST_S", "2") or 2)
        except ValueError:
            burst = 2.0
        return cls(rate, _parse_kv_floats("TPU_TENANT_LIMITS"), burst)

    def _rate_for(self, tenant: str) -> float:
        return self.overrides.get(tenant, self.rate)

    def _refill(self, tenant: str, now: float) -> float:
        rate = self._rate_for(tenant)
        cap = rate * self.burst_s
        avail, last = self._buckets.get(tenant, (cap, now))
        avail = min(avail + (now - last) * rate, cap)
        self._buckets[tenant] = (avail, now)
        return avail

    def debit(self, tenant: str, n_tokens: int) -> None:
        if not self.enabled or self._rate_for(tenant) <= 0:
            return
        now = time.monotonic()
        with self._lock:
            avail = self._refill(tenant, now)
            self._buckets[tenant] = (avail - n_tokens, now)

    def debt_delay(self, tenant: str) -> float:
        """Seconds until this tenant's bucket is back above zero; 0.0
        when the tenant is within its rate (or unlimited)."""
        rate = self._rate_for(tenant)
        if not self.enabled or rate <= 0:
            return 0.0
        now = time.monotonic()
        with self._lock:
            avail = self._refill(tenant, now)
        if avail >= 0:
            return 0.0
        return -avail / rate


# ----------------------------------------------------------------------
# the waiting line itself
# ----------------------------------------------------------------------

class _ClassQueue:
    """One priority class: tenant → FIFO deque, served by weighted
    deficit round-robin over request token costs."""

    __slots__ = ("tenants", "deficit", "tokens")

    def __init__(self):
        self.tenants: "OrderedDict[str, deque]" = OrderedDict()
        self.deficit: Dict[str, float] = {}
        self.tokens = 0.0   # running token backlog of this class

    def __len__(self):
        return sum(len(d) for d in self.tenants.values())

    def push(self, req):
        dq = self.tenants.get(req.tenant)
        if dq is None:
            dq = self.tenants[req.tenant] = deque()
            # a tenant re-entering after idling starts with a clean
            # deficit (classic DRR: credit does not accrue while idle)
            self.deficit[req.tenant] = 0.0
        dq.append(req)
        self.tokens += req.cost

    def _drop_tenant_if_empty(self, tenant: str):
        if not self.tenants.get(tenant):
            self.tenants.pop(tenant, None)
            self.deficit.pop(tenant, None)

    def pop(self, weights: Dict[str, float], quantum: float):
        """WDRR dequeue: serve the front tenant while its deficit covers
        its head request's cost; otherwise top the deficit up by
        quantum × weight and rotate. Bounded: every full rotation adds
        at least one quantum to some tenant, so the loop terminates in
        O(max_cost / quantum) rotations."""
        if not self.tenants:
            return None
        for _ in range(16384):   # backstop, never hit in practice
            tenant, dq = next(iter(self.tenants.items()))
            head = dq[0]
            if self.deficit[tenant] >= head.cost:
                self.deficit[tenant] -= head.cost
                dq.popleft()
                self.tokens -= head.cost
                self._drop_tenant_if_empty(tenant)
                return head
            self.deficit[tenant] += quantum * weights.get(tenant, 1.0)
            self.tenants.move_to_end(tenant)
        # pathological cost/quantum ratio: force-serve the front tenant
        tenant, dq = next(iter(self.tenants.items()))
        head = dq.popleft()
        self.deficit[tenant] = 0.0
        self.tokens -= head.cost
        self._drop_tenant_if_empty(tenant)
        return head

    def newest(self):
        """(tenant, request) of the most recent arrival, for
        shed-lowest-first victim selection."""
        best = None
        for tenant, dq in self.tenants.items():
            r = dq[-1]
            if best is None or r.stats.t_submit > best[1].stats.t_submit:
                best = (tenant, r)
        return best

    def remove(self, req) -> bool:
        dq = self.tenants.get(req.tenant)
        if dq is None:
            return False
        try:
            dq.remove(req)
        except ValueError:
            return False
        self.tokens -= req.cost
        self._drop_tenant_if_empty(req.tenant)
        return True


class AdmissionQueue:
    """The scheduler's waiting line: strict priority across classes,
    WDRR token-budget fairness across tenants within a class, bounded at
    ``max_queue`` with shed-lowest-first displacement. Thread-safe (its
    own lock), mirroring the queue.Queue it replaces; it never touches
    request output queues or metrics — shedding side-effects stay in the
    scheduler so every shed path reads identically there."""

    def __init__(self, max_queue: int = 256,
                 weights: Optional[Dict[str, float]] = None,
                 quantum: Optional[float] = None):
        self.max_queue = max_queue
        self.weights = (_parse_kv_floats("TPU_TENANT_WEIGHTS")
                        if weights is None else dict(weights))
        if quantum is None:
            try:
                quantum = float(
                    os.environ.get("TPU_WDRR_QUANTUM", "256") or 256)
            except ValueError:
                quantum = 256.0
        self.quantum = max(quantum, 1.0)
        self._lock = threading.Lock()
        self._classes: List[_ClassQueue] = [
            _ClassQueue() for _ in PRIORITIES]

    def __len__(self):
        with self._lock:
            return sum(len(c) for c in self._classes)

    def empty(self) -> bool:
        return len(self) == 0

    def offer(self, req):
        """Try to enqueue. Returns ``(accepted, victim)``: accepted with
        no victim on space; accepted after evicting a strictly
        lower-priority ``victim`` (caller sheds it) under pressure;
        ``(False, None)`` when the incoming request itself is the lowest
        priority present — the caller rejects it."""
        with self._lock:
            if sum(len(c) for c in self._classes) < self.max_queue:
                self._classes[req.rank].push(req)
                return True, None
            # full: shed lowest-first — displace the newest request of
            # the lowest class strictly below the incoming one
            for rank in range(len(PRIORITIES) - 1, req.rank, -1):
                got = self._classes[rank].newest()
                if got is None:
                    continue
                _tenant, victim = got
                self._classes[rank].remove(victim)
                self._classes[req.rank].push(req)
                return True, victim
            return False, None

    def pop(self):
        """Strict-priority dequeue; WDRR inside the winning class."""
        with self._lock:
            for c in self._classes:
                req = c.pop(self.weights, self.quantum)
                if req is not None:
                    return req
            return None

    def peek_rank(self) -> Optional[int]:
        with self._lock:
            for rank, c in enumerate(self._classes):
                if c.tenants:
                    return rank
            return None

    def backlog_tokens(self, rank: int) -> float:
        """Token backlog queued at priority ``rank`` or better — the
        work a fresh arrival of that class must wait behind."""
        with self._lock:
            return sum(c.tokens for c in self._classes[:rank + 1])

    def queued_for(self, tenant: str) -> int:
        with self._lock:
            return sum(len(c.tenants.get(tenant, ()))
                       for c in self._classes)

    def sweep(self, pred) -> List:
        """Remove and return every queued request matching ``pred``
        (deadline/cancellation sweeps)."""
        out: List = []
        with self._lock:
            for c in self._classes:
                for tenant in list(c.tenants):
                    dq = c.tenants[tenant]
                    hit = [r for r in dq if pred(r)]
                    if not hit:
                        continue
                    keep = deque(r for r in dq if not pred(r))
                    c.tokens -= sum(r.cost for r in hit)
                    c.tenants[tenant] = keep
                    c._drop_tenant_if_empty(tenant)
                    out.extend(hit)
        return out

    def drain(self) -> List:
        """Remove and return everything (shutdown / broken drain)."""
        out: List = []
        with self._lock:
            for c in self._classes:
                for dq in c.tenants.values():
                    out.extend(dq)
                c.tenants.clear()
                c.deficit.clear()
                c.tokens = 0.0
        return out

    def stats(self) -> Dict:
        """Live snapshot for /api/ps: per-class queue depth and token
        backlog, distinct tenants queued."""
        with self._lock:
            tenants = set()
            for c in self._classes:
                tenants.update(c.tenants)
            return {
                "queued_by_class": {
                    p: len(self._classes[r])
                    for p, r in PRIORITY_RANK.items()},
                "backlog_tokens_by_class": {
                    p: int(self._classes[r].tokens)
                    for p, r in PRIORITY_RANK.items()},
                "tenants_queued": len(tenants),
                "wdrr_quantum": self.quantum,
            }
