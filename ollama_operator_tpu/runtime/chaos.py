"""Seeded randomized chaos campaigns over the fault-point catalog.

The per-point drills (tests/test_faults.py, tests/test_gateway.py) prove
each recovery path in isolation; what they cannot prove is that the
paths COMPOSE — that an engine restart during a gateway failover during
a scrape delay still converges to a fleet where every client stream
terminates exactly once and no page leaks. That is what a *campaign*
checks: from a single integer seed, a deterministic randomized schedule
of fault events drawn from the full ``FAULTS`` catalog (runtime/
faults.py) plus fleet-level actions (replica kills/revives, gateway
crash + journal restore), executed against a real fleet under mixed
traffic, with global invariants asserted after EVERY event and again at
quiesce.

Split of responsibilities:

- this module is the generic ENGINE: schedule generation, the
  inject → traffic → check loop, the chaos counter, and violation
  reporting. It is stdlib-only (plus the repo's metrics/faults/trace
  singletons) and knows nothing about servers or gateways.
- the HARNESS (tools/chaos_campaign builds the real one; tests build
  small ones) supplies the fleet. Duck-typed protocol:

  - ``fault_points`` — list of catalog point names to draw from
    (normally every name in ``FAULTS.points()``).
  - ``actions`` — ordered mapping of action name → ``fn(rng)`` for
    fleet events the injector cannot express (kill a replica process,
    crash the gateway, partition the control plane).
  - ``traffic(rng)`` — drive one round of mixed client traffic.
  - ``check(final=False)`` — raise ``AssertionError`` on any violated
    invariant; ``final=True`` runs the expensive quiesce-only checks
    (journal drained, threads settled, byte-identity ledger).
  - ``quiesce()`` — let in-flight work finish and revive anything the
    campaign killed, so the final check sees a settled fleet.

Determinism: the schedule is generated ONE EVENT AT A TIME from a
``random.Random(seed)`` that nothing else consumes, so the schedule for
``--events N`` is a strict prefix of the schedule for ``--events M > N``
— a violation at event k reproduces with ``--seed S --events k``.
Traffic shapes come from a second generator derived from the seed;
thread interleavings still vary, which is the point: the INVARIANTS
must hold on every interleaving, while the *injection sequence* is
pinned by the seed.

Every fault injection increments
``tpu_model_chaos_events_total{point=...}`` and records a
``chaos_inject`` flight event; the engine cross-checks counter against
schedule after each event, so "counters consistent with the flight
recorder" is itself a campaign invariant.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..server.metrics import GLOBAL as METRICS
from .faults import FAULTS
from .trace import FLIGHT

CHAOS_COUNTER = "tpu_model_chaos_events_total"

# Every spec here is SELF-DISARMING (bounded trigger): a campaign must
# converge back to a healthy fleet, so an unbounded `fail` that poisons
# every later round is not a legal draw. Delays model slow components
# (scrape timeouts, watchdog trips); fails model crashes.
FAULT_SPECS: Sequence[str] = (
    "fail:once",
    "fail:n=2",
    "fail:n=3",
    "delay:20ms:once",
    "delay:5ms:n=5",
)

# fraction of events that arm a fault point (the rest are fleet actions,
# split uniformly over the harness's action table)
_FAULT_WEIGHT = 0.7


@dataclass(frozen=True)
class ChaosEvent:
    """One scheduled injection: either arm ``spec`` at fault ``point``,
    or invoke the harness action named ``kind``."""
    idx: int                    # 1-based position in the schedule
    kind: str                   # "fault" or a harness action name
    point: str = ""
    spec: str = ""

    def describe(self) -> str:
        if self.kind == "fault":
            return f"fault {self.point} {self.spec}"
        return f"action {self.kind}"


class InvariantViolation(AssertionError):
    """A global invariant failed during a campaign. Carries everything a
    human needs for a deterministic repro: the seed, the failing event's
    index (``--events idx`` replays exactly this prefix), and the
    minimal event prefix itself."""

    def __init__(self, seed: int, events: List[ChaosEvent], cause: BaseException):
        self.seed = seed
        self.events = list(events)
        self.cause = cause
        at = events[-1].describe() if events else "quiesce"
        prefix = "\n".join(f"  {e.idx:3d}. {e.describe()}" for e in events)
        super().__init__(
            f"chaos invariant violated at event {len(events)} "
            f"({at}): {cause}\n"
            f"repro: python -m tools.chaos_campaign "
            f"--seed {seed} --events {max(1, len(events))}\n"
            f"event prefix:\n{prefix}")


@dataclass
class CampaignReport:
    """What a green campaign proved; rendered into GITHUB_STEP_SUMMARY
    by the CI job."""
    seed: int
    n_events: int
    faults_by_point: Dict[str, int] = field(default_factory=dict)
    actions: Dict[str, int] = field(default_factory=dict)
    traffic_rounds: int = 0
    checks: int = 0

    def summary_lines(self) -> List[str]:
        out = [f"seed {self.seed}: {self.n_events} events, "
               f"{self.traffic_rounds} traffic rounds, "
               f"{self.checks} invariant checks — green"]
        for point in sorted(self.faults_by_point):
            out.append(f"  - fault {point}: "
                       f"{self.faults_by_point[point]} injected")
        for name in sorted(self.actions):
            out.append(f"  - action {name}: {self.actions[name]}")
        return out


def next_event(rng: random.Random, idx: int, points: Sequence[str],
               actions: Sequence[str]) -> ChaosEvent:
    """Draw event ``idx``. Consumes ``rng`` only — the schedule prefix
    property (see module docstring) depends on nothing else touching
    this generator."""
    if actions and rng.random() >= _FAULT_WEIGHT:
        return ChaosEvent(idx=idx, kind=rng.choice(list(actions)))
    point = rng.choice(list(points))
    return ChaosEvent(idx=idx, kind="fault", point=point,
                      spec=rng.choice(list(FAULT_SPECS)))


def run_campaign(harness: Any, seed: int, n_events: int,
                 log: Optional[Callable[[str], None]] = None
                 ) -> CampaignReport:
    """Run one campaign; returns a report, or raises
    :class:`InvariantViolation` with the seed + minimal event prefix."""
    say = log or (lambda _m: None)
    points = list(getattr(harness, "fault_points", None)
                  or [p.name for p in FAULTS.points()])
    actions: Dict[str, Callable] = dict(getattr(harness, "actions", {}))
    sched_rng = random.Random(seed)
    # traffic randomness is seeded but SEPARATE: traffic draws must not
    # perturb the schedule prefix property
    traffic_rng = random.Random((seed << 1) ^ 0x5DEECE66D)
    report = CampaignReport(seed=seed, n_events=n_events)
    baseline = {p: METRICS.get(CHAOS_COUNTER, f'{{point="{p}"}}')
                for p in points}
    executed: List[ChaosEvent] = []
    try:
        for i in range(1, n_events + 1):
            ev = next_event(sched_rng, i, points, list(actions))
            executed.append(ev)
            if ev.kind == "fault":
                FAULTS.arm(ev.point, ev.spec)
                METRICS.inc(CHAOS_COUNTER, 1.0, f'{{point="{ev.point}"}}')
                FLIGHT.record("chaos_inject", point=ev.point, spec=ev.spec)
                report.faults_by_point[ev.point] = \
                    report.faults_by_point.get(ev.point, 0) + 1
            else:
                FLIGHT.record("chaos_action", action=ev.kind)
                actions[ev.kind](traffic_rng)
                report.actions[ev.kind] = report.actions.get(ev.kind, 0) + 1
            say(f"[{i}/{n_events}] {ev.describe()}")
            harness.traffic(traffic_rng)
            report.traffic_rounds += 1
            harness.check(final=False)
            report.checks += 1
            # counter ↔ schedule consistency is itself an invariant: the
            # chaos counter must read exactly what this campaign injected
            for p, n in report.faults_by_point.items():
                got = METRICS.get(CHAOS_COUNTER, f'{{point="{p}"}}')
                assert got == baseline[p] + n, (
                    f"chaos counter for {p} reads {got}, expected "
                    f"{baseline[p]} + {n} injected")
        # quiesce: disarm everything still pending, let the fleet settle,
        # then run the expensive whole-campaign checks
        FAULTS.reset()
        harness.quiesce()
        harness.check(final=True)
        report.checks += 1
    except AssertionError as e:
        FAULTS.reset()
        raise InvariantViolation(seed, executed, e) from e
    return report
