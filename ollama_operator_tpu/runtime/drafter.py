"""Host-side prompt-lookup drafter for fused speculative decoding.

Prompt-lookup / n-gram drafting (llama.cpp lookup decoding, Saxena 2023):
the candidate continuation for a slot is the run of tokens that followed
the most recent earlier occurrence of the context's final n-gram, taken
from the slot's OWN prompt + generated history. No draft model, no extra
HBM — repetition-heavy streams (code, JSON, summarisation, the loops
greedy decoding itself falls into) accept long runs, and a miss costs
nothing but the proposal loop.

The n-gram → continuation-position index is maintained incrementally by
the caller (one dict per request), so proposing after a dispatch costs
O(new tokens + k), not O(context). The index maps each n-gram to its
LATEST occurrence, matching the recency bias of the generated stream.

Drafts are verified device-side against the model's own argmax
(``ops/sampling.spec_accept`` inside the engine's fused spec program), so
draft QUALITY only affects speed, never output content — a wrong draft
is rejected by the same comparison that makes a right one free.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

NGRAM = 2      # bigram keys: cheapest index with useful recall


def extend_index(idx: Dict[Tuple[int, ...], int], hist: Sequence[int],
                 indexed_upto: int, ngram: int = NGRAM) -> int:
    """Fold ``hist[indexed_upto:]`` into the n-gram index and return the
    new high-water mark. The key for continuation position ``i`` is the
    n-gram ENDING at ``i - 1``, so the context's own final n-gram (whose
    continuation would sit past the end) is structurally unindexable —
    every gram with an in-range continuation is fair game, including the
    one ending at the second-to-last position (a period-1 loop like
    ``... x x x`` matches through exactly that entry)."""
    upto = len(hist)
    for i in range(max(indexed_upto, ngram), upto):
        idx[tuple(int(t) for t in hist[i - ngram: i])] = i
    return max(indexed_upto, upto)


def propose(hist: Sequence[int], idx: Dict[Tuple[int, ...], int],
            indexed_upto: int, k: int,
            ngram: int = NGRAM) -> Tuple[Optional[List[int]], int]:
    """Draft up to ``k`` tokens continuing ``hist``, or None when the
    final n-gram has no earlier occurrence. Returns (draft, new
    indexed_upto); the caller stores the high-water mark back so the
    next call only indexes tokens appended since."""
    indexed_upto = extend_index(idx, hist, indexed_upto, ngram)
    if len(hist) < ngram + 1:
        return None, indexed_upto
    key = tuple(int(t) for t in hist[-ngram:])
    pos = idx.get(key)
    if pos is None:
        return None, indexed_upto
    draft = [int(t) for t in hist[pos: pos + k]]
    if draft and len(draft) < k:
        # the matched continuation runs off the end of hist, which means
        # the tail repeats with period len(hist) - pos — keep unrolling
        # the loop instead of proposing a truncated draft (greedy
        # streams stuck in short cycles then accept all k every
        # dispatch; a wrong guess still costs nothing but the slack)
        period = len(hist) - pos
        while len(draft) < k:
            draft.append(int(hist[pos + len(draft) % period]))
    return (draft or None), indexed_upto
