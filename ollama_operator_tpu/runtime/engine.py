"""The serving engine: jitted prefill/decode over a slot-based KV cache.

This (plus scheduler.py) is the TPU-native replacement for llama.cpp's
server loop that the reference delegates to the ollama image
(/root/reference/pkg/model/pod.go:14-66, `ollama serve`). Design:

- **Slots**: a fixed decode batch of ``max_slots`` sequences. Every decode
  step advances all slots in ONE compiled XLA program (continuous batching —
  new requests are prefilled into free slots while others keep decoding).
- **Static shapes**: prefill lengths are padded to power-of-two buckets, so
  the number of compiled programs is O(log max_seq_len), not O(requests).
- **Donation**: KV caches and per-slot state are donated into each step, so
  XLA updates them in place in HBM — no cache copies per token.
- **Sharding**: params are TP-sharded (parallel/sharding.py), caches sharded
  [L, B@dp, KvH@tp, S, hd] (head-first so the pallas kernels read (S, hd)
  tiles directly); the same code runs single-chip (trivial mesh) or over a
  v5e slice.
- All sampling is on-device (ops/sampling.py); the only per-step
  host↔device traffic is the sampled token ids [B] coming back for
  streaming/stop handling.
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
from functools import partial
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.config import ModelConfig
from ..models import decoder
from ..ops import sampling
from .faults import FAULTS
from .trace import FLIGHT
from ..parallel.sharding import (kv_cache_pspec, params_sharding_tree,
                                 resolve_moe_impl)
from ..server.metrics import GLOBAL as METRICS


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    max_slots: int = 8
    max_seq_len: int = 2048
    # jnp.bfloat16 / jnp.float32, or jnp.int8 for the quantized KV cache
    # (ops/quant_cache.py: int8 entries + per-(position, head) f32 scales —
    # half the decode cache traffic, double the context per chip)
    cache_dtype: Any = jnp.bfloat16
    min_prefill_bucket: int = 64
    # penalty window CAPACITY (Ollama repeat_last_n default): repeat/
    # presence/frequency penalties see only the last N tokens, maintained
    # as a device-side ring buffer. The ring is statically sized at this
    # engine max; each request's own repeat_last_n (SlotOptions) selects
    # its effective window ≤ this via a per-slot modulus — no recompile.
    repeat_last_n: int = 64
    # decode steps per host round-trip: a lax.scan of this many steps runs
    # as ONE device program, so dispatch/sync latency (large under the
    # remote-TPU tunnel; nonzero everywhere) amortises across the chunk.
    # Streaming granularity and admission latency grow with it. 0 = let
    # resolve_serving_defaults pick per backend (32 on TPU — the measured
    # serving config, BASELINE.md r3/r4 — 8 elsewhere); direct engine
    # constructions use the explicit value.
    decode_chunk: int = 8
    # paged KV cache (runtime/paged.py + ops/pallas/paged.py): slots share
    # a physical page pool instead of each reserving max_seq_len — HBM
    # scales with live tokens, so max_slots can be 32+ on one chip
    # (SURVEY.md §7 hard-part 2). Meshes: single-device, tp, dp, dp×tp
    # (under dp the pool shards into per-shard sub-pools); sp keeps the
    # dense sequence-sharded cache. The SERVER passes None = decide per
    # model at load (resolve_paged_default); direct engine constructions
    # default off.
    paged: Optional[bool] = False
    # 0 = resolve per backend when paged (128 on TPU — the round-5
    # page-size ladder measured +10.5% over 64 at B=32 and −1.5% at 256;
    # fewer, larger page DMAs amortize the serialized per-page walk);
    # direct engine constructions use the explicit value
    page_size: int = 64
    # data pages in the pool (excl. the trash page); None = the dense
    # equivalent max_slots * max_seq_len / page_size — same HBM ceiling,
    # but shared, so mixed-length batches fit far more concurrency
    n_pages: Optional[int] = None


def resolve_serving_defaults(ecfg: "EngineConfig", cfg: ModelConfig,
                             mesh) -> "EngineConfig":
    """Resolve the server's tri-state knobs into a concrete EngineConfig.

    - ``paged=None`` → resolve_paged_default (GQA on TPU pages, MHA/MoE/
      CPU stay dense; explicit True/False passes through).
    - ``max_slots=0`` → 64 for GQA paged on TPU (r5 ladder: 3902 tok/s
      vs 2848 at 32), 32 for other paged, 8 dense.
    - ``decode_chunk=0`` → 32 on TPU, 8 elsewhere (the config every
      BASELINE.md headline was measured at; round-1's chunk-8 default
      served the 64–116 tok/s class on the same chip).
    - ``page_size=0`` → 128 for GQA paged on TPU (r5 page-size ladder:
      +10.5% over 64 at B=32, 256 regresses; MHA measured −2% so it
      keeps 64), 64 elsewhere.
    - When paged resolved on with auto slots and no explicit pool size,
      the pool is byte-capped: the 32-slot default shares a dense-8
      HBM-equivalent pool (footprint of the old dense default), the
      64-slot GQA default a dense-24 one — the measured minimum that
      holds 64 mixed slots at design load without running dry (r5
      window 3/4). Full-length overload preempts/requeues instead of
      OOMing at load. The pool stores heads padded to the 128-lane tile,
      so for hd<128 models the auto page count shrinks by hd/hd_pool —
      the BYTE ceiling is what's preserved, not the token count.
    """
    import os

    import jax
    on_tpu = jax.default_backend() == "tpu"
    chunk = ecfg.decode_chunk or resolve_decode_chunk_default()
    # prefill-bucket floor: smaller buckets mean finer chunked-prefill
    # pieces (TPU_PREFILL_CHUNK rounds up to a bucket) at the cost of a
    # few more compiled prefill programs — O(log seq) either way. Mostly
    # useful on small-context models where the 64 default leaves no room
    # for a multi-piece admission.
    minb = (int(os.environ.get("TPU_MIN_PREFILL_BUCKET", "0") or 0)
            or ecfg.min_prefill_bucket)
    # page_size 128 only pays for GQA (few kv heads → 16 KB pages at 64;
    # doubling them bought +10.5% in the r5 ladder). An MHA page is
    # already KvH× larger — the same window measured ps=128 at −2%
    # (noise) on phi, so MHA keeps 64.
    gqa = cfg.n_kv_heads < cfg.n_heads
    if ecfg.paged is not None and ecfg.max_slots != 0:
        ps = ecfg.page_size or (128 if on_tpu and ecfg.paged and gqa
                                else 64)
        return dataclasses.replace(ecfg, decode_chunk=chunk, page_size=ps,
                                   min_prefill_bucket=minb)
    paged = (resolve_paged_default(cfg, mesh) if ecfg.paged is None
             else ecfg.paged)
    ps = ecfg.page_size or (128 if on_tpu and paged and gqa else 64)
    # GQA pages at 64 slots on TPU (r5 ladder: 3902 tok/s at 64 vs 2848
    # at 32, TTFT p50 ~112 ms — aggregate throughput is the serving
    # metric); MHA keeps 32 (its paged step is ~3x GQA's, 64 would double
    # streaming latency on an unmeasured combination)
    slots = ecfg.max_slots or ((64 if on_tpu and gqa else 32)
                               if paged else 8)
    n_pages = ecfg.n_pages
    if paged and n_pages is None and ecfg.max_slots == 0:
        serve_seq = min(ecfg.max_seq_len, cfg.max_seq_len)
        hd_pool = -(-cfg.head_dim // 128) * 128
        # pool byte ceiling: dense-8 equivalent for the 32-slot default,
        # dense-24 for the 64-slot GQA default — measured, not guessed:
        # the r5 window-3 capture showed 64 mixed slots at design load
        # (live ~210/slot) round up to ~160 ps-128 pages, so a dense-16
        # cap (128 pages) ran the pool dry mid-capture; 24×seq holds the
        # design load with ~15% slack (window-4 validation capture)
        ceil_slots = 24 if slots >= 64 else 8
        n_pages = max(1, (ceil_slots * serve_seq) * cfg.head_dim
                      // hd_pool // ps)
    return dataclasses.replace(ecfg, paged=paged, max_slots=slots,
                               n_pages=n_pages, decode_chunk=chunk,
                               page_size=ps, min_prefill_bucket=minb)


def resolve_paged_default(cfg: ModelConfig, mesh) -> bool:
    """The serving default for an unset paged flag, per model and mesh.

    Data-driven (BASELINE.md r3+r4, v5e): GQA models page (r3: paged-32
    measured 1.90-2.04x the dense-8 aggregate on tinyllama). MHA models
    page too SINCE the v3 live-page async-DMA kernel — the r4
    same-window A/B measured phi (KvH=32) paged-32 at 934.5 tok/s
    vs ~570 dense-8 (the r3 grid kernel was per-head-dot-bound at
    190 ms/step, which is why MHA used to stay dense); with the kernel
    explicitly reverted (TPU_PAGED_V3=0) MHA keeps the dense default.
    Off for MoE (untested combination), for meshes the pool can't shard
    (sp; dp without a valid dp-manual layout), and off the TPU backend
    entirely (the measurement is v5e's; a 1-core CPU dev/kind pod gets
    4x the per-step compute from a 32-slot batch). An explicit --paged /
    TPU_PAGED=0|1 always wins."""
    import os

    import jax
    if jax.default_backend() != "tpu":
        return False
    if (cfg.n_kv_heads >= cfg.n_heads
            and os.environ.get("TPU_PAGED_V3", "1") != "1"):
        return False
    if cfg.n_experts:
        return False
    if mesh is None:
        return True
    shape = dict(mesh.shape)
    if any(sz > 1 for ax, sz in shape.items() if ax not in ("tp", "dp")):
        return False
    if shape.get("dp", 1) > 1:
        from ..models.decoder import _paged_dp_axes
        if _paged_dp_axes(cfg, mesh, cfg.n_kv_heads) is None:
            return False
    return True


def resolve_decode_chunk_default() -> int:
    """Serving decode_chunk when the CR/env/flag leaves it unset.

    Data-driven (BASELINE.md, v5e): the dispatch+sync round-trip under the
    remote-TPU path is ~10 ms, so chunk 8 leaves >50% of the step budget in
    host turnaround; every headline capture since r2 ran chunk 32 (phi
    dense-8 ~570 tok/s vs 64–116 at r1's chunk 8), with chunk 64 only ~3%
    beyond it (589.2 — not worth 2× chunkier streaming by default; it
    remains the explicit-throughput knob, TPU_DECODE_CHUNK=64). CPU pods
    keep 8: per-step compute dominates there, and kind/e2e latency would
    otherwise balloon."""
    import jax
    return 32 if jax.default_backend() == "tpu" else 8


def resolve_engine_dtype(cfg: ModelConfig, backend: str) -> str:
    """Weight serving dtype when neither CR ``spec.quantization`` nor
    --dtype/TPU_ENGINE_DTYPE picked one.

    The zero-config contract (the reference's sample CR serves usably with
    no tuning fields, /root/reference/config/samples/ollama_v1_model.yaml)
    must land in the measured headline band, not the bf16 config nothing
    benches: on a 16 GB v5e chip, int8 weight-only quantization is the
    measured serving config ≤4B (phi int8 ~570 tok/s dense-8; bf16 halves
    that by doubling streamed bytes), and 7B+ needs int4 to leave HBM room
    for the KV pool (mistral-7B int4 = the r4 flagship; bf16 7B does not
    fit at all). MoE expert stacks serve dense bf16 (quantized expert
    matmuls are an unmeasured path). CPU serves f32 — XLA's CPU thunk
    runtime has no bf16 dots and the quantized matmuls are pallas/TPU
    paths. An explicit spec/env/flag always wins (callers only consult
    this when theirs is unset)."""
    if backend != "tpu":
        return "float32"
    if cfg.n_experts:
        return "bfloat16"
    return "int4" if cfg.n_params >= 4e9 else "int8"


def resolve_kv_dtype_default(backend: str) -> str:
    """KV-cache dtype default: int8 on TPU (half the decode cache traffic,
    double the context per chip — every BASELINE.md capture since r2 runs
    it; parity suite covers the quantized cache), f32 on CPU (no bf16
    support in the thunk runtime, and CPU pods are dev/e2e anyway)."""
    return "int8" if backend == "tpu" else "float32"


CACHE_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
                "int8": jnp.int8,
                # "int4" stays a STRING sentinel: there is no 4-bit storage
                # array — the pool holds nibble-packed int8 ({"q4", "s"},
                # ops/quant_cache.py) and only the paged cache supports it
                "int4": "int4"}


def resolve_cache_dtype(name_or_dtype) -> Any:
    """Normalise a cache dtype given as a name or jnp dtype; rejects
    anything outside the supported set (a stray dense-int8 cache would
    silently truncate K/V to ±1). int4 resolves to the string sentinel
    "int4" (nibble-packed storage has no jnp dtype of its own)."""
    if isinstance(name_or_dtype, str):
        if name_or_dtype not in CACHE_DTYPES:
            raise ValueError(f"cache dtype {name_or_dtype!r}; expected one "
                             f"of {sorted(CACHE_DTYPES)}")
        return CACHE_DTYPES[name_or_dtype]
    dt = jnp.dtype(name_or_dtype)
    table = {jnp.dtype(v): v for v in CACHE_DTYPES.values()
             if not isinstance(v, str)}
    assert dt in table, f"unsupported cache dtype {dt}"
    return table[dt]


def unpack_mask(mask_bits, V: int):
    """Packed [..., ceil(V/32)] uint32 → bool [..., V] allowed-token mask.

    The grammar-constrained decode path (ops/constrain.py): the host uploads
    one packed row per slot and the decode program unpacks it on device —
    32× less host→device traffic than a dense bool mask, and no logits
    download (sampling stays on device)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (mask_bits[..., None] >> shifts) & jnp.uint32(1)
    return bits.reshape(*mask_bits.shape[:-1], -1)[..., :V] != 0


def prefill_buckets(max_seq_len: int, min_bucket: int):
    b, out = min_bucket, []
    while b < max_seq_len:
        out.append(b)
        b *= 2
    out.append(max_seq_len)
    return out


@dataclasses.dataclass
class SlotOptions:
    """Host-side per-request sampling options (Ollama API options subset)."""
    temperature: float = 0.8
    top_k: int = 40
    top_p: float = 0.9
    min_p: float = 0.0
    typical_p: float = 1.0
    repeat_penalty: float = 1.1
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # mirostat: 0 off, 1/2 replace the static filters with the adaptive
    # surprise truncation (per-slot mu state lives in Engine.mu)
    mirostat: int = 0
    mirostat_tau: float = 5.0
    mirostat_eta: float = 0.1
    seed: int = -1
    # penalty window for THIS request: 0 disables the window, -1 means
    # "engine max"; values above the engine's repeat_last_n capacity clamp
    repeat_last_n: int = 64


class DecodeHandle:
    """An in-flight chunked decode dispatch: the device program is
    launched and the slot state already advanced, but the sampled tokens
    are still device-side futures. ``wait()`` materialises them ([n, B]).

    The point is JAX async dispatch: the caller can launch dispatch N+1
    (or an admission piece) BEFORE waiting on dispatch N, so host-side
    fan-out/detokenise work overlaps device compute. Donated-state data
    dependencies keep device programs ordered regardless of when (or
    whether) wait() runs.

    ``epoch`` is the paged-mode dispatch epoch this launch was stamped
    with (0 for dense engines). Once wait() returns, the program is
    materialised and the caller may pass the epoch back into the next
    ``decode_n_launch(retire=...)`` to unfence pages quarantined up to
    it — wait() itself must NOT retire, because multi-host followers
    replay launches without ever waiting and the free-list order has to
    stay bit-identical across hosts (runtime/paged.py docstring).

    A speculative launch (``decode_n_launch(drafts=...)``) additionally
    sets ``budgets`` — the per-slot host-length advance taken at launch,
    an upper bound since accept counts are still device-side futures —
    and wait() fills ``accepted`` (tokens actually emitted per slot) and
    returns rows transposed to [k+1, B] so fan-out sees the same
    row-major layout as a chunked dispatch. The caller acks the
    overshoot back with ``Engine.spec_ack(budgets - accepted)``; the ack
    rides the broadcast call stream, which is what lets followers (who
    never wait) keep bit-identical host lengths."""

    __slots__ = ("_engine", "_toks", "_t0", "_out", "epoch", "budgets",
                 "accepted", "t_done")

    def __init__(self, engine: "Engine", toks, t0: float, epoch: int = 0,
                 budgets: Optional[np.ndarray] = None):
        self._engine = engine
        self._toks = toks
        self._t0 = t0
        self._out: Optional[np.ndarray] = None
        self.epoch = epoch
        self.budgets = budgets
        self.accepted: Optional[np.ndarray] = None
        # perf_counter() when wait() materialised the tokens; with
        # t_launch this makes the async launch→materialize overlap
        # visible to the tracing layer (runtime/trace.py)
        self.t_done: Optional[float] = None

    @property
    def t_launch(self) -> float:
        """perf_counter() at launch time (set by decode_n_launch)."""
        return self._t0

    def wait(self) -> np.ndarray:
        if self._out is None:
            toks = self._engine._fetch(self._toks)
            self.t_done = time.perf_counter()
            if self.budgets is not None:
                # [B, k+1] sentinel-padded: valid entries per row are the
                # accepted draft prefix + bonus token, in order
                self.accepted = (
                    toks < self._engine.cfg.vocab_size).sum(axis=1)
                toks = toks.T
                self._engine.dispatch_ms["spec"] = (
                    (self.t_done - self._t0) * 1e3)
            else:
                self._engine.dispatch_ms["decode"] = (
                    (self.t_done - self._t0) * 1e3)
            self._out = toks
            self._toks = None
        return self._out


class Engine:
    """Owns device state and the compiled step functions."""

    def __init__(self, cfg: ModelConfig, params, mesh: Optional[Mesh] = None,
                 ecfg: EngineConfig = EngineConfig()):
        # pallas_call is opaque to GSPMD, but the attention dispatch
        # (ops/attention.py) wraps the kernels in a dp/tp-manual shard_map
        # whenever a >1-device mesh is passed — so real meshes keep the
        # flash kernels (round-1 VERDICT weak #2: the old code forced
        # kernels="xla" here and the tp path served on einsum attention).
        if cfg.n_experts:
            cfg = dataclasses.replace(
                cfg, moe_impl=resolve_moe_impl(cfg, mesh))
        self.cfg = cfg
        self.ecfg = ecfg
        self.mesh = mesh
        B, S = ecfg.max_slots, min(ecfg.max_seq_len, cfg.max_seq_len)
        self.n_slots, self.max_seq = B, S
        L, KvH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
        V = cfg.vocab_size

        cache_dtype = resolve_cache_dtype(ecfg.cache_dtype)
        if cache_dtype is not ecfg.cache_dtype:
            ecfg = dataclasses.replace(ecfg, cache_dtype=cache_dtype)
            self.ecfg = ecfg
        self.quant4 = cache_dtype == "int4"
        self.quant_cache = (self.quant4
                            or jnp.dtype(cache_dtype) == jnp.dtype(jnp.int8))
        if self.quant4 and not ecfg.paged:
            raise ValueError(
                "cache dtype 'int4' requires the paged cache (the dense "
                "cache has no nibble-packed layout); set paged=True or "
                "use int8")
        self.sp_size = mesh.shape.get("sp", 1) if mesh is not None else 1
        if self.sp_size > 1:
            assert self.sp_size & (self.sp_size - 1) == 0, (
                f"sp={self.sp_size} must be a power of two (prefill buckets "
                f"are; each bucket must shard evenly over sp)")
            assert S % self.sp_size == 0, (
                f"max_seq_len {S} must be divisible by sp={self.sp_size}")
        self.paged = ecfg.paged
        self._paged_dp = 1
        if self.paged:
            assert self.sp_size == 1, (
                "paged cache: sp meshes keep the dense sequence-sharded "
                "cache (long_context.py)")
            if mesh is not None:
                extra = {ax: sz for ax, sz in dict(mesh.shape).items()
                         if sz > 1 and ax not in ("tp", "dp")}
                assert not extra, (
                    f"paged cache supports single-device, tp, dp, or "
                    f"dp×tp meshes; got {extra}")
                if mesh.shape.get("dp", 1) > 1:
                    from ..models.decoder import _paged_dp_axes
                    assert _paged_dp_axes(cfg, mesh, KvH) is not None, (
                        f"paged dp mesh needs dp×tp covering all devices "
                        f"with heads divisible by tp; got "
                        f"{dict(mesh.shape)}, H={cfg.n_heads}, KvH={KvH}")
                    self._paged_dp = mesh.shape["dp"]
            ps = ecfg.page_size
            assert ps > 0 and ps & (ps - 1) == 0, (
                f"page_size {ps} must be a power of two")
            assert S % ps == 0, f"max_seq_len {S} must be divisible by page_size {ps}"
        if mesh is not None:
            dp = mesh.shape.get("dp", 1)
            assert B % dp == 0, f"max_slots {B} must divide dp {dp}"
            cache_sh = NamedSharding(mesh, kv_cache_pspec(cfg, mesh))
            b_ax = "dp" if dp > 1 else None
            slot_sh = NamedSharding(mesh, P(b_ax))
            # rank-2 slot state (counts [B,V], pring [B,W], masks) needs a
            # CLOSED spec: P("dp") on rank 2 leaves dim 1 open, and GSPMD
            # is then free to shard it differently per program — an AOT
            # decode exec would reject the re-sharded state
            slot_sh2 = NamedSharding(mesh, P(b_ax, None))
            # multi-controller slice (jax.distributed world): the mesh
            # spans devices other processes own, so host values become
            # global arrays via make_array_from_callback — device_put
            # rejects non-addressable shardings
            self._multi = not all(d.process_index == jax.process_index()
                                  for d in mesh.devices.flat)
            assert not (self._multi and dp > 1), (
                "multi-host slices serve with tp/sp meshes; dp-sharded "
                "slot state is process-local (decode outputs ride P('dp') "
                "and the host only reads its own shard) — scale batch "
                "across hosts with CRD replicas instead")
            self._repl_sh = NamedSharding(mesh, P())
        else:
            cache_sh = slot_sh = slot_sh2 = None
            self._multi = False
            self._repl_sh = None
        self._cache_sh, self._slot_sh = cache_sh, slot_sh
        self._slot_sh2 = slot_sh2
        # fused single-matmul QKV (models/decoder.fuse_qkv_params).
        # Opt-in (TPU_FUSED_QKV=1): isolated jit-call microbenches showed
        # 3.5x on GQA projections, but the on-chip serving A/B measured
        # -3.7% — inside the one compiled decode program XLA already
        # schedules the three dots back-to-back, so there is no per-op
        # dispatch floor to save (BASELINE.md r4). Kept for experiments
        # and hosts where dispatch-bound serving paths exist.
        import os as _os
        if (_os.environ.get("TPU_FUSED_QKV", "0") == "1"
                and (mesh is None
                     or all(sz == 1 for ax, sz in dict(mesh.shape).items()
                            if ax != "dp"))):
            from ..models.decoder import fuse_qkv_params
            params = fuse_qkv_params(params, cfg)
        if mesh is not None:
            self._param_sh = params_sharding_tree(params, mesh, cfg)
            params = jax.tree_util.tree_map(self._g, params,
                                            self._param_sh)
        else:
            self._param_sh = None
        self.params = params

        def zeros(shape, dtype, sh):
            return self._g(np.zeros(shape, dtype), sh)

        self._radix = None
        self._arena = None           # tier-1 host arena (host_cache.py)
        self._host_page_bytes = 0
        self.n_spilled_pages = 0     # pages moved HBM → host, lifetime
        self.last_stitch = None      # per-tier token breakdown of the
                                     # most recent stitch() (scheduler
                                     # reads it for the tier metrics)
        if self.paged:
            from .paged import PageTable, ShardedPageTable
            ps = ecfg.page_size
            self._nblk = S // ps
            n_pages = ecfg.n_pages or (B * S) // ps
            # pool head dim padded to the 128-lane tile: an unaligned hd
            # (phi's 80) otherwise makes XLA materialise PADDED temp
            # copies of the whole pool per program (measured on v5e:
            # 2x4 GB HLO temps, OOM at 32 slots). Writers zero-pad K/V;
            # readers slice back (models/decoder.py paged section).
            # hd=128 families (llama/qwen/mixtral) are untouched; hd<128
            # (phi 80, tinyllama 64) pay the padding in pool bytes — on
            # TPU the minor dim would tile to 128 anyway, but on the CPU
            # backend (dev/kind clusters) this genuinely grows host RAM.
            hd_pool = -(-hd // 128) * 128
            dp = self._paged_dp
            if dp > 1:
                # pool PAGE axis sharded over dp: each shard owns an
                # independent sub-pool (own trash page, own free list) and
                # tables carry shard-LOCAL page indices — the paged
                # forward's dp-manual region then never crosses shards
                per_shard = -(-n_pages // dp)
                self._pt = ShardedPageTable(B, dp, per_shard, ps,
                                            self._nblk)
                pool_shape = (L, dp * (per_shard + 1), KvH, ps, hd_pool)
                pg_ax = "dp"
            else:
                self._pt = PageTable(B, n_pages + 1, ps, self._nblk)
                pool_shape = (L, n_pages + 1, KvH, ps, hd_pool)
                pg_ax = None
            h_ax = ("tp" if (mesh is not None
                             and mesh.shape.get("tp", 1) > 1
                             and KvH % mesh.shape["tp"] == 0) else None)
            pool_sh = (NamedSharding(mesh, P(None, pg_ax, h_ax, None, None))
                       if mesh is not None else None)
            if self.quant_cache:
                s_sh = (NamedSharding(mesh, P(None, pg_ax, h_ax, None))
                        if mesh is not None else None)
                # int4 packs two POSITIONS per byte along the page axis
                # ("q4" [L, P, KvH, ps//2, hd_pool] — ops/quant_cache.py),
                # keeping the 128-lane head dim intact for the fused
                # kernel's page DMAs; scales stay per-position f32
                qkey = "q4" if self.quant4 else "q"
                if self.quant4:
                    assert ps >= 2, "int4 KV needs page_size >= 2"
                q_shape = (pool_shape[:-2] + (ps // 2, hd_pool)
                           if self.quant4 else pool_shape)
                cache_sh = {qkey: pool_sh, "s": s_sh}
                # scale arrays lane-padded to the 128 tile like the codes'
                # head dim: the v3 kernel DMAs [KvH, ps] f32 slices per
                # page, and Mosaic requires the DMA'd minor dim to be a
                # multiple of 128 lanes (ps=64 default crashes the real
                # lowering). Writers scatter at off < ps; readers slice
                # (:ps); pad lanes stay zero and inert.
                sp_pool = -(-ps // 128) * 128
                s_shape = pool_shape[:-2] + (sp_pool,)
                self.k_cache = {
                    qkey: zeros(q_shape, jnp.int8, pool_sh),
                    "s": zeros(s_shape, jnp.float32, s_sh)}
                self.v_cache = {
                    qkey: zeros(q_shape, jnp.int8, pool_sh),
                    "s": zeros(s_shape, jnp.float32, s_sh)}
            else:
                cache_sh = pool_sh
                self.k_cache = zeros(pool_shape, ecfg.cache_dtype, pool_sh)
                self.v_cache = zeros(pool_shape, ecfg.cache_dtype, pool_sh)
            self._cache_sh = cache_sh
            # admission-order stamps for preemption victim choice
            self._admit_order = np.zeros((B,), np.int64)
            self._admit_seq = 0
            # radix prefix cache: page-granular cross-request KV reuse
            # (single sub-pool only — a dp-sharded pool's table entries
            # are shard-LOCAL page ids, so a tree spanning shards would
            # stitch pages the slot's shard cannot read).
            # TPU_PREFIX_CACHE=0 falls back to the parked-slot path.
            if (dp == 1
                    and _os.environ.get("TPU_PREFIX_CACHE", "1").lower()
                    not in ("0", "false")):
                from .radix import RadixCache
                self._radix = RadixCache(ps)
                # tier-1 host arena: radix LRU eviction spills quiescent
                # pages here instead of freeing them (ISSUE 18). Bounded
                # by TPU_HOST_CACHE_GB; 0 keeps eviction tierless.
                from .host_cache import HostArena, host_cache_bytes
                hc_bytes = host_cache_bytes()
                if hc_bytes > 0:
                    def _pg_bytes(tree):
                        return sum(leaf.nbytes // leaf.shape[1]
                                   for leaf in
                                   jax.tree_util.tree_leaves(tree))
                    self._host_page_bytes = (_pg_bytes(self.k_cache)
                                             + _pg_bytes(self.v_cache))
                    self._arena = HostArena(hc_bytes,
                                            self._host_page_bytes)
        elif self.quant_cache:
            from ..ops.quant_cache import empty_cache

            def qzeros(sh):
                c = empty_cache(L, B, KvH, S, hd)
                if sh is None:
                    return c
                return jax.tree_util.tree_map(self._g, c, sh)
            cache_sh = self._quant_cache_sharding(cache_sh)
            self._cache_sh = cache_sh
            self.k_cache = qzeros(cache_sh)
            self.v_cache = qzeros(cache_sh)
        else:
            cache_shape = (L, B, KvH, S, hd)  # head-first: (S, hd) tiles
            self.k_cache = zeros(cache_shape, ecfg.cache_dtype, cache_sh)
            self.v_cache = zeros(cache_shape, ecfg.cache_dtype, cache_sh)
        self.lengths = zeros((B,), jnp.int32, slot_sh)
        self.counts = zeros((B, V), jnp.int32, slot_sh2)
        # penalty ring: the last repeat_last_n token ids per slot (sentinel
        # V = "empty"; scatter-drop keeps it out of counts)
        W = max(1, ecfg.repeat_last_n)
        self.pring = self._g(np.full((B, W), V, np.int32), slot_sh2)
        self.last_tokens = zeros((B,), jnp.int32, slot_sh)
        # grammar-constraint state: packed per-slot allowed-token masks
        # (all-ones + flag 0 = unconstrained; ops/constrain.py fills rows)
        self.mask_words = (V + 31) // 32
        self._mask_ones = self._gr(
            np.full((self.mask_words,), 0xFFFFFFFF, np.uint32))
        self.mask_bits = self._g(
            np.full((B, self.mask_words), 0xFFFFFFFF, np.uint32), slot_sh2)
        self._constrained = np.zeros((B,), bool)
        self._constr_dev = zeros((B,), jnp.int32, slot_sh)
        # device-resident grammar program (ops/constrain.GrammarTable):
        # gmask [G, mask_words] holds the packed allowed-token mask per
        # precomputed automaton state, gtrans [G, V] the successor state
        # per sampled token (-1 = the walk leaves the table). Each slot
        # carries a device FSM state: >= 0 device-table mode (its mask is
        # gmask[gstate], advanced ON DEVICE after sampling — no host
        # round-trip per token), -1 host-mask mode (mask_bits row, one
        # token per dispatch), -2 escaped (frozen until the host
        # re-installs a fresh mask via set_mask).
        self._gstates_cap = int(os.environ.get("TPU_GRAMMAR_STATES",
                                               "64"))
        self._grammar_device = os.environ.get(
            "TPU_GRAMMAR_DEVICE", "1").lower() not in ("0", "false")
        self._gmask_dev = self._gr(np.zeros(
            (self._gstates_cap, self.mask_words), np.uint32))
        self._gtrans_dev = self._gr(np.full(
            (self._gstates_cap, V), -1, np.int32))
        self._gstate = self._g(np.full((B,), -1, np.int32), slot_sh)
        self._gdev_mode = np.zeros((B,), bool)  # host mirror: gstate >= 0
        self._gtable_key: Any = None
        self.active = np.zeros((B,), bool)  # host-side mask
        self._active_dev = zeros((B,), jnp.int32, slot_sh)
        # per-slot effective penalty window (≤ W ring capacity)
        self._repeat_n = np.full((B,), W, np.int32)
        self._rln_dev = self._g(self._repeat_n, self._slot_sh)
        # host mirror of per-slot lengths — lets decode_n pick the static
        # attention bucket without a device sync
        self._host_lengths = np.zeros((B,), np.int64)
        # last observed wall-clock per dispatch kind (launch→tokens-on-
        # host), exported as gauges — gives dispatch-dominated regressions
        # a number. The BENCH_r05 623ms/spec-dispatch anomaly was exactly
        # this gauge catching mid-serving XLA compiles: spec executables
        # were only warmed for one attention bucket, so every bucket
        # crossing recompiled inside a timed dispatch. warm_buckets now
        # compiles every (k, bucket) spec program AND pre-seeds
        # dispatch_ms["spec"] from a no-op dispatch over the empty batch,
        # so the first real request pays neither compile nor first-run
        # setup.
        self.dispatch_ms = {"decode": 0.0, "admit": 0.0, "extend": 0.0,
                            "spec": 0.0}
        # mid-serving recompile detector: warm_buckets registers every
        # AOT-warmed executable signature; an executable-cache miss
        # outside warming is an XLA compile inside a timed dispatch —
        # counted per program kind (the BENCH_r05 incident as a counter)
        self._warming = False
        self._warmed_sigs: set = set()
        self.recompiles: Dict[str, int] = {
            "decode": 0, "admit": 0, "admit_many": 0, "extend": 0,
            "spec": 0}

        # per-slot sampling params, host mirror + device arrays
        self._opts: Dict[int, SlotOptions] = {}
        self.sp = jax.tree_util.tree_map(
            lambda a: self._g(np.asarray(a), slot_sh),
            sampling.SamplingParams.make(B))
        # mirostat surprise budget, re-seeded to 2*tau at admission; rides
        # the slot-state tuple through every decode/admit program
        self.mu = zeros((B,), jnp.float32, slot_sh)

        def _base_keys():
            return jax.vmap(jax.random.fold_in)(
                jnp.broadcast_to(jax.random.key(0), (B,)), jnp.arange(B))
        # typed key arrays can't ride make_array_from_callback — create
        # them as a (collective) jitted program with a global out_sharding
        self.keys = (jax.jit(_base_keys, out_shardings=slot_sh)()
                     if slot_sh is not None else _base_keys())

        # SP prefill shards the chunk over sp — every bucket must divide it
        # (both are powers of two, so raising the floor suffices; the last
        # bucket is S itself, asserted divisible above).
        self._buckets = prefill_buckets(
            S, max(ecfg.min_prefill_bucket, self.sp_size))
        self._compile_fns()

    def _g(self, x, sharding):
        """Host value → device array under ``sharding``. Single-process:
        plain device_put. Multi-controller slice: the mesh spans devices
        other processes own, so build a global array from the (identical)
        host value via make_array_from_callback."""
        if sharding is None:
            return jnp.asarray(x)
        if not self._multi:
            return jax.device_put(x, sharding)
        # lint: allow(host-sync-hot-path): staging host data for device_put — x is host-resident
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    def _gr(self, x):
        """Replicated upload (scalars, B=1 rows, packed masks)."""
        return self._g(x, self._repl_sh)

    def _dummy_key(self):
        """Replicated PRNG key for AOT lowering (typed key arrays can't
        ride make_array_from_callback; a jitted maker can)."""
        k = getattr(self, "_dummy_key_val", None)
        if k is None:
            if self._slot_sh is None:
                k = jax.random.key(0)
            else:
                k = jax.jit(jax.random.key, static_argnums=0,
                            out_shardings=self._repl_sh)(0)
            self._dummy_key_val = k
        return k

    @staticmethod
    def _fetch(x) -> np.ndarray:
        """Device→host for replicated values; a multi-controller array is
        not fully addressable, so read one local (identical) shard."""
        if getattr(x, "is_fully_addressable", True):
            return np.asarray(x)
        return np.asarray(x.addressable_data(0))

    @staticmethod
    def _quant_cache_sharding(cache_sh):
        """Sharding tree for the {"q", "s"} cache: q keeps the dense spec,
        s drops the trailing head_dim axis."""
        if cache_sh is None:
            return None
        spec = cache_sh.spec
        return {"q": cache_sh,
                "s": NamedSharding(cache_sh.mesh, P(*spec[:-1]))}

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _compile_fns(self):
        cfg = self.cfg
        cache_sh, slot_sh = self._cache_sh, self._slot_sh
        slot_sh2 = self._slot_sh2

        def pin(k_cache, v_cache, lengths, counts, last_tokens, pring, mu):
            """Pin slot-state outputs to their canonical shardings — the
            AOT-compiled decode executables require the state sharding to
            be IDENTICAL across admits (GSPMD would otherwise pick a fresh
            output sharding per program and the exec call would reject).
            Rank-2 state pins with the CLOSED spec (see __init__)."""
            if slot_sh is None:
                return (k_cache, v_cache, lengths, counts, last_tokens,
                        pring, mu)
            wsc = jax.lax.with_sharding_constraint
            return (wsc(k_cache, cache_sh), wsc(v_cache, cache_sh),
                    wsc(lengths, slot_sh), wsc(counts, slot_sh2),
                    wsc(last_tokens, slot_sh), wsc(pring, slot_sh2),
                    wsc(mu, slot_sh))

        if self.sp_size > 1:
            from ..parallel import long_context
            mesh = self.mesh
            prefill_impl = partial(long_context.prefill_chunk_sp, cfg=cfg,
                                   mesh=mesh)
            # the sp cache is sequence-sharded; bucketing would cut across
            # shards, so the sp path always attends its full local prefix
            step_impl = partial(long_context.forward_with_cache_sp, cfg=cfg,
                                mesh=mesh)
            self._bucketed_attn = False
        else:
            prefill_impl = partial(decoder.prefill_chunk, cfg=cfg,
                                   mesh=self.mesh)
            step_impl = partial(decoder.forward_with_cache, cfg=cfg,
                                mesh=self.mesh)
            self._bucketed_attn = True

        W = max(1, self.ecfg.repeat_last_n)

        def _sample_install(lengths, counts, last_tokens, pring, mu, logits,
                            ring_row, counts_row, slot, total, sp_row, key,
                            mask_row, cflag, rln):
            """Shared admission tail (fresh prefill AND prefix-cache
            extend): grammar-mask + sample the first token from ``logits``
            (the [V] row of the last valid prompt position — the caller
            indexes it), push it through the penalty window
            (``ring_row``/``counts_row`` cover the prompt), and install
            slot state. ``rln`` is the request's effective window (≤ W;
            0 = penalties see nothing). The slot's mirostat budget
            re-seeds to 2*tau here (llama.cpp's init) and absorbs the
            first token's surprise. Returns (tok, lengths, counts,
            last_tokens, pring, mu)."""
            last = logits
            allowed = unpack_mask(mask_row, cfg.vocab_size)
            last = jnp.where((cflag == 1) & ~allowed, sampling.NEG_INF, last)
            mu_row = 2.0 * sp_row.mirostat_tau
            # position-folded key, SAME stream as the decode steps (which
            # fold the pre-increment length: the token installed at index
            # total-1 would have used fold_in(key, total-1) had it been
            # decoded). Admission and decode drawing from one keystream is
            # what makes a seeded stream resume bit-identically after a
            # preemption or a restart replay — the re-prefill's first
            # sample lands on exactly the fold the uninterrupted decode
            # would have used at that position.
            tok, mu_row = sampling.sample(
                last[None], counts_row[None], sp_row,
                jax.random.fold_in(key, total - 1)[None], mu_row)
            tok = tok[0]
            mu = mu.at[slot].set(mu_row[0])
            rmod = jnp.maximum(rln, 1)
            evict = ring_row[total % rmod]
            counts_row = counts_row.at[evict].add(-1, mode="drop")
            tok_entry = jnp.where(rln > 0, tok, jnp.int32(cfg.vocab_size))
            ring_row = ring_row.at[total % rmod].set(tok_entry)
            counts_row = counts_row.at[tok_entry].add(1, mode="drop")
            pring = pring.at[slot].set(ring_row)
            lengths = lengths.at[slot].set(total)
            counts = counts.at[slot].set(counts_row)
            last_tokens = last_tokens.at[slot].set(tok)
            return tok, lengths, counts, last_tokens, pring, mu

        def _insert_prefilled(k_cache, v_cache, lengths, counts,
                              last_tokens, pring, mu, logits, ks, vs,
                              tokens, slot, n_valid, sp_row, key, mask_row,
                              cflag, rln, table_row=None):
            """Fresh-prefill admission: build the penalty window from the
            LAST ``rln`` prompt tokens of the device-side chunk (image pad
            positions carry id == vocab_size, which the scatter-add drops —
            image tokens never enter the counts), sample, and install
            chunk K/V + slot state."""
            last = jax.lax.dynamic_index_in_dim(
                logits[0], n_valid - 1, axis=0, keepdims=False)
            # ring of the last rln prompt tokens: absolute positions
            # n_valid-rln .. n_valid-1 land in slots pos % rln (each slot
            # exactly once — no scatter duplicates); ring capacity is the
            # static W, entries >= rln stay sentinel
            T = tokens.shape[1]
            rmod = jnp.maximum(rln, 1)
            idx = jnp.arange(W, dtype=jnp.int32)
            pos = n_valid - rln + idx
            valid = (idx < rln) & (pos >= 0)
            vals = jnp.where(
                valid, tokens[0][jnp.clip(pos, 0, T - 1)],
                jnp.int32(cfg.vocab_size))
            slot_idx = jnp.where(valid, pos % rmod, jnp.int32(W))
            ring_row = jnp.full((W,), cfg.vocab_size, jnp.int32
                                ).at[slot_idx].set(vals, mode="drop")
            counts_row = jnp.zeros((cfg.vocab_size,), jnp.int32
                                   ).at[vals].add(1, mode="drop")
            (tok, lengths, counts, last_tokens, pring,
             mu) = _sample_install(
                lengths, counts, last_tokens, pring, mu, last, ring_row,
                counts_row, slot, n_valid, sp_row, key, mask_row, cflag,
                rln)
            if self.paged and self._paged_dp > 1:
                k_cache, v_cache = decoder.paged_insert_dp(
                    cfg, k_cache, v_cache, ks, vs, table_row, n_valid,
                    self.mesh)
            elif self.paged:
                k_cache, v_cache = decoder.paged_insert(
                    cfg, k_cache, v_cache, ks, vs, table_row, n_valid)
            elif self.quant_cache:
                from ..ops.quant_cache import quantize_kv
                kq, ksc = quantize_kv(ks)          # [L,1,KvH,T,hd]
                vq, vsc = quantize_kv(vs)
                dus = jax.lax.dynamic_update_slice
                k_cache = {"q": dus(k_cache["q"], kq, (0, slot, 0, 0, 0)),
                           "s": dus(k_cache["s"], ksc, (0, slot, 0, 0))}
                v_cache = {"q": dus(v_cache["q"], vq, (0, slot, 0, 0, 0)),
                           "s": dus(v_cache["s"], vsc, (0, slot, 0, 0))}
            else:
                k_cache = jax.lax.dynamic_update_slice(
                    k_cache, ks.astype(k_cache.dtype), (0, slot, 0, 0, 0))
                v_cache = jax.lax.dynamic_update_slice(
                    v_cache, vs.astype(v_cache.dtype), (0, slot, 0, 0, 0))
            return (tok, *pin(k_cache, v_cache, lengths, counts,
                              last_tokens, pring, mu))

        def _admit(params, k_cache, v_cache, lengths, counts, last_tokens,
                   pring, mu, tokens, slot, n_valid, sp_row, key, mask_row,
                   cflag, rln, table_row=None):
            """Prefill a padded B=1 chunk AND insert it into the slot state
            — one device program, one host round-trip per admission.
            ``table_row`` [NBLK] — the slot's block table (paged mode)."""
            logits, ks, vs = prefill_impl(params, tokens=tokens)
            return _insert_prefilled(k_cache, v_cache, lengths, counts,
                                     last_tokens, pring, mu, logits, ks, vs,
                                     tokens, slot, n_valid, sp_row, key,
                                     mask_row, cflag, rln, table_row)

        def _make_admit_many(m):
            """Batched fresh admission: prefill ``m`` same-bucket prompts
            in ONE device program and insert each into its slot. The
            prefill is batch-generic (causal masking makes each row's
            logits independent of the others), and the per-slot inserts
            unroll statically — the program is keyed by (m, bucket)."""
            def _admit_many(params, k_cache, v_cache, lengths, counts,
                            last_tokens, pring, mu, tokens, slots,
                            n_valids, sp_rows, keys_m, mask_row, rlns,
                            table_rows=None):
                logits, ks, vs = prefill_impl(params, tokens=tokens)
                toks = []
                for i in range(m):
                    (tok, k_cache, v_cache, lengths, counts, last_tokens,
                     pring, mu) = _insert_prefilled(
                        k_cache, v_cache, lengths, counts, last_tokens,
                        pring, mu, logits[i:i + 1], ks[:, i:i + 1],
                        vs[:, i:i + 1], tokens[i:i + 1], slots[i],
                        n_valids[i],
                        jax.tree_util.tree_map(lambda a: a[i:i + 1],
                                               sp_rows),
                        keys_m[i], mask_row, jnp.int32(0), rlns[i],
                        None if table_rows is None else table_rows[i])
                    toks.append(tok)
                return (jnp.stack(toks), k_cache, v_cache, lengths,
                        counts, last_tokens, pring, mu)
            return _admit_many

        def _admit_embeds(params, k_cache, v_cache, lengths, counts,
                          last_tokens, pring, mu, tokens, embeds, slot,
                          n_valid, sp_row, key, mask_row, cflag, rln,
                          table_row=None):
            """Multimodal admission: like _admit but prefilling from a
            precomputed [1, T, D] embedding sequence (image tokens spliced
            into text embeddings); ``tokens`` feeds the penalty counts with
            id == vocab_size at image positions (dropped by the scatter).
            The embedding lookup never sees ``tokens``."""
            logits, ks, vs = prefill_impl(params, tokens=tokens,
                                          inputs_embeds=embeds)
            return _insert_prefilled(k_cache, v_cache, lengths, counts,
                                     last_tokens, pring, mu, logits, ks, vs,
                                     tokens, slot, n_valid, sp_row, key,
                                     mask_row, cflag, rln, table_row)

        def _decode_body(params, k_cache, v_cache, lengths, counts,
                         last_tokens, pring, mu, sp, keys, active,
                         mask_bits, constrained, rln, gstate, gmask,
                         gtrans, attn_len=None, tables=None):
            # escaped slots (gstate == -2) freeze in place: the host has
            # to re-derive their mask before they may advance again
            active = active * (gstate != -2).astype(active.dtype)
            if self.paged:
                ps = self.ecfg.page_size
                nblk = -(-(attn_len or self.max_seq) // ps)
                logits, k_cache, v_cache = decoder.forward_with_cache_paged(
                    params, cfg, last_tokens[:, None], k_cache, v_cache,
                    tables, lengths, nblk, mesh=self.mesh)
            else:
                kw = {"attn_len": attn_len} if (attn_len is not None
                                                and self._bucketed_attn) \
                    else {}
                logits, k_cache, v_cache = step_impl(
                    params, tokens=last_tokens[:, None], k_cache=k_cache,
                    v_cache=v_cache, lengths=lengths, **kw)
            step_keys = jax.vmap(jax.random.fold_in)(keys, lengths)
            last = logits[:, 0]
            # device-table slots read their mask straight off the
            # precomputed grammar table (host rows for everyone else)
            gdev = gstate >= 0
            gi = jnp.clip(gstate, 0, gmask.shape[0] - 1)
            eff_bits = jnp.where(gdev[:, None], gmask[gi], mask_bits)
            allowed = unpack_mask(eff_bits, cfg.vocab_size)
            last = jnp.where((constrained == 1)[:, None] & ~allowed,
                             sampling.NEG_INF, last)
            toks, mu_new = sampling.sample(last, counts, sp, step_keys,
                                           mu)
            # advance the device automaton by the sampled token; a -1
            # transition (walk left the precomputed table) escapes to -2
            ns = gtrans[gi, toks]
            ns = jnp.where(ns < 0, jnp.int32(-2), ns)
            gstate = jnp.where(gdev & (active == 1), ns, gstate)
            mu = jnp.where(active == 1, mu_new, mu)
            B = toks.shape[0]
            bi = jnp.arange(B)
            # penalty window: the NEW token's absolute position is
            # lengths + 1 (last_tokens sits at lengths); evict whatever
            # occupied that ring slot rln[i] tokens ago, then admit the
            # new token. Per-slot rln picks each request's effective
            # window inside the static-W ring via the modulus — inactive
            # or rln==0 slots write the OOB sentinel.
            rmod = jnp.maximum(rln, 1)
            slot_pos = (lengths + 1) % rmod
            evict = pring[bi, slot_pos]
            evict = jnp.where(active == 1, evict, jnp.int32(cfg.vocab_size))
            live = (active == 1) & (rln > 0)
            new = jnp.where(live, toks, jnp.int32(cfg.vocab_size))
            counts = counts.at[bi, evict].add(-1, mode="drop")
            counts = counts.at[bi, new].add(1, mode="drop")
            pring = jnp.where(live[:, None],
                              pring.at[bi, slot_pos].set(toks), pring)
            lengths = lengths + active
            last_tokens = jnp.where(active == 1, toks, last_tokens)
            if slot_sh is not None:
                gstate = jax.lax.with_sharding_constraint(gstate, slot_sh)
            return (toks, *pin(k_cache, v_cache, lengths, counts,
                               last_tokens, pring, mu), gstate)

        def _decode(params, k_cache, v_cache, lengths, counts, last_tokens,
                    pring, mu, sp, keys, active, mask_bits, constrained,
                    rln, gstate, gmask, gtrans, tables=None):
            (toks, k_cache, v_cache, lengths, counts, last_tokens,
             pring, mu, gstate) = _decode_body(
                 params, k_cache, v_cache, lengths, counts, last_tokens,
                 pring, mu, sp, keys, active, mask_bits, constrained, rln,
                 gstate, gmask, gtrans, tables=tables)
            return (toks, k_cache, v_cache, lengths, counts, last_tokens,
                    pring, mu, keys, gstate)

        def _decode_n(params, k_cache, v_cache, lengths, counts, last_tokens,
                      pring, mu, sp, keys, active, mask_bits, constrained,
                      rln, gstate, gmask, gtrans, n, attn_len, tables=None,
                      budgets=None):
            """n decode steps as ONE device program (lax.scan) — a single
            dispatch + host sync per n tokens per slot. ``attn_len`` is the
            static attended-cache prefix (decode traffic scales with it,
            not with max_seq_len; in paged mode it only bounds the kernel
            grid — page DMAs clamp to each slot's own length). ``tables``
            [B, NBLK] (paged): the host grows them to cover lengths + n
            before dispatch.

            ``budgets`` [B] int32 — per-slot step budget: a slot freezes
            (no length advance, no state change) once the step index
            reaches its budget. HOST-masked grammar slots get budget 1 —
            they need a fresh host-side PDA mask per token — while the
            rest of the batch keeps the full chunk (round-1 weak #5: one
            format:"json" request used to collapse everyone to n=1).
            Device-table grammar slots (gstate >= 0) keep the full chunk:
            their mask refreshes on device from gmask/gtrans."""
            def step(carry, t):
                (k_cache, v_cache, lengths, counts, last_tokens,
                 pring, mu, gstate) = carry
                act = active if budgets is None else active * (t < budgets)
                (toks, k_cache, v_cache, lengths, counts, last_tokens,
                 pring, mu, gstate) = _decode_body(
                     params, k_cache, v_cache, lengths, counts,
                     last_tokens, pring, mu, sp, keys, act, mask_bits,
                     constrained, rln, gstate, gmask, gtrans,
                     attn_len=attn_len, tables=tables)
                return (k_cache, v_cache, lengths, counts, last_tokens,
                        pring, mu, gstate), toks

            carry = (k_cache, v_cache, lengths, counts, last_tokens, pring,
                     mu, gstate)
            carry, toks_n = jax.lax.scan(
                step, carry, jnp.arange(n, dtype=jnp.int32))
            (k_cache, v_cache, lengths, counts, last_tokens, pring,
             mu, gstate) = carry
            return (toks_n, k_cache, v_cache, lengths, counts, last_tokens,
                    pring, mu, keys, gstate)

        def _spec_verify(params, k_cache, v_cache, lengths, counts,
                         last_tokens, pring, mu, sp, keys, active,
                         mask_bits, constrained, rln, gstate, gmask,
                         gtrans, is_greedy, drafts, attn_len,
                         tables=None):
            """Speculative verify step (one dispatch): run the cached
            forward over [last_token, draft_0..draft_{k-1}] per slot,
            greedy-accept the longest matching draft prefix (greedy
            slots only — temperature-0 acceptance is exact), and emit
            accepted drafts + one model token per slot. Rejected
            positions\' K/V are garbage above the advanced length and are
            never attended; the next write overwrites them. Non-greedy
            slots sample their single token exactly like _decode_body, so
            a k=0-accepting batch degrades to one normal decode step."""
            B, kk = drafts.shape
            V = cfg.vocab_size
            # escaped device-grammar slots freeze exactly as in decode
            active = active * (gstate != -2).astype(active.dtype)
            tokens_in = jnp.concatenate([last_tokens[:, None], drafts], 1)
            kw = {"attn_len": attn_len} if self._bucketed_attn else {}
            if self.paged:
                ps = self.ecfg.page_size
                nblk = -(-attn_len // ps)
                logits, k_cache, v_cache = \
                    decoder.forward_with_cache_paged(
                        params, cfg, tokens_in, k_cache, v_cache,
                        tables, lengths, nblk, mesh=self.mesh)
            else:
                logits, k_cache, v_cache = step_impl(
                    params, tokens=tokens_in, k_cache=k_cache,
                    v_cache=v_cache, lengths=lengths, **kw)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            ok = (active == 1) & (is_greedy == 1)
            bi = jnp.arange(B)
            step_keys = jax.vmap(jax.random.fold_in)(keys, lengths)
            l0 = logits[:, 0]
            gdev = gstate >= 0
            gi = jnp.clip(gstate, 0, gmask.shape[0] - 1)
            eff_bits = jnp.where(gdev[:, None], gmask[gi], mask_bits)
            allowed = unpack_mask(eff_bits, V)
            l0 = jnp.where((constrained == 1)[:, None] & ~allowed,
                           sampling.NEG_INF, l0)
            sampled0, mu_new = sampling.sample(l0, counts, sp, step_keys,
                                               mu)
            # greedy (accepting) slots never run mirostat; only the
            # sampled path's slots absorb the surprise update
            mu = jnp.where((active == 1) & ~ok, mu_new, mu)
            # vectorized accept/rollback (ops/sampling.spec_accept):
            # accepted draft prefix + bonus token per row, sentinel
            # padding at and beyond the first mismatch
            n_acc, out = sampling.spec_accept(drafts, greedy, ok,
                                              sampled0, V)
            out = jnp.where((active == 1)[:, None], out, jnp.int32(V))
            # constrained slots are spec-ineligible (_spec_flags), so they
            # emit exactly out[:, 0] == sampled0 — advance the device
            # automaton by that single token
            tok0 = out[:, 0]
            ns = gtrans[gi, jnp.clip(tok0, 0, V - 1)]
            ns = jnp.where(ns < 0, jnp.int32(-2), ns)
            gstate = jnp.where(gdev & (active == 1) & (tok0 < V),
                               ns, gstate)
            if slot_sh is not None:
                gstate = jax.lax.with_sharding_constraint(gstate, slot_sh)

            def push(carry, t):
                lengths, counts, last_tokens, pring = carry
                tok_t = out[:, t]
                act_t = ((active == 1) & (t <= n_acc)
                         & (tok_t < V)).astype(jnp.int32)
                rmod = jnp.maximum(rln, 1)
                slot_pos = (lengths + 1) % rmod
                evict = pring[bi, slot_pos]
                evict = jnp.where(act_t == 1, evict, jnp.int32(V))
                live = (act_t == 1) & (rln > 0)
                new = jnp.where(live, tok_t, jnp.int32(V))
                counts2 = counts.at[bi, evict].add(-1, mode="drop")
                counts2 = counts2.at[bi, new].add(1, mode="drop")
                pring2 = jnp.where(live[:, None],
                                   pring.at[bi, slot_pos].set(tok_t),
                                   pring)
                lengths2 = lengths + act_t
                last2 = jnp.where(act_t == 1, tok_t, last_tokens)
                return (lengths2, counts2, last2, pring2), None

            (lengths, counts, last_tokens, pring), _ = jax.lax.scan(
                push, (lengths, counts, last_tokens, pring),
                jnp.arange(kk + 1, dtype=jnp.int32))
            return (out, *pin(k_cache, v_cache, lengths, counts,
                              last_tokens, pring, mu), keys, gstate)

        def _make_extend_paged(A):
            """Paged prefix-cache continuation, attending only the first
            ``A`` positions (the live-prefix bucket): the reused prefix
            stays in its pages untouched; the tail prefills through the
            paged forward (B=1 view, positions offset by ``start``),
            writing into pages from ``table_row`` — no cache
            slice/unslice copies, and quantized pools work the same (the
            paged forward quantizes fresh K/V per layer). Tail
            bucket-padding beyond n_new lands on unowned table entries,
            i.e. the trash page. On dp meshes the table argument is the
            [dp, NBLK] owner-real/others-trash rows plus the owning
            shard's index, and the forward is the dp-manual twin
            (decoder.paged_extend_dp)."""
            nblk_a = -(-A // self.ecfg.page_size)

            def _extend_paged(params, k_cache, v_cache, lengths, counts,
                              last_tokens, pring, mu, tokens, ring_row,
                              counts_row, slot, start, n_new, table_row,
                              sp_row, key, mask_row, cflag, rln):
                logits, k_cache, v_cache = \
                    decoder.forward_with_cache_paged(
                        params, cfg, tokens, k_cache, v_cache,
                        table_row[None], start[None], nblk_a,
                        mesh=self.mesh)
                last = jax.lax.dynamic_index_in_dim(
                    logits[0], n_new - 1, axis=0, keepdims=False)
                (tok, lengths, counts, last_tokens, pring,
                 mu) = _sample_install(
                    lengths, counts, last_tokens, pring, mu, last,
                    ring_row, counts_row, slot, start + n_new, sp_row, key,
                    mask_row, cflag, rln)
                return (tok, *pin(k_cache, v_cache, lengths, counts,
                                  last_tokens, pring, mu))

            def _extend_paged_dp(params, k_cache, v_cache, lengths,
                                 counts, last_tokens, pring, mu, tokens,
                                 ring_row, counts_row, slot, start, n_new,
                                 table_rows, owner, sp_row, key, mask_row,
                                 cflag, rln):
                logits, k_cache, v_cache = decoder.paged_extend_dp(
                    params, cfg, tokens, k_cache, v_cache, table_rows,
                    start[None], nblk_a, owner, self.mesh)
                last = jax.lax.dynamic_index_in_dim(
                    logits[0], n_new - 1, axis=0, keepdims=False)
                (tok, lengths, counts, last_tokens, pring,
                 mu) = _sample_install(
                    lengths, counts, last_tokens, pring, mu, last,
                    ring_row, counts_row, slot, start + n_new, sp_row, key,
                    mask_row, cflag, rln)
                return (tok, *pin(k_cache, v_cache, lengths, counts,
                                  last_tokens, pring, mu))
            return (_extend_paged_dp if self._paged_dp > 1
                    else _extend_paged)

        def _make_extend_sp(A):
            """sp twin of ``_make_extend``: the slot's cache stays
            sequence-sharded end to end. The tail chunk's compute is
            replicated across sp — ``forward_with_cache_sp`` is built for
            T>1 continuation (per-query absolute positions mask the chunk
            causally against the cache AND itself; ``sp_cache_write``
            scatters each fresh key to its owning shard) — so the only
            sp-specific engine work is skipping the attended-prefix
            bucketing: the sp path always attends its full local chunk,
            and ``extend()`` passes A = max_seq (closing round-2 weak #5:
            sp caches used to forfeit prefix caching entirely)."""
            from ..parallel.long_context import forward_with_cache_sp
            return _make_extend(A, forward=forward_with_cache_sp)

        def _make_extend(A, forward=None):
            """Prefix-cache continuation: prefill only the tail of a
            prompt whose first ``start`` tokens are already in ``slot``'s
            KV cache (a parked conversation), slicing AND attending only
            the first ``A`` cache positions — the live-prefix bucket
            (programs are keyed by (tail, attn) bucket pairs, so the
            admission's HBM traffic scales with the conversation, not
            max_seq_len). ``ring_row``/``counts_row`` are the penalty
            window over the FULL continuation prompt, prebuilt on the
            host (the parked window may belong to a divergent suffix).
            sp caches extend through ``_make_extend_sp`` (same body,
            ``forward`` swapped, A = max_seq so the slice is the whole
            sequence axis); int8 caches slice both the entries and their
            scales — the cached forward quantizes the tail in place
            (round-1 weak #4: int8 and prefix caching used to be
            mutually exclusive)."""
            fwd = forward if forward is not None \
                else decoder.forward_with_cache

            def _extend(params, k_cache, v_cache, lengths, counts,
                        last_tokens, pring, mu, tokens, ring_row,
                        counts_row, slot, start, n_new, sp_row, key,
                        mask_row, cflag, rln):
                dsl = jax.lax.dynamic_slice
                dus = jax.lax.dynamic_update_slice
                if self.quant_cache:
                    Lq, _, KvH, _S, hd = k_cache["q"].shape
                    def slice5(c):
                        return {"q": dsl(c["q"], (0, slot, 0, 0, 0),
                                         (Lq, 1, KvH, A, hd)),
                                "s": dsl(c["s"], (0, slot, 0, 0),
                                         (Lq, 1, KvH, A))}
                    def write5(c, cs):
                        return {"q": dus(c["q"], cs["q"],
                                         (0, slot, 0, 0, 0)),
                                "s": dus(c["s"], cs["s"], (0, slot, 0, 0))}
                else:
                    Lq, _, KvH, _S, hd = k_cache.shape
                    def slice5(c):
                        return dsl(c, (0, slot, 0, 0, 0),
                                   (Lq, 1, KvH, A, hd))
                    def write5(c, cs):
                        return dus(c, cs, (0, slot, 0, 0, 0))
                kc_s, vc_s = slice5(k_cache), slice5(v_cache)
                logits, kc_s, vc_s = fwd(
                    params, cfg, tokens, kc_s, vc_s, start[None],
                    mesh=self.mesh)
                k_cache = write5(k_cache, kc_s)
                v_cache = write5(v_cache, vc_s)
                last = jax.lax.dynamic_index_in_dim(
                    logits[0], n_new - 1, axis=0, keepdims=False)
                (tok, lengths, counts, last_tokens, pring,
                 mu) = _sample_install(
                    lengths, counts, last_tokens, pring, mu, last,
                    ring_row, counts_row, slot, start + n_new, sp_row, key,
                    mask_row, cflag, rln)
                return (tok, *pin(k_cache, v_cache, lengths, counts,
                                  last_tokens, pring, mu))
            return _extend

        def _release(lengths, counts, last_tokens, pring, mu, slot):
            lengths = lengths.at[slot].set(0)
            counts = counts.at[slot].set(0)
            last_tokens = last_tokens.at[slot].set(0)
            pring = pring.at[slot].set(cfg.vocab_size)
            mu = mu.at[slot].set(0.0)
            return lengths, counts, last_tokens, pring, mu

        def _set_mask(mask_bits, constr, gstate, slot, row, flag, gval):
            mask_bits = mask_bits.at[slot].set(row)
            constr = constr.at[slot].set(flag)
            gstate = gstate.at[slot].set(gval)
            if slot_sh is not None:
                wsc = jax.lax.with_sharding_constraint
                mask_bits = wsc(mask_bits, slot_sh2)
                constr = wsc(constr, slot_sh)
                gstate = wsc(gstate, slot_sh)
            return mask_bits, constr, gstate

        # Explicit out_shardings on every state-returning program: wsc
        # inside the trace guides internals, but the JIT BOUNDARY sharding
        # of unannotated outputs is GSPMD's choice — on a dp×tp mesh it
        # happily re-shards counts [B, V] over tp in one program, and the
        # AOT execs (compiled against the canonical state shardings) then
        # reject their own prior outputs.
        state_outs = None
        if slot_sh is not None:
            state_outs = (cache_sh, cache_sh, slot_sh, slot_sh2, slot_sh,
                          slot_sh2, slot_sh)

        def _jit(fn, donate, static=None, outs=None):
            kw = {"donate_argnums": donate}
            if static is not None:
                kw["static_argnums"] = static
            if outs is not None and slot_sh is not None:
                kw["out_shardings"] = outs
            return jax.jit(fn, **kw)

        if state_outs:
            # every output gets a CONCRETE sharding (a None leaf in an
            # out_shardings tree reads as an empty pytree node, not
            # "unspecified"): sampled tokens ride the batch axis, the
            # first admission token is a replicated scalar
            b_ax = slot_sh.spec[0] if slot_sh.spec else None
            repl_sh = NamedSharding(self.mesh, P())
            toksn_sh = NamedSharding(self.mesh, P(None, b_ax))
            tok_outs = (repl_sh,) + state_outs
            dec_outs = (slot_sh,) + state_outs + (slot_sh, slot_sh)
            decn_outs = (toksn_sh,) + state_outs + (slot_sh, slot_sh)
        else:
            tok_outs = dec_outs = decn_outs = None
        self._admit_fn = _jit(_admit, (1, 2, 3, 4, 5, 6, 7),
                              outs=tok_outs)
        self._admit_embeds_fn = _jit(_admit_embeds, (1, 2, 3, 4, 5, 6, 7),
                                     outs=tok_outs)
        self._admit_execs: Dict[int, Any] = {}
        if state_outs:
            toksm_sh = repl_sh  # stacked replicated scalars stay replicated
            many_outs = (toksm_sh,) + state_outs
        else:
            many_outs = None
        self._admit_many_make = lambda m: _jit(
            _make_admit_many(m), (1, 2, 3, 4, 5, 6, 7), outs=many_outs)
        self._admit_many_jits: Dict[int, Any] = {}
        self._admit_many_execs: Dict[Any, Any] = {}
        make_ext = (_make_extend_paged if self.paged
                    else _make_extend_sp if self.sp_size > 1
                    else _make_extend)
        self._extend_make = lambda A: _jit(make_ext(A),
                                           (1, 2, 3, 4, 5, 6, 7),
                                           outs=tok_outs)
        self._extend_jits: Dict[int, Any] = {}
        self._extend_execs: Dict[Any, Any] = {}
        self._decode_fn = _jit(_decode, (1, 2, 3, 4, 5, 6, 7, 9, 14),
                               outs=dec_outs)
        self._decode_n_fn = _jit(_decode_n, (1, 2, 3, 4, 5, 6, 7, 9, 14),
                                 static=(17, 18), outs=decn_outs)
        spec_outs = (((slot_sh2,) + state_outs + (slot_sh, slot_sh))
                     if state_outs else None)
        self._spec_fn = _jit(_spec_verify, (1, 2, 3, 4, 5, 6, 7, 9, 14),
                             static=(19,), outs=spec_outs)
        self._spec_execs: Dict[Any, Any] = {}
        self._release_fn = _jit(
            _release, (0, 1, 2, 3, 4),
            outs=((slot_sh, slot_sh2, slot_sh, slot_sh2, slot_sh)
                  if slot_sh else None))

        if self.paged:
            def _copy_page(k_cache, v_cache, src, dst):
                """Copy-on-write: physical page ``src`` → ``dst`` across
                all layers. The page axis is axis 1 in both the code
                pools and the quant scale arrays, so one tree_map'd
                slice covers the plain and {"q","s"} layouts."""
                def cp(c):
                    page = jax.lax.dynamic_slice_in_dim(c, src, 1, axis=1)
                    return jax.lax.dynamic_update_slice_in_dim(
                        c, page, dst, axis=1)
                k_cache = jax.tree_util.tree_map(cp, k_cache)
                v_cache = jax.tree_util.tree_map(cp, v_cache)
                if slot_sh is not None:
                    wsc = jax.lax.with_sharding_constraint
                    k_cache = wsc(k_cache, cache_sh)
                    v_cache = wsc(v_cache, cache_sh)
                return k_cache, v_cache
            self._copy_page_fn = _jit(_copy_page, (0, 1),
                                      outs=(cache_sh, cache_sh))

            # tiered KV cache (ISSUE 18): gather slices one page out of
            # the pool for the host-tier spill (REPLICATED output, so on
            # a multi-host mesh every host can device_get identical
            # bytes); upload writes a spilled page's bytes back into a
            # freshly grown page — an async enqueue that overlaps the
            # tail prefill, never a host sync.
            page_repl = (tuple(
                jax.tree_util.tree_map(lambda _s: self._repl_sh, cache_sh)
                for _ in range(2)) if slot_sh is not None else None)

            def _gather_page(k_cache, v_cache, src):
                def g(c):
                    return jax.lax.dynamic_slice_in_dim(c, src, 1, axis=1)
                return (jax.tree_util.tree_map(g, k_cache),
                        jax.tree_util.tree_map(g, v_cache))
            self._gather_page_fn = _jit(_gather_page, (), outs=page_repl)

            def _upload_page(k_cache, v_cache, kp, vp, dst):
                def up(c, page):
                    return jax.lax.dynamic_update_slice_in_dim(
                        c, page, dst, axis=1)
                k_cache = jax.tree_util.tree_map(up, k_cache, kp)
                v_cache = jax.tree_util.tree_map(up, v_cache, vp)
                if slot_sh is not None:
                    wsc = jax.lax.with_sharding_constraint
                    k_cache = wsc(k_cache, cache_sh)
                    v_cache = wsc(v_cache, cache_sh)
                return k_cache, v_cache
            self._upload_page_fn = _jit(_upload_page, (0, 1),
                                        outs=(cache_sh, cache_sh))

        def _install_key(keys, slot, seed):
            k = jax.random.key(seed)
            return keys.at[slot].set(k), k
        self._install_key_fn = _jit(
            _install_key, (0,),
            outs=(slot_sh, self._repl_sh) if slot_sh is not None else None)
        self._set_mask_fn = _jit(
            _set_mask, (0, 1, 2),
            outs=(slot_sh2, slot_sh, slot_sh) if slot_sh else None)
        # AOT-compiled decode_n executables keyed by (n, attn_bucket) — a
        # bucket crossing must swap programs, never recompile mid-serving
        self._decode_execs: Dict[Any, Any] = {}

    # ------------------------------------------------------------------
    # host API
    # ------------------------------------------------------------------
    def free_slots(self):
        return [i for i in range(self.n_slots) if not self.active[i]]

    def bucket_for(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds max_seq_len "
                         f"{self.max_seq}")

    def _sp_row(self, o: SlotOptions):
        g = self._gr
        return sampling.SamplingParams(
            temperature=g(np.array([o.temperature], np.float32)),
            top_k=g(np.array([o.top_k], np.int32)),
            top_p=g(np.array([o.top_p], np.float32)),
            min_p=g(np.array([o.min_p], np.float32)),
            typical_p=g(np.array([o.typical_p], np.float32)),
            repeat_penalty=g(np.array([o.repeat_penalty], np.float32)),
            presence_penalty=g(np.array([o.presence_penalty], np.float32)),
            frequency_penalty=g(np.array([o.frequency_penalty],
                                         np.float32)),
            mirostat=g(np.array([o.mirostat], np.int32)),
            mirostat_tau=g(np.array([o.mirostat_tau], np.float32)),
            mirostat_eta=g(np.array([o.mirostat_eta], np.float32)))

    def _rebuild_sp(self):
        opts = [self._opts.get(i, SlotOptions()) for i in range(self.n_slots)]
        g = lambda a: self._g(a, self._slot_sh)  # noqa: E731
        self.sp = sampling.SamplingParams(
            temperature=g(np.array([o.temperature for o in opts],
                                   np.float32)),
            top_k=g(np.array([o.top_k for o in opts], np.int32)),
            top_p=g(np.array([o.top_p for o in opts], np.float32)),
            min_p=g(np.array([o.min_p for o in opts], np.float32)),
            typical_p=g(np.array([o.typical_p for o in opts], np.float32)),
            repeat_penalty=g(np.array(
                [o.repeat_penalty for o in opts], np.float32)),
            presence_penalty=g(np.array(
                [o.presence_penalty for o in opts], np.float32)),
            frequency_penalty=g(np.array(
                [o.frequency_penalty for o in opts], np.float32)),
            mirostat=g(np.array([o.mirostat for o in opts], np.int32)),
            mirostat_tau=g(np.array(
                [o.mirostat_tau for o in opts], np.float32)),
            mirostat_eta=g(np.array(
                [o.mirostat_eta for o in opts], np.float32)))

    def _prep_slot(self, slot: int, opts: SlotOptions, seq_len: int,
                   mask_row: Optional[np.ndarray]):
        """Shared admission setup: install the slot PRNG key, resolve the
        optional grammar mask. Returns (key, mask_row_dev, cflag)."""
        # deterministic mix (NOT hash(): Python salts it per process, and
        # multi-host followers must derive byte-identical keys or the
        # replicated sampling inputs diverge across the SPMD world)
        seed = (opts.seed if opts.seed >= 0
                else (slot * 1000003 + seq_len * 7919 + 12345)
                & 0x7FFFFFFF)
        self.keys, key = self._install_key_fn(
            self.keys, self._gr(np.int32(slot)), self._gr(np.int32(seed)))
        if mask_row is not None:
            return key, self._gr(self._pad_mask_row(mask_row)), \
                self._gr(np.int32(1))
        return key, self._mask_ones, self._gr(np.int32(0))

    def _resolve_rln(self, opts: SlotOptions) -> int:
        """Request window → effective window: -1 = engine max, clamp to
        the static ring capacity W."""
        W = max(1, self.ecfg.repeat_last_n)
        r = opts.repeat_last_n
        return W if r < 0 else min(r, W)

    def _commit_slot(self, slot: int, n_total: int, opts: SlotOptions):
        """Shared admission tail: mark the slot live and rebuild batched
        sampling params."""
        self.active[slot] = True
        self._host_lengths[slot] = n_total
        self._opts[slot] = opts
        self._repeat_n[slot] = self._resolve_rln(opts)
        self._rln_dev = self._g(self._repeat_n, self._slot_sh)
        if self.paged:
            self._admit_seq += 1
            self._admit_order[slot] = self._admit_seq
        self._rebuild_sp()
        self._active_dev = self._g(self.active.astype(np.int32),
                                   self._slot_sh)

    def admit(self, slot: int, prompt: np.ndarray,
              opts: SlotOptions = SlotOptions(),
              embeds: Optional[np.ndarray] = None,
              mask_row: Optional[np.ndarray] = None) -> int:
        """Prefill ``prompt`` into ``slot``; returns the first sampled token.

        ``embeds`` [n, D] — optional precomputed embedding sequence for the
        prompt (multimodal); must match len(prompt), where image positions
        in ``prompt`` carry a pad token id for the penalty counts.

        ``mask_row`` [mask_words] uint32 — optional packed allowed-token
        mask applied to the FIRST sampled token (grammar-constrained
        requests); the caller then keeps per-step masks flowing via
        ``set_mask``.
        """
        FAULTS.check("engine.admit")
        t0 = time.perf_counter()
        assert not self.active[slot], f"slot {slot} busy"
        n = int(prompt.shape[0])
        if n >= self.max_seq:
            raise ValueError(f"prompt too long: {n} >= {self.max_seq}")
        bucket = self.bucket_for(n)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n] = prompt
        key, mrow, cflag = self._prep_slot(slot, opts, n, mask_row)
        table_row = self._grow_for_admit(slot, n)
        if embeds is not None:
            assert embeds.shape[0] == n, "embeds must cover the prompt"
            emb = np.zeros((1, bucket, embeds.shape[1]), np.float32)
            emb[0, :n] = embeds
            (tok, self.k_cache, self.v_cache, self.lengths, self.counts,
             self.last_tokens, self.pring,
             self.mu) = self._admit_embeds_fn(
                self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                self._gr(tokens), self._gr(emb), self._gr(np.int32(slot)),
                self._gr(np.int32(n)), self._sp_row(opts), key, mrow,
                cflag, self._gr(np.int32(self._resolve_rln(opts))),
                table_row)
        else:
            (tok, self.k_cache, self.v_cache, self.lengths, self.counts,
             self.last_tokens, self.pring,
             self.mu) = self._admit_exec(bucket)(
                self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                self._gr(tokens), self._gr(np.int32(slot)),
                self._gr(np.int32(n)), self._sp_row(opts), key, mrow,
                cflag, self._gr(np.int32(self._resolve_rln(opts))),
                table_row)
        self._commit_slot(slot, n, opts)
        tok = int(tok)
        self.dispatch_ms["admit"] = (time.perf_counter() - t0) * 1e3
        return tok

    def _grow_for_admit(self, slot: int, n: int):
        """Paged admission bookkeeping: drop any pages the slot still owns
        (a parked prefix being overwritten), allocate pages for the prompt,
        return the device table row. None in dense mode."""
        if not self.paged:
            return None
        from .paged import PagesExhausted
        self._pt.release(slot)
        # availability check includes one decode chunk of headroom (not
        # allocated — prepare_decode claims it): admitting a request the
        # very next chunk must preempt would thrash prefill work.
        # free_for(slot): on a dp mesh each slot allocates only from its
        # own shard's sub-pool
        ahead = min(n + self.ecfg.decode_chunk, self.max_seq)
        if (self._pt.blocks_for(ahead) > self._pt.free_for(slot)
                or not self._pt.grow(slot, n)):
            raise PagesExhausted(
                f"prompt of {n} tokens (+1 chunk headroom) needs "
                f"{self._pt.blocks_for(ahead)} pages; "
                f"{self._pt.free_for(slot)} free")
        return self._table_row_dev(slot)

    def _table_row_dev(self, slot: int):
        """The admission program's table argument: the slot's row [NBLK]
        (local == global indices without dp), or [dp, NBLK] per-shard rows
        where only the owning shard carries real (LOCAL) pages — the
        others get all-trash rows so their replicated writes self-discard
        (decoder.paged_insert_dp)."""
        if self._paged_dp == 1:
            return self._gr(self._pt.tables[slot])
        from .paged import TRASH_PAGE
        rows = np.full((self._paged_dp, self._nblk), TRASH_PAGE, np.int32)
        rows[self._pt.shard_of(slot)] = self._pt.tables[slot]
        # [dp, NBLK]: each dp shard reads its own row inside the insert's
        # manual region
        return self._g(rows, NamedSharding(self.mesh, P("dp", None))
                       if self.mesh is not None else None)

    @property
    def supports_admit_many(self) -> bool:
        """Batched fresh admission (admit_many): single-controller
        bucketed caches only — sp shards the prefill chunk over sequence
        (rows are not independent there), paged×dp needs per-slot
        owner/trash table routing the batched insert doesn't carry, and
        multi-host replay keeps to the single-admit programs."""
        return (self.sp_size == 1 and not self._multi
                and not (self.paged and self._paged_dp > 1))

    def _stack_keys(self, keys: List[Any]):
        """Stack per-slot replicated PRNG keys into one [m] key array
        (typed key arrays can't ride np.stack; a jitted stack with a
        replicated out-sharding can)."""
        fn = getattr(self, "_stack_keys_fn", None)
        if fn is None:
            if self._slot_sh is not None:
                fn = jax.jit(lambda *ks: jnp.stack(ks),
                             out_shardings=self._repl_sh)
            else:
                fn = jax.jit(lambda *ks: jnp.stack(ks))
            self._stack_keys_fn = fn
        return fn(*keys)

    def _sp_many(self, opts_list: Sequence[SlotOptions]):
        """[m]-row replicated SamplingParams (the batched twin of
        _sp_row)."""
        g = self._gr

        def arr(f, dt):
            return g(np.array([f(o) for o in opts_list], dt))
        return sampling.SamplingParams(
            temperature=arr(lambda o: o.temperature, np.float32),
            top_k=arr(lambda o: o.top_k, np.int32),
            top_p=arr(lambda o: o.top_p, np.float32),
            min_p=arr(lambda o: o.min_p, np.float32),
            typical_p=arr(lambda o: o.typical_p, np.float32),
            repeat_penalty=arr(lambda o: o.repeat_penalty, np.float32),
            presence_penalty=arr(lambda o: o.presence_penalty,
                                 np.float32),
            frequency_penalty=arr(lambda o: o.frequency_penalty,
                                  np.float32),
            mirostat=arr(lambda o: o.mirostat, np.int32),
            mirostat_tau=arr(lambda o: o.mirostat_tau, np.float32),
            mirostat_eta=arr(lambda o: o.mirostat_eta, np.float32))

    def _admit_many_jit(self, m: int):
        fn = self._admit_many_jits.get(m)
        if fn is None:
            fn = self._admit_many_make(m)
            self._admit_many_jits[m] = fn
        return fn

    def _admit_many_exec(self, m: int, bucket: int):
        exe = self._admit_many_execs.get((m, bucket))
        if exe is None:
            self._note_compile("admit_many", (m, bucket))
            tokens = self._gr(np.zeros((m, bucket), np.int32))
            table_rows = (self._gr(np.zeros((m, self._nblk), np.int32))
                          if self.paged else None)
            gi = lambda a: self._gr(np.asarray(a, np.int32))  # noqa: E731
            exe = self._admit_many_jit(m).lower(
                self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                tokens, gi(list(range(m))), gi([1] * m),
                self._sp_many([SlotOptions()] * m),
                self._stack_keys([self._dummy_key()] * m),
                self._mask_ones, gi([1] * m), table_rows).compile()
            self._admit_many_execs[(m, bucket)] = exe
        return exe

    def admit_many(self, slots: Sequence[int], prompts: Sequence[Any],
                   opts_list: Optional[Sequence[SlotOptions]] = None
                   ) -> List[int]:
        """Admit several prompts padding to the SAME prefill bucket in one
        batched dispatch; returns each slot's first sampled token, in
        order. Token-stream-identical to m sequential admit() calls: the
        per-slot PRNG seeds derive from (slot, seq_len) exactly as in
        _prep_slot, and causal masking keeps each row's prefill
        independent of its batch mates. Grammar-constrained and
        multimodal requests take the single-admit path (the caller
        routes them there)."""
        m = len(slots)
        assert m == len(prompts) >= 2, "admit_many wants >= 2 prompts"
        assert self.supports_admit_many, "unsupported engine mode"
        if opts_list is None:
            opts_list = [SlotOptions()] * m
        FAULTS.check("engine.admit")
        t0 = time.perf_counter()
        ns = [int(np.asarray(p).shape[0]) for p in prompts]
        for s, n in zip(slots, ns):
            assert not self.active[s], f"slot {s} busy"
            if n >= self.max_seq:
                raise ValueError(f"prompt too long: {n} >= {self.max_seq}")
        bucket = self.bucket_for(max(ns))
        assert all(self.bucket_for(n) == bucket for n in ns), \
            "admit_many is per-bucket (caller groups by bucket)"
        tokens = np.zeros((m, bucket), np.int32)
        for i, (p, n) in enumerate(zip(prompts, ns)):
            tokens[i, :n] = np.asarray(p, np.int32)
        table_rows = None
        if self.paged:
            from .paged import PagesExhausted
            grown: List[int] = []
            try:
                for s, n in zip(slots, ns):
                    self._grow_for_admit(s, n)
                    grown.append(s)
            except PagesExhausted:
                # roll back so a sequential-fallback pass sees the pool
                # unchanged (the parked prefixes these slots may have
                # held are gone either way — the caller already popped
                # them from its reuse map)
                for s in grown:
                    self._pt.release(s)
                raise
            table_rows = self._gr(
                np.stack([self._pt.tables[s] for s in slots]))
        keys = []
        for s, o, n in zip(slots, opts_list, ns):
            key, _, _ = self._prep_slot(s, o, n, None)
            keys.append(key)
        gi = lambda a: self._gr(np.asarray(a, np.int32))  # noqa: E731
        (toks, self.k_cache, self.v_cache, self.lengths, self.counts,
         self.last_tokens, self.pring, self.mu) = \
            self._admit_many_exec(m, bucket)(
                self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                self._gr(tokens), gi(list(slots)), gi(ns),
                self._sp_many(opts_list), self._stack_keys(keys),
                self._mask_ones,
                gi([self._resolve_rln(o) for o in opts_list]), table_rows)
        for s, n, o in zip(slots, ns, opts_list):
            self.active[s] = True
            self._host_lengths[s] = n
            self._opts[s] = o
            self._repeat_n[s] = self._resolve_rln(o)
            if self.paged:
                self._admit_seq += 1
                self._admit_order[s] = self._admit_seq
        self._rln_dev = self._g(self._repeat_n, self._slot_sh)
        self._rebuild_sp()
        self._active_dev = self._g(self.active.astype(np.int32),
                                   self._slot_sh)
        out = [int(t) for t in self._fetch(toks)]
        self.dispatch_ms["admit"] = (time.perf_counter() - t0) * 1e3
        return out

    @property
    def supports_extend(self) -> bool:
        """Prefix-cache continuation: EVERY cache mode since round 3 —
        dense (incl. int8), sp sequence-sharded (tail compute replicates,
        writes scatter to the owning shard — _make_extend_sp), paged, and
        paged×dp (tail replicates across shards with owner-real/
        others-trash table rows and an owner-select psum —
        decoder.paged_extend_dp)."""
        return True

    def _canon_attn(self, A: int) -> int:
        """Paged extend programs depend only on ceil(A / page_size):
        canonicalize so byte-identical programs share one compile."""
        if not self.paged:
            return A
        ps = self.ecfg.page_size
        return -(-A // ps) * ps

    def _extend_jit(self, A: int):
        fn = self._extend_jits.get(A)
        if fn is None:
            fn = self._extend_make(A)
            self._extend_jits[A] = fn
        return fn

    def _extend_exec(self, bucket: int, A: int):
        A = self._canon_attn(A)
        exe = self._extend_execs.get((bucket, A))
        if exe is None:
            self._note_compile("extend", (bucket, A))
            tokens = self._gr(np.zeros((1, bucket), np.int32))
            W = max(1, self.ecfg.repeat_last_n)
            zi = lambda v: self._gr(np.int32(v))  # noqa: E731
            args = [self.params, self.k_cache, self.v_cache, self.lengths,
                    self.counts, self.last_tokens, self.pring, self.mu,
                    tokens,
                    self._gr(np.zeros((W,), np.int32)), self._gr(
                        np.zeros((self.cfg.vocab_size,), np.int32)),
                    zi(0), zi(1), zi(1)]
            if self.paged and self._paged_dp > 1:
                rows = np.zeros((self._paged_dp, self._nblk), np.int32)
                args.append(self._g(rows, NamedSharding(
                    self.mesh, P("dp", None))))
                args.append(zi(0))            # owning shard index
            elif self.paged:
                args.append(self._gr(np.zeros((self._nblk,), np.int32)))
            args += [self._sp_row(SlotOptions()), self._dummy_key(),
                     self._mask_ones, zi(0), zi(W)]
            exe = self._extend_jit(A).lower(*args).compile()
            self._extend_execs[(bucket, A)] = exe
        return exe

    def extend(self, slot: int, full_ids: np.ndarray, start: int,
               opts: SlotOptions = SlotOptions(),
               mask_row: Optional[np.ndarray] = None) -> int:
        """Admit ``full_ids`` into ``slot`` reusing its cached first
        ``start`` positions (prefix cache); prefills only the tail.
        Returns the first sampled token. The caller guarantees the slot's
        cache holds K/V for ``full_ids[:start]`` (a parked sequence whose
        ids share that prefix — stale entries at positions >= start are
        never attended: masking is position-based and the tail overwrites
        them)."""
        # same fault point as admit(): an extend IS an admission (prefix
        # reuse or a chunked-prefill piece), and chaos drills must reach
        # the chunked path through it
        FAULTS.check("engine.admit")
        t0 = time.perf_counter()
        assert not self.active[slot], f"slot {slot} busy"
        full_ids = np.asarray(full_ids, np.int32)
        n_total = int(full_ids.shape[0])
        n_new = n_total - start
        assert 0 < n_new, f"nothing to prefill (start={start})"
        if n_total >= self.max_seq:
            raise ValueError(f"prompt too long: {n_total} >= {self.max_seq}")
        bucket = self.bucket_for(n_new)
        if start + bucket > self.max_seq:
            # tail positions run to start+bucket: dense writes there
            # directly; paged padding past the table would clamp into the
            # slot's LAST live page and corrupt the prefix (the forward
            # also trash-redirects out-of-table blocks as a second line
            # of defence)
            raise ValueError(
                f"tail bucket {bucket} does not fit above {start}")
        # attended-prefix bucket: the program slices/attends only the
        # first A cache positions, so continuation cost scales with the
        # conversation, not max_seq_len (sp always attends its full local
        # chunk — one program per tail bucket)
        attn_a = (self.bucket_for(start + bucket) if self._bucketed_attn
                  else self.max_seq)
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :n_new] = full_ids[start:]
        # penalty window over the full continuation prompt (host-built:
        # the parked ring may describe a divergent suffix), at the
        # REQUEST's effective window inside the static-W ring
        W = max(1, self.ecfg.repeat_last_n)
        rln = self._resolve_rln(opts)
        rmod = max(rln, 1)
        V = self.cfg.vocab_size
        ring = np.full((W,), V, np.int32)
        window = full_ids[max(0, n_total - rln):] if rln > 0 \
            else full_ids[:0]
        pos = np.arange(n_total - len(window), n_total)
        ring[pos % rmod] = window
        counts_row = np.zeros((V,), np.int32)
        np.add.at(counts_row, window, 1)
        key, mrow, cflag = self._prep_slot(slot, opts, n_total, mask_row)
        args = [self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                self._gr(tokens), self._gr(ring),
                self._gr(counts_row), self._gr(np.int32(slot)),
                self._gr(np.int32(start)), self._gr(np.int32(n_new))]
        if self.paged:
            from .paged import PagesExhausted
            ahead = min(n_total + self.ecfg.decode_chunk, self.max_seq)
            deficit = (self._pt.blocks_for(ahead)
                       - self._pt.owned_blocks(slot))
            if deficit > self._pt.free_for(slot) \
                    or not self._pt.grow(slot, n_total):
                # the scheduler already popped this slot from its parked
                # map, so nothing will ever reuse or evict the prefix —
                # return its pages now or they leak until a fresh admit
                # happens to land on this slot (ADVICE r2)
                self._pt.release(slot)
                raise PagesExhausted(
                    f"extend to {n_total} tokens (+1 chunk headroom): "
                    f"{self._pt.n_free} pages free")
            if self._paged_dp > 1:
                # [dp, NBLK] owner-real/others-trash rows + owner index
                # (decoder.paged_extend_dp)
                args.append(self._table_row_dev(slot))
                args.append(self._gr(np.int32(self._pt.shard_of(slot))))
            else:
                args.append(self._gr(self._pt.tables[slot]))
        args += [self._sp_row(opts), key, mrow, cflag,
                 self._gr(np.int32(rln))]
        (tok, self.k_cache, self.v_cache, self.lengths, self.counts,
         self.last_tokens, self.pring, self.mu) = \
            self._extend_exec(bucket, attn_a)(*args)
        self._commit_slot(slot, n_total, opts)
        tok = int(tok)
        self.dispatch_ms["extend"] = (time.perf_counter() - t0) * 1e3
        return tok

    def _attn_bucket(self, n: int) -> int:
        """Static attended-prefix length covering every active slot for the
        next ``n`` steps: smallest bucket >= max(lengths) + n. Decode cache
        traffic scales with this, not with max_seq_len."""
        if not self._bucketed_attn:
            return self.max_seq
        need = int(self._host_lengths[self.active].max(initial=0)) + n
        for b in self._buckets:
            if need <= b:
                return b
        return self.max_seq

    def _pad_mask_row(self, row) -> np.ndarray:
        """Zero-pad a packed mask to the engine's width — ids beyond the
        grammar's token table are unknown to it and stay disallowed."""
        # lint: allow(host-sync-hot-path): grammar masks are host numpy state — no device transfer
        row = np.asarray(row, np.uint32)
        if row.shape[0] == self.mask_words:
            return row
        assert row.shape[0] < self.mask_words, (
            f"mask row of {row.shape[0]} words exceeds vocab "
            f"({self.mask_words} words)")
        out = np.zeros((self.mask_words,), np.uint32)
        out[:row.shape[0]] = row
        return out

    def set_mask(self, slot: int, row: np.ndarray, gid: int = -1):
        """Install the packed allowed-token mask for ``slot`` (applies from
        the next decode step; constrained until release/clear_mask).

        ``gid`` >= 0 additionally places the slot in DEVICE-grammar mode:
        its mask is read from the installed grammar table row ``gid`` and
        the automaton advances on device every sampled token, so the slot
        keeps the full decode_n chunk instead of one token per dispatch.
        The host row still installs as the fallback the device escapes
        to."""
        self._constrained[slot] = True
        self._gdev_mode[slot] = gid >= 0
        (self.mask_bits, self._constr_dev,
         self._gstate) = self._set_mask_fn(
            self.mask_bits, self._constr_dev, self._gstate,
            self._gr(np.int32(slot)), self._gr(self._pad_mask_row(row)),
            self._gr(np.int32(1)), self._gr(np.int32(gid)))

    def clear_mask(self, slot: int):
        if not self._constrained[slot]:
            return
        self._constrained[slot] = False
        self._gdev_mode[slot] = False
        (self.mask_bits, self._constr_dev,
         self._gstate) = self._set_mask_fn(
            self.mask_bits, self._constr_dev, self._gstate,
            self._gr(np.int32(slot)), self._mask_ones,
            self._gr(np.int32(0)), self._gr(np.int32(-1)))

    def install_grammar(self, key: Any, mask: np.ndarray,
                        trans: np.ndarray) -> bool:
        """Upload a precomputed grammar program (ops/constrain.py
        GrammarTable.mask/.trans) to the device tables. ``key`` identifies
        the table; a matching key is a no-op. Returns False — scheduler
        falls back to host masks — when a DIFFERENT table is live while
        any slot is still in device mode (swapping it under them would
        corrupt their automata). Rows/cols beyond the static
        [TPU_GRAMMAR_STATES, vocab] capacity truncate; transitions into
        truncated states were already -1 (escape) in the table."""
        if not self._grammar_device:
            return False
        if self._gtable_key == key:
            return True
        if self._gdev_mode.any():
            return False
        G, V = self._gstates_cap, self.cfg.vocab_size
        # lint: allow(host-sync-hot-path): grammar tables arrive as host numpy; upload is once per grammar, not per dispatch
        mask = np.asarray(mask, np.uint32)[:G]
        trans = np.asarray(trans, np.int32)[:G]  # lint: allow(host-sync-hot-path): host numpy staging for device_put
        m = np.zeros((G, self.mask_words), np.uint32)
        m[:mask.shape[0], :min(mask.shape[1], self.mask_words)] = \
            mask[:, :self.mask_words]
        t = np.full((G, V), -1, np.int32)
        t[:trans.shape[0], :min(trans.shape[1], V)] = trans[:, :V]
        # a transition into a state id beyond capacity escapes
        t[t >= G] = -1
        self._gmask_dev = self._gr(m)
        self._gtrans_dev = self._gr(t)
        self._gtable_key = key
        return True

    def _tables_dev(self):
        if not self.paged:
            return None
        return self._g(self._pt.tables,
                       self._slot_sh2 if self.mesh is not None else None)

    def decode(self) -> np.ndarray:
        """One decode step for every slot; returns sampled tokens [B] (only
        entries where self.active were valid at call time)."""
        if self.paged:
            victims = self.prepare_decode(1)
            if victims:
                from .paged import PagesExhausted
                raise PagesExhausted(f"pool dry; victims {victims}")
        (toks, self.k_cache, self.v_cache, self.lengths, self.counts,
         self.last_tokens, self.pring, self.mu, self.keys,
         self._gstate) = self._decode_fn(
            self.params, self.k_cache, self.v_cache, self.lengths,
            self.counts, self.last_tokens, self.pring, self.mu, self.sp,
            self.keys, self._active_dev, self.mask_bits, self._constr_dev,
            self._rln_dev, self._gstate, self._gmask_dev,
            self._gtrans_dev, self._tables_dev())
        self._host_lengths[self.active] += 1
        return self._fetch(toks)

    def _note_compile(self, kind: str, key: Any) -> None:
        """Called from every executable-cache miss. While warm_buckets is
        running the signature is merely registered; outside it, a miss is
        a mid-serving XLA compile paid inside a timed dispatch — count it
        (once per signature) and drop a flight-recorder event."""
        sig = (kind, key)
        if self._warming:
            self._warmed_sigs.add(sig)
            return
        if sig in self._warmed_sigs:
            return
        self._warmed_sigs.add(sig)
        self.recompiles[kind] = self.recompiles.get(kind, 0) + 1
        METRICS.inc("tpu_model_recompiles_total", 1.0, f'{{kind="{kind}"}}')
        FLIGHT.record("recompile", program=kind, key=str(key))

    def _decode_n_exec(self, n: int, attn_len: int):
        key = (n, attn_len)
        exe = self._decode_execs.get(key)
        if exe is None:
            self._note_compile("decode", key)
            budgets = self._g(np.full((self.n_slots,), n, np.int32),
                              self._slot_sh)
            exe = self._decode_n_fn.lower(
                self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                self.sp, self.keys, self._active_dev, self.mask_bits,
                self._constr_dev, self._rln_dev, self._gstate,
                self._gmask_dev, self._gtrans_dev, n, attn_len,
                self._tables_dev(), budgets).compile()
            self._decode_execs[key] = exe
        return exe

    def _admit_exec(self, bucket: int):
        exe = self._admit_execs.get(bucket)
        if exe is None:
            self._note_compile("admit", bucket)
            tokens = self._gr(np.zeros((1, bucket), np.int32))
            if not self.paged:
                table_row = None
            elif self._paged_dp > 1:
                table_row = self._g(
                    np.zeros((self._paged_dp, self._nblk), np.int32),
                    NamedSharding(self.mesh, P("dp", None))
                    if self.mesh is not None else None)
            else:
                table_row = self._gr(np.zeros((self._nblk,), np.int32))
            zi = lambda v: self._gr(np.int32(v))  # noqa: E731
            exe = self._admit_fn.lower(
                self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                tokens, zi(0), zi(1),
                self._sp_row(SlotOptions()), self._dummy_key(),
                self._mask_ones, zi(0), zi(1),
                table_row).compile()
            self._admit_execs[bucket] = exe
        return exe

    def warm_buckets(self, n: Optional[int] = None, *,
                     ctx_lo: Optional[int] = None,
                     ctx_hi: Optional[int] = None,
                     full: bool = True):
        """Public warm entry: every executable compiled inside is
        registered as an AOT-warmed signature (not a recompile) — the
        recompile detector only counts cache misses OUTSIDE this scope.
        See _warm_buckets for the warm plan itself."""
        prev = self._warming
        self._warming = True
        try:
            return self._warm_buckets(n, ctx_lo=ctx_lo, ctx_hi=ctx_hi,
                                      full=full)
        finally:
            self._warming = prev

    def _warm_buckets(self, n: Optional[int] = None, *,
                      ctx_lo: Optional[int] = None,
                      ctx_hi: Optional[int] = None,
                      full: bool = True):
        """AOT-compile the chunked decode program for every attention
        bucket AND the admission program for every prefill bucket, so
        serving never pays an XLA compile mid-request. Non-bucketed paths
        (sp meshes) only ever decode at max_seq — one program, not a
        duplicate per bucket.

        ``ctx_lo``/``ctx_hi`` bound the context lengths the caller will
        actually reach, restricting the decode warm to the reachable
        attention buckets (smallest covering ctx_lo+n .. smallest covering
        ctx_hi) — the bench uses this so a capture doesn't pay compiles for
        buckets it never decodes in. ``full=False`` additionally skips the
        single-step, admission, spec, and extend warms (lazy compile covers
        a first use; a server must never take that hit mid-request, a bench
        capture may)."""
        n = n or self.ecfg.decode_chunk
        buckets = self._buckets if self._bucketed_attn else [self.max_seq]
        if self._bucketed_attn and (ctx_lo is not None
                                    or ctx_hi is not None):
            lo = self.bucket_for(min((ctx_lo or 0) + n, self.max_seq))
            hi = self.bucket_for(min(ctx_hi, self.max_seq)) \
                if ctx_hi else self.max_seq
            buckets = [b for b in buckets if lo <= b <= hi] or [hi]
        for b in buckets:
            self._decode_n_exec(n, b)
            if n != 1 and full:
                # grammar-constrained serving steps one token per dispatch
                # (scheduler drops to decode_n(1)) — warm those too
                self._decode_n_exec(1, b)
        if not full:
            return
        for b in self._buckets:
            self._admit_exec(b)
        if self.supports_admit_many:
            # batched-admission programs for the group sizes the
            # scheduler forms (see Scheduler._admit_waiting)
            for b in self._buckets:
                for m in (2, 4):
                    if m <= self.n_slots:
                        self._admit_many_exec(m, b)
        import os as _os
        spec_k = int(_os.environ.get("TPU_SPEC_DECODE", "0") or "0")
        if (spec_k > 0 and self.sp_size == 1
                and not (self.paged and self._paged_dp > 1)):
            # speculative verify programs per attention bucket — a bucket
            # crossing must swap programs, never recompile mid-serving
            # (the BENCH_r05 623ms/spec-dispatch anomaly was exactly this
            # warm missing: one warmed bucket, compiles on every cross)
            for b in buckets:
                self._spec_exec(spec_k, b)
            if not self.active.any():
                # pre-seed dispatch_ms["spec"] from a no-op dispatch
                # over the empty batch (every slot inactive → the push
                # scan advances nothing and inactive-slot KV writes land
                # above/outside attended lengths): the gauge starts at
                # steady-state launch cost instead of 0, and the first
                # REAL spec dispatch pays neither compile nor first-run
                # executable setup. Bypasses decode_n_launch so warm
                # never consumes an armed engine.step fault.
                h = self._spec_launch(
                    np.zeros((self.n_slots, spec_k), np.int32), None,
                    time.perf_counter())
                h.wait()
                if self.paged:
                    self._pt.retire_epoch(h.epoch)
        if self.supports_extend:
            # (tail, attended) bucket pairs; the max_seq tail bucket is
            # unreachable (extend requires start >= 1 and start + bucket
            # <= max_seq), and the attended bucket covers start + tail so
            # A >= the tail bucket — O(log² max_seq) programs. sp extends
            # ignore A entirely (extend() always passes max_seq there):
            # one program per tail bucket, not a pair matrix.
            for b in self._buckets:
                if b >= self.max_seq:
                    continue
                attns = ([a for a in self._buckets if a > b]
                         if self._bucketed_attn else [self.max_seq])
                for a in attns:
                    self._extend_exec(b, a)

    # --- warm-snapshot (scale-to-zero fast cold-start) -----------------
    def _exec_cache_items(self):
        """Yield ((kind, key), executable) over every AOT exec cache —
        the same (kind, key) vocabulary _note_compile registers."""
        for key, exe in self._decode_execs.items():
            yield ("decode", key), exe
        for b, exe in self._admit_execs.items():
            yield ("admit", b), exe
        for k, exe in self._admit_many_execs.items():
            yield ("admit_many", k), exe
        for k, exe in self._extend_execs.items():
            yield ("extend", k), exe
        for k, exe in self._spec_execs.items():
            yield ("spec", k), exe

    def _install_exec(self, sig, exe) -> bool:
        kind, key = sig
        if kind == "decode":
            self._decode_execs[key] = exe
        elif kind == "admit":
            self._admit_execs[key] = exe
        elif kind == "admit_many":
            self._admit_many_execs[key] = exe
        elif kind == "extend":
            self._extend_execs[key] = exe
        elif kind == "spec":
            self._spec_execs[key] = exe
        else:
            return False
        return True

    def _compile_sig(self, sig) -> bool:
        """Recompile one recorded warm signature through its normal
        cache-miss path. Only ever called inside the warming scope, so
        the recompile counter stays untouched by construction."""
        kind, key = sig
        try:
            if kind == "decode":
                self._decode_n_exec(*key)
            elif kind == "admit":
                self._admit_exec(key)
            elif kind == "admit_many":
                self._admit_many_exec(*key)
            elif kind == "extend":
                self._extend_exec(*key)
            elif kind == "spec":
                self._spec_exec(*key)
            else:
                return False
        except Exception:  # noqa: BLE001 — a sig the current config
            return False   # disallows (e.g. spec off) is simply skipped
        return True

    def warm_snapshot(self) -> bytes:
        """Serialize the AOT warm state: every warmed (kind, key)
        signature plus — where the backend supports it — the compiled
        executables themselves (jax.experimental.serialize_executable).
        Saved to the image-store PVC at drain time so a scale-to-zero
        wake restores warmth instead of recompiling the warm plan.

        Executable payloads are per-entry best-effort: an entry that
        fails to serialize is covered by its recorded signature (restore
        recompiles it inside the warming scope — slower wake, identical
        recompile-counter outcome of zero).

        Payloads default to accelerator backends only: the XLA CPU
        executable-deserialization path miscompiles on some hosts (the
        same instability that keeps the persistent compile cache opt-in
        for tests), and on CPU a sig replay is cheap anyway.
        TPU_WARM_SNAPSHOT_EXECS=1 forces payloads on, =0 forces off."""
        import os as _os
        import pickle
        execs = {}
        if self._snapshot_execs_ok():
            try:
                from jax.experimental import serialize_executable as _se
            except ImportError:
                _se = None
            if _se is not None:
                for sig, exe in self._exec_cache_items():
                    try:
                        payload, in_tree, out_tree = _se.serialize(exe)
                        execs[sig] = (payload,
                                      pickle.dumps((in_tree, out_tree)))
                    except Exception:  # lint: allow(exception-hygiene): sig replay covers a lost executable
                        continue
        return pickle.dumps(
            {"version": 1,
             "jax": jax.__version__,
             "backend": jax.default_backend(),
             "sigs": sorted(self._warmed_sigs, key=repr),
             "execs": execs},
            protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def _snapshot_execs_ok() -> bool:
        """Tri-state TPU_WARM_SNAPSHOT_EXECS: unset = executable
        payloads on accelerator backends only (CPU deserialization is
        unstable on some hosts), "1" forces on, "0" forces off."""
        import os as _os
        want = _os.environ.get("TPU_WARM_SNAPSHOT_EXECS", "")
        if want == "0":
            return False
        return want == "1" or jax.default_backend() != "cpu"

    def restore_warm(self, blob: bytes) -> Dict[str, int]:
        """Install a warm_snapshot() blob into a fresh engine: load
        serialized executables where the backend/version still match,
        then recompile any remaining signatures inside the warming scope.
        Either way the engine comes up with the full warm plan registered
        and `tpu_model_recompiles_total` untouched — the scale-to-zero
        wake contract. Returns {"restored": n, "compiled": n}."""
        import pickle
        snap = pickle.loads(blob)
        if int(snap.get("version") or 0) != 1:
            raise ValueError("unknown warm snapshot version")
        restored = compiled = 0
        prev = self._warming
        self._warming = True
        try:
            execs = snap.get("execs") or {}
            compat = (snap.get("jax") == jax.__version__
                      and snap.get("backend") == jax.default_backend())
            # same tri-state as the save side: a CPU wake never
            # deserializes executables unless explicitly forced — the
            # sigs below cover every entry either way
            if execs and compat and self._snapshot_execs_ok():
                try:
                    from jax.experimental import serialize_executable as _se
                except ImportError:
                    _se = None
                if _se is not None:
                    for sig, (payload, trees) in execs.items():
                        try:
                            in_tree, out_tree = pickle.loads(trees)
                            exe = _se.deserialize_and_load(
                                payload, in_tree, out_tree)
                        except Exception:  # lint: allow(exception-hygiene): falls through to the recompile path
                            continue       # to the recompile path below
                        if self._install_exec(sig, exe):
                            self._warmed_sigs.add(sig)
                            restored += 1
            for sig in snap.get("sigs") or []:
                sig = (sig[0], tuple(sig[1]) if isinstance(sig[1], list)
                       else sig[1])
                if sig in self._warmed_sigs:
                    continue
                if self._compile_sig(sig):
                    compiled += 1
        finally:
            self._warming = prev
        FLIGHT.record("warm_restore", restored=restored, compiled=compiled)
        return {"restored": restored, "compiled": compiled}

    def prepare_decode(self, n: Optional[int] = None) -> list:
        """Paged mode: grow every active slot's block table to cover
        lengths + n upcoming tokens (pages must exist BEFORE the chunk —
        steps advance device-side with no host round-trip). Grows in
        admission order, so when the pool runs dry the NEWEST slots fail;
        returns them (newest first) for the scheduler to preempt/requeue.
        Engine state is untouched for victims. [] in dense mode."""
        if not self.paged:
            return []
        n = n or self.ecfg.decode_chunk
        order = sorted((s for s in range(self.n_slots) if self.active[s]),
                       key=lambda s: self._admit_order[s])
        # clamp at max_seq: a slot finishing its context within the chunk
        # over-decodes into its last page (same as the dense cache's
        # over-decode-then-release semantics), never past the table
        victims = [s for s in order
                   if not self._pt.grow(
                       s, min(int(self._host_lengths[s]) + n,  # lint: allow(host-sync-hot-path): host shadow of slot lengths
                              self.max_seq))]
        victims.reverse()
        return victims

    def can_admit(self, slot: int, n_tokens: int) -> bool:
        """Would admitting ``n_tokens`` into ``slot`` find enough pages in
        its allocation domain (the slot's dp-shard sub-pool)? Admission
        releases the slot's own parked pages first, so they count as
        available. Dense mode: always True (the scheduler uses this to
        steer admissions toward dp shards that still have pages)."""
        if not self.paged:
            return True
        ahead = min(n_tokens + self.ecfg.decode_chunk, self.max_seq)
        return (self._pt.blocks_for(ahead)
                <= self._pt.free_for(slot) + self._pt.owned_blocks(slot))

    def admissible(self, n_tokens: int) -> bool:
        """Could a prompt of n_tokens EVER be admitted (whole pool free)?
        Dense mode always True — length limits are checked elsewhere."""
        if not self.paged:
            return True
        ahead = min(n_tokens + self.ecfg.decode_chunk, self.max_seq)
        return self._pt.blocks_for(ahead) <= self._pt.data_pages

    def free_slot_pages(self, slot: int):
        """Drop a PARKED (inactive) slot's pages back to the pool — the
        scheduler evicts prefix caches with this when admissions or decode
        growth run out of pages."""
        if self.paged:
            assert not self.active[slot], "freeing pages of an active slot"
            self._pt.release(slot)

    @property
    def free_pages(self) -> int:
        return self._pt.n_free if self.paged else -1

    # ------------------------------------------------------------------
    # radix prefix cache (paged, single sub-pool)
    # ------------------------------------------------------------------
    @property
    def radix_enabled(self) -> bool:
        return self._radix is not None

    @property
    def radix_nodes(self) -> int:
        """Resident radix-tree nodes (0 when the cache is off)."""
        return self._radix.n_nodes if self._radix is not None else 0

    @property
    def radix_pages(self) -> int:
        """Physical pages pinned by the radix tree (tier-0 nodes only —
        tier-1 nodes hold host bytes, not pool pages)."""
        return self._radix.n_pages if self._radix is not None else 0

    @property
    def radix_hosted(self) -> int:
        """Radix nodes whose KV lives in the host arena (tier 1)."""
        return self._radix.n_hosted if self._radix is not None else 0

    # -- tier-1 host arena occupancy (0 everywhere when the tier is off)
    @property
    def host_cache_enabled(self) -> bool:
        return self._arena is not None

    @property
    def host_cache_used_bytes(self) -> int:
        return self._arena.used_bytes if self._arena is not None else 0

    @property
    def host_cache_capacity_bytes(self) -> int:
        return self._arena.capacity_bytes if self._arena is not None else 0

    @property
    def host_cache_pages(self) -> int:
        return self._arena.n_entries if self._arena is not None else 0

    @property
    def host_page_bytes(self) -> int:
        """Nominal host bytes per spilled page (0 when the tier is off)."""
        return self._host_page_bytes

    def prefix_probe(self, full_ids) -> int:
        """Non-mutating: how many leading tokens of ``full_ids`` the radix
        cache could serve (full pages + one partial boundary page), capped
        at len-1 so at least one tail token remains to prefill. The
        scheduler uses this to apply its reuse floor and bucket-fit checks
        BEFORE committing to a stitch. 0 when the cache is off or cold.
        Tier-1 (host-spilled) pages count as servable — ``stitch`` may
        still choose to recompute them if the break-even model says the
        copy is dearer than the prefill."""
        return self.prefix_probe_tier(full_ids)[0]

    def prefix_probe_tier(self, full_ids):
        """Tier-aware probe: ``(servable_tokens, tier)`` where ``tier``
        is the WORST tier on the matched path — 0 = fully HBM-hot,
        1 = needs a host-arena restitch, 2 = needs a restitch of
        fleet-snapshot pages. The gateway prefers lower tiers on
        matched-length ties so affinity stays truthful across replica
        wake (a just-woken replica answers 2, a hot one 0)."""
        if self._radix is None:
            return 0, 0
        ids = np.asarray(full_ids)
        full, part, q = self._radix.match(ids, int(ids.shape[0]) - 1,
                                          bump=False)
        tier = 0
        for n in full:
            if n.tier != 0:
                tier = max(tier, 2 if (n.host is not None
                                       and n.host.snapshot) else 1)
        if part is not None and q > 0 and part.tier != 0:
            tier = max(tier, 2 if (part.host is not None
                                   and part.host.snapshot) else 1)
        return len(full) * self.ecfg.page_size + q, tier

    def stitch(self, slot: int, full_ids, max_reuse: int) -> int:
        """Map the radix cache's longest prefix of ``full_ids`` (at most
        ``max_reuse`` tokens) into ``slot``'s block table ahead of an
        extend(): whole-page hits are shared READ-ONLY (refcount bump, no
        copy, no compute); a partially-matched boundary page is copied
        into a private page first (copy-on-write) because the tail
        prefill will write the remaining positions of that very page.
        Any pages the slot still held (stale parked prefix) are dropped
        first. Returns the reuse length actually stitched (0 = cold).
        Raises PagesExhausted when a page cannot be allocated — the
        slot is left with NO pages so the caller can fall back cleanly.
        Deterministic from call order, so follower replay stays in step.

        Tiered KV cache (ISSUE 18): the matched path splits into a
        leading tier-0 run (shared read-only, as before) and a tier-1
        run of host-spilled pages. When the break-even model says the
        host→HBM copy beats recomputing the run, each tier-1 page is
        RESTITCHED — a private page is grown, the upload is enqueued
        (async, overlapping the tail prefill) and the node is promoted
        back to tier 0 so later requests share the fresh page. Short
        runs recompute (counted as tiered misses). An armed
        ``pages.restitch`` fault aborts the stitch into the same clean
        pageless state as pool exhaustion; already-promoted nodes stay
        valid because their uploads were already enqueued.
        ``last_stitch`` records the per-tier token breakdown for the
        scheduler's metrics."""
        assert self._radix is not None, "radix cache disabled"
        assert not self.active[slot], f"slot {slot} busy"
        from .faults import InjectedFault
        from .paged import PagesExhausted
        self._pt.release(slot)
        ls = self.last_stitch = {"t0": 0, "t1": 0, "t2": 0,
                                 "skip1": 0, "skip2": 0}
        ids = np.asarray(full_ids, np.int32)
        cap = min(int(max_reuse), int(ids.shape[0]) - 1)
        if cap <= 0:
            return 0
        full, part, q = self._radix.match(ids, cap, bump=True)
        if not full and q == 0:
            return 0
        ps = self.ecfg.page_size
        # split the matched path: shareable tier-0 run, then the
        # restitchable tier-1 run (paths are tier0* then tier1*)
        t0run, t1run = [], []
        for n in full:
            if n.tier == 0 and not t1run:
                t0run.append(n)
            elif n.tier != 0:
                t1run.append(n)
            else:     # tier-0 below tier-1: unreachable by invariant
                break
        self._pt.map_shared(slot, [n.page for n in t0run])
        reuse = len(t0run) * ps
        ls["t0"] = reuse
        restitch = False
        if t1run and self._arena is not None:
            from .host_cache import worth_restitch
            restitch = worth_restitch(
                self.cfg, reuse, len(t1run) * ps,
                sum(n.host.nbytes for n in t1run))
        skipped = bool(t1run) and not restitch
        if skipped:
            # break-even says recompute: the run stays spilled, the tail
            # prefill regenerates those positions (a tiered miss)
            for n in t1run:
                ls["skip2" if n.host.snapshot else "skip1"] += ps
            t1run = []
        # make room for the planned uploads BEFORE enqueuing any of them:
        # at this point no restitch program is in flight, so eviction can
        # still spill victims to the host tier (mid-stitch the epoch has
        # advanced and a dry pool would plainly free them instead). The
        # probe just bumped the matched path MRU, so LRU victims are
        # other prefixes — never the run being restitched.
        need = len(t1run) + (1 if part is not None and q > 0
                             and not skipped else 0)
        if need > self._pt.n_free:
            self.radix_evict(need - self._pt.n_free)
        try:
            for node in t1run:
                was_snap = node.host.snapshot
                dst = self._upload_host(slot, node.host.kv, reuse + ps)
                self._pt.pin(dst)
                self._arena.free(self._radix.mark_promoted(node, dst))
                reuse += ps
                ls["t2" if was_snap else "t1"] += ps
            # boundary page: COW from a tier-0 partial, or a PRIVATE
            # host upload from a tier-1 partial (no promotion — the tail
            # prefill writes this page's remaining positions, so the
            # tree keeps its spilled copy). A skipped tier-1 run makes
            # the boundary unreachable (its prefix wasn't stitched).
            if part is not None and q > 0 and not skipped:
                if part.tier == 0:
                    if not self._pt.grow(slot, reuse + q):
                        self._pt.release(slot)
                        raise PagesExhausted(
                            f"no page for the copy-on-write boundary "
                            f"({self._pt.n_free} free)")
                    dst = self._pt.slot_pages(slot)[-1]
                    self.k_cache, self.v_cache = self._copy_page_fn(
                        self.k_cache, self.v_cache,
                        self._gr(np.int32(part.page)),
                        self._gr(np.int32(dst)))
                    reuse += q
                    ls["t0"] += q
                elif self._arena is not None:
                    from .host_cache import worth_restitch
                    if worth_restitch(self.cfg, reuse, q,
                                      part.host.nbytes):
                        self._upload_host(slot, part.host.kv, reuse + q)
                        reuse += q
                        ls["t2" if part.host.snapshot else "t1"] += q
                    else:
                        ls["skip2" if part.host.snapshot
                           else "skip1"] += q
        except InjectedFault as e:
            # chaos (pages.restitch): abort into the same pageless state
            # as pool exhaustion — the caller cold-admits cleanly
            self._pt.release(slot)
            raise PagesExhausted(f"restitch aborted: {e}")
        return reuse

    def _upload_host(self, slot: int, kv, n_tokens: int) -> int:
        """Grow one private page for ``slot`` and enqueue the host→HBM
        upload of a spilled page's bytes into it. The jitted update is
        async — it overlaps the tail prefill's host-side work and the
        donated-cache dependency chain orders it before any program
        that reads the page. Returns the page id."""
        from .paged import PagesExhausted
        FAULTS.check("pages.restitch")
        if not self._pt.grow(slot, n_tokens):
            self._pt.release(slot)
            raise PagesExhausted(
                f"no page for tier-1 restitch ({self._pt.n_free} free)")
        dst = self._pt.slot_pages(slot)[-1]
        kp = jax.tree_util.tree_map(self._gr, kv[0])
        vp = jax.tree_util.tree_map(self._gr, kv[1])
        self.k_cache, self.v_cache = self._upload_page_fn(
            self.k_cache, self.v_cache, kp, vp, self._gr(np.int32(dst)))
        return dst

    def donate_prefix(self, slot: int, token_ids) -> int:
        """Insert ``slot``'s full-page-aligned KV prefix for ``token_ids``
        into the radix tree, then release the slot. Chunks the tree did
        not yet hold adopt the slot's physical pages (pinned — they
        survive the release); chunks already cached keep the tree's
        existing page and the slot's duplicate goes back to the pool.
        Replaces slot-parking in radix mode: any number of later requests
        can stitch the prefix concurrently. Returns tokens donated."""
        if self._radix is None:
            self.release(slot)
            return 0
        # lint: allow(host-sync-hot-path): token ids arrive as host lists
        ids = np.asarray(token_ids, np.int32)
        ps = self.ecfg.page_size
        # lint: allow(host-sync-hot-path): shape read of a host array
        k = min(int(ids.shape[0]) // ps, self._pt.owned_blocks(slot))
        if k > 0:
            adopted = self._radix.insert(ids[:k * ps],
                                         self._pt.slot_pages(slot)[:k])
            for node in adopted:
                self._pt.pin(node.page)
            if self._arena is not None:
                # chunks the donor re-materialised while spilled got
                # promoted back to tier 0: retire their host bytes
                self._arena.free_all(self._radix.take_dropped_hosts())
        self.release(slot)
        return k * ps

    def radix_evict(self, n_pages: int = 1) -> int:
        """Evict up to ``n_pages`` least-recently-used radix leaves whose
        pages no slot currently maps, page-by-page (children before
        parents), returning their pages to the pool. Replaces the
        all-or-nothing parked-slot eviction. Returns pages freed.

        With the host arena on (TPU_HOST_CACHE_GB > 0) an evicted page
        is SPILLED to the host tier first — but only while the epoch
        fence is quiescent (no launched dispatch un-retired: a host copy
        must never race in-flight device writes) and the arena has room
        after dropping LRU tier-1 entries. Otherwise the page is plainly
        freed, pruning any tier-1 descendants with it so every resident
        path stays rooted. Spill decisions are pure functions of
        mirrored state, so follower replay spills identically."""
        if self._radix is None:
            return 0

        def evictable(pg):
            return self._pt.shared_refs(pg) == 0

        if self._arena is None:
            pages = self._radix.evict(n_pages, evictable)
            for pg in pages:
                self._pt.unpin(pg)
            return len(pages)
        freed = 0
        while freed < n_pages:
            node = self._radix.spill_lru(evictable)
            if node is None:
                break
            if self._pt.quiescent and self._spill_node(node):
                freed += 1
                continue
            pages, hosts = self._radix.remove(node)
            self._arena.free_all(hosts)
            for pg in pages:
                self._pt.unpin(pg)
            freed += len(pages)
        return freed

    def _spill_node(self, node) -> bool:
        """Move one radix node's page into the host arena (tier 0 → 1).
        False = the caller falls back to a plain eviction (arena full
        even after an LRU drop, or the ``pages.spill`` chaos point
        fired). Caller guarantees the fence is quiescent, so the
        ``device_get`` here captures stable bytes; it runs on the
        admission/eviction path only, never the dispatch hot loop."""
        from .faults import InjectedFault
        if not self._arena.room_for(1):
            self._arena.free_all(self._radix.drop_host_lru(1))
        if not self._arena.room_for(1):
            return False
        try:
            FAULTS.check("pages.spill")
        except InjectedFault:
            return False
        kp, vp = self._gather_page_fn(self.k_cache, self.v_cache,
                                      self._gr(np.int32(node.page)))
        kv = jax.device_get((kp, vp))
        pg = self._radix.mark_spilled(node, self._arena.store(kv))
        self._pt.unpin(pg)
        self.n_spilled_pages += 1
        METRICS.inc("tpu_model_spilled_pages_total")
        return True

    def radix_reset(self):
        """Drop the whole radix tree (supervised restart: cache contents
        are unknown after a failed step, so nothing may be reused).
        Tier-1 state dies with the tree — a restarted engine never
        restitches bytes whose provenance it can no longer trust."""
        if self._radix is None:
            return
        for pg in self._radix.reset():
            self._pt.unpin(pg)
        if self._arena is not None:
            self._arena.clear()

    # ------------------------------------------------------------------
    # tier-2 fleet prefix snapshots (gguf/store.py persistence)
    # ------------------------------------------------------------------
    def export_prefixes(self, max_bytes: int = 64 << 20):
        """Serialize the hottest radix prefixes (any tier) into a
        self-contained snapshot blob, most-recently-used first within
        ``max_bytes`` (a child only ships if its parent made the cut,
        so every shipped path is rooted). Tier-0 pages are gathered
        from the pool — the ``device_get`` waits out pending programs,
        so call this at drain/idle, never on the dispatch path.
        Read-only and leader-side (NOT mirrored). None when empty."""
        if self._radix is None or self._radix.n_nodes == 0:
            return None
        from . import kv_wire
        nodes = self._radix.walk()     # parents before children (BFS)
        # parent.stamp >= child.stamp (bumps touch whole paths), and the
        # stable sort keeps BFS order on ties — parents stay first
        nodes.sort(key=lambda n: -n.stamp)
        idx: Dict[int, int] = {}
        recs: List[Dict[str, Any]] = []
        budget = int(max_bytes)
        for node in nodes:
            at_root = not node.parent.chunk
            pidx = -1 if at_root else idx.get(id(node.parent), -1)
            if not at_root and pidx < 0:
                continue              # parent missed the budget
            kv = self._page_kv(node)
            nbytes = kv_wire.kv_nbytes(kv)
            if nbytes > budget:
                continue
            budget -= nbytes
            idx[id(node)] = len(recs)
            recs.append(kv_wire.record(pidx, node.chunk, kv))
        if not recs:
            return None
        return kv_wire.encode(recs, self.ecfg.page_size)

    def _page_kv(self, node):
        """One radix node's KV bytes on host: tier-0 pages are gathered
        from the pool (the ``device_get`` waits out pending programs —
        callers fence or run at drain/idle), spilled tiers already hold
        host bytes."""
        if node.tier == 0:
            kp, vp = self._gather_page_fn(
                self.k_cache, self.v_cache,
                self._gr(np.int32(node.page)))
            return jax.device_get((kp, vp))
        return node.host.kv

    def import_prefixes(self, blob) -> int:
        """Install a tier-2 fleet snapshot as tier-1 nodes backed by the
        host arena, stopping at arena capacity. Existing nodes are kept
        (never downgraded) and reused as parents. MIRRORED: the import
        mutates replay-relevant tree state, so followers install the
        identical blob at the identical call-stream position. Returns
        pages imported (0 when radix/arena off, bad blob, or geometry
        mismatch — a snapshot is a warm start, never a failure)."""
        if self._radix is None or self._arena is None or not blob:
            return 0
        from . import kv_wire
        try:
            recs = kv_wire.decode(blob, self.ecfg.page_size)
        except kv_wire.WireError:
            return 0
        want = kv_wire.cache_spec(self.k_cache, self.v_cache)
        imported = 0
        by_idx: List[Any] = []
        for rec in recs:
            p = int(rec.get("p", -1))
            parent = None
            if p >= 0:
                parent = by_idx[p]    # decode guarantees p < this index
                if parent is None:
                    by_idx.append(None)
                    continue
            chunk = tuple(int(t) for t in rec["c"])
            node = self._radix.child(parent, chunk)
            if node is None:
                kv = (rec["k"], rec["v"])
                if (kv_wire.kv_spec(kv) != want
                        or not self._arena.room_for(1)):
                    by_idx.append(None)
                    continue
                node = self._radix.insert_host(
                    parent, chunk, self._arena.store(kv, snapshot=True))
                imported += 1
            by_idx.append(node)
        return imported

    # ------------------------------------------------------------------
    # disaggregated prefill→decode KV transfer (ISSUE 20)
    # ------------------------------------------------------------------
    def export_request_kv(self, full_ids,
                          max_bytes: int = 64 << 20) -> Optional[bytes]:
        """Serialize the radix-cached KV chain for one request's token
        ids (the prefill side of a disagg handoff). Only FULL quiescent
        pages ship — the epoch fence runs first so the gathers can never
        race an in-flight program; the partial boundary page travels as
        a token tail the decode side re-extends through chunked prefill
        (bit-identical by construction). A byte-budget cut stops at the
        cut (never skips) so the shipped chain stays rooted. None when
        the radix cache is off or holds nothing for these ids.
        Leader-side only — callers gate on single-host serving."""
        if self._radix is None:
            return None
        FAULTS.check("pages.export")
        from . import kv_wire
        # lint: allow(host-sync-hot-path): token ids arrive as host lists
        ids = np.asarray(full_ids, np.int32)
        if int(ids.shape[0]) < 2:
            return None
        full, _part, _q = self._radix.match(ids, int(ids.shape[0]) - 1,
                                            bump=False)
        if not full:
            return None
        self.fence_quiesce()
        budget = int(max_bytes)
        recs: List[Dict[str, Any]] = []
        for i, node in enumerate(full):
            kv = self._page_kv(node)
            nbytes = kv_wire.kv_nbytes(kv)
            if nbytes > budget:
                break
            budget -= nbytes
            recs.append(kv_wire.record(i - 1, node.chunk, kv))
        if not recs:
            return None
        return kv_wire.encode(recs, self.ecfg.page_size)

    def import_request_kv(self, blob) -> int:
        """Install a transferred request chain into the LIVE pool and
        radix tree at tier 0 (the decode side of a disagg handoff):
        each page is uploaded into a freshly pinned pool page and
        grafted via ``insert_page``, so the very next stitch serves the
        prefix HBM-hot. Chunks already resident at tier 0 are kept;
        spilled chunks are promoted onto the transferred bytes. Stops
        (keeping the rooted prefix) at a geometry mismatch or a dry
        pool after one eviction attempt per page. Returns pages
        imported/promoted; 0 on a bad blob — a transfer is a warm
        start, never a failure (the caller re-prefills the miss)."""
        if self._radix is None or not blob:
            return 0
        FAULTS.check("pages.import")
        from . import kv_wire
        try:
            recs = kv_wire.decode(blob, self.ecfg.page_size)
        except kv_wire.WireError:
            return 0
        want = kv_wire.cache_spec(self.k_cache, self.v_cache)
        parent = None
        imported = 0
        for i, rec in enumerate(recs):
            if int(rec.get("p", -1)) != i - 1:
                break         # a request transfer is ONE rooted chain
            chunk = tuple(int(t) for t in rec["c"])
            node = self._radix.child(parent, chunk)
            if node is not None and node.tier == 0:
                parent = node
                continue      # already HBM-hot here: nothing to upload
            kv = (rec["k"], rec["v"])
            if kv_wire.kv_spec(kv) != want:
                break
            if not self._pt.n_free:
                self.radix_evict(1)
            pg = self._pt.alloc_pinned()
            if pg is None:
                break         # pool dry: keep the rooted prefix we got
            kp = jax.tree_util.tree_map(self._gr, kv[0])
            vp = jax.tree_util.tree_map(self._gr, kv[1])
            self.k_cache, self.v_cache = self._upload_page_fn(
                self.k_cache, self.v_cache, kp, vp, self._gr(np.int32(pg)))
            parent = self._radix.insert_page(parent, chunk, pg)
            imported += 1
        if imported and self._arena is not None:
            # promotions over spilled chunks retired their host bytes
            self._arena.free_all(self._radix.take_dropped_hosts())
        return imported

    @property
    def quarantined_pages(self) -> int:
        """Pages fenced in the page-table quarantine (0 when dense)."""
        return self._pt.quarantined if self.paged else 0

    def fence_quiesce(self) -> int:
        """Materialise every launched device program, then drain the page
        quarantine entirely; returns the number of pages reclaimed.
        Dense engines: no-op. Device programs are serialized by their
        donated cache data dependencies, so blocking on the latest
        ``lengths`` output proves no in-flight program can still read any
        quarantined page through a captured block table. MIRRORED across
        hosts (each blocks on its OWN devices), so callers must invoke it
        only at deterministic call-stream positions guarded by
        deterministic state — e.g. ``quarantined_pages > 0`` — never from
        timing-dependent branches."""
        if not self.paged:
            return 0
        jax.block_until_ready(self.lengths)
        return self._pt.drain_quarantine()

    def decode_n(self, n: Optional[int] = None) -> np.ndarray:
        """n decode steps in one device program; returns tokens [n, B].

        One dispatch + one host sync per call — the per-step host
        round-trip (expensive under a remote-TPU tunnel) amortises over
        the chunk. For UNCONSTRAINED slots chunk semantics are identical
        to n decode() calls; grammar-constrained slots freeze after the
        first step (see ``step_budgets``) — only row 0 of their toks_n
        column is real, rows >= 1 are stale-mask resamples the caller
        must discard (the scheduler does).
        Paged mode: callers that want preemption-on-pool-dry run
        ``prepare_decode`` themselves first and requeue the victims; here
        a dry pool raises (tests/bench size their pools adequately)."""
        handle = self.decode_n_launch(n)
        toks = handle.wait()
        if self.paged:
            # synchronous flow self-retires: the program just
            # materialised, so its quarantined pages are reclaimable NOW
            # and epoch == retired at every free point — sync paged mode
            # keeps exactly its pre-fence free-list order (and followers
            # replay this call, waiting on their own devices, so the
            # retirement is lockstep across hosts)
            self._pt.retire_epoch(handle.epoch)
        return toks

    def decode_n_launch(self, n: Optional[int] = None,
                        retire: Optional[int] = None,
                        drafts: Optional[np.ndarray] = None
                        ) -> DecodeHandle:
        """Launch one decode dispatch WITHOUT materialising its tokens:
        slot state (host lengths included) advances immediately; the
        returned handle's wait() fetches [n, B]. Double-buffering
        callers launch dispatch N+1 before waiting on N so fan-out work
        overlaps device compute (see DecodeHandle).

        ``drafts`` [B, k] switches the dispatch to the fused speculative
        draft+verify program (prompt-lookup decoding): ONE dispatch
        scores k+1 positions per slot, greedy-accepts each eligible
        slot's longest matching draft prefix plus a bonus token, and
        advances every other slot exactly one decode-identical token —
        rejection costs a sentinel mask and a host-length rollback
        (``spec_ack``), never a second dispatch or a KV copy. wait()
        then returns [k+1, B] sentinel-padded rows and fills the
        handle's ``accepted`` counts. Zeros are fine for slots with
        nothing to propose; this is the ONLY speculative entry point
        (the standalone decode_spec surface is gone).

        Paged mode: each successful launch advances the page-table
        dispatch epoch; ``retire`` (the ``.epoch`` of the newest handle
        the caller has ALREADY waited on) first unfences pages
        quarantined at or before that epoch, making them allocatable for
        this very launch. The kwarg rides the multi-host mirror
        broadcast, so followers retire at the identical call-stream
        position without ever waiting on a handle themselves.
        Speculative launches need no extra fence states: draft tokens
        write into pages already mapped by prepare_decode, and the
        accept mask only moves ``lengths``."""
        FAULTS.check("engine.step")
        t0 = time.perf_counter()
        if drafts is not None:
            # lint: allow(host-sync-hot-path): draft tokens are host ints
            return self._spec_launch(np.asarray(drafts, np.int32),
                                     retire, t0)
        n = n or self.ecfg.decode_chunk
        if self.paged and retire is not None:
            self._pt.retire_epoch(retire)
        victims = self.prepare_decode(n)
        if victims:
            from .paged import PagesExhausted
            raise PagesExhausted(f"pool dry; victims {victims}")
        exe = self._decode_n_exec(n, self._attn_bucket(n))
        budgets = self.step_budgets(n)
        (toks_n, self.k_cache, self.v_cache, self.lengths, self.counts,
         self.last_tokens, self.pring, self.mu, self.keys,
         self._gstate) = exe(
            self.params, self.k_cache, self.v_cache, self.lengths,
            self.counts, self.last_tokens, self.pring, self.mu, self.sp,
            self.keys, self._active_dev, self.mask_bits, self._constr_dev,
            self._rln_dev, self._gstate, self._gmask_dev,
            self._gtrans_dev, self._tables_dev(),
            self._g(budgets, self._slot_sh))
        self._host_lengths[self.active] += budgets[self.active]
        # stamp AFTER the successful launch: a raise above leaves the
        # epoch untouched, so later frees aren't fenced behind a program
        # that never existed
        epoch = self._pt.advance_epoch() if self.paged else 0
        return DecodeHandle(self, toks_n, t0, epoch)

    def _spec_exec(self, k: int, attn_len: int):
        key = (k, attn_len)
        exe = self._spec_execs.get(key)
        if exe is None:
            self._note_compile("spec", key)
            drafts = self._g(np.zeros((self.n_slots, k), np.int32),
                             self._slot_sh2)
            flags = self._g(np.zeros((self.n_slots,), np.int32),
                            self._slot_sh)
            exe = self._spec_fn.lower(
                self.params, self.k_cache, self.v_cache, self.lengths,
                self.counts, self.last_tokens, self.pring, self.mu,
                self.sp, self.keys, self._active_dev, self.mask_bits,
                self._constr_dev, self._rln_dev, self._gstate,
                self._gmask_dev, self._gtrans_dev, flags, drafts,
                attn_len, self._tables_dev()).compile()
            self._spec_execs[key] = exe
        return exe

    def _spec_flags(self) -> np.ndarray:
        """Per-slot eligibility for exact speculative acceptance:
        acceptance compares raw argmax, so it is exact ONLY for active,
        unconstrained, greedy slots with neutral penalties (sample()
        would otherwise adjust logits by the evolving counts); everyone
        else takes the single-token sampled path inside the same
        dispatch. Derived from host-mirrored slot state alone, so every
        host computes identical flags at the same call-stream
        position."""
        flags = np.zeros((self.n_slots,), np.int32)
        for s in range(self.n_slots):
            if not self.active[s] or self._constrained[s]:
                continue
            o = self._opts.get(s, SlotOptions())
            if (o.temperature <= 0.0 and o.repeat_penalty == 1.0
                    and o.presence_penalty == 0.0
                    and o.frequency_penalty == 0.0):
                flags[s] = 1
        return flags

    def _spec_launch(self, drafts: np.ndarray, retire: Optional[int],
                     t0: float) -> DecodeHandle:
        """Fused speculative dispatch body (see decode_n_launch).

        Host lengths advance by each slot's UPPER BOUND (k+1 for
        eligible slots, 1 for the rest) at launch — the accept counts
        are still device-side futures, and followers replay launches
        without waiting, so the advance must be deterministic from the
        call args alone. Over-estimation is safe everywhere host
        lengths are read (attention buckets grow monotonically with
        them; prepare_decode maps at most one page early); the caller
        reconciles to the exact value by passing the waited handle's
        overshoot back through ``spec_ack``, which rides the broadcast
        stream like ``retire`` does."""
        assert self.sp_size == 1, \
            "speculative decode: bucketed caches only (no sp meshes)"
        assert not (self.paged and self._paged_dp > 1), \
            "speculative decode: the paged dp-manual region is T=1 only"
        k = int(drafts.shape[1])  # lint: allow(host-sync-hot-path): shape read of a host array
        assert k >= 1, "need at least one draft column"
        n = k + 1
        if self.paged and retire is not None:
            self._pt.retire_epoch(retire)
        victims = self.prepare_decode(n)
        if victims:
            from .paged import PagesExhausted
            raise PagesExhausted(f"pool dry; victims {victims}")
        flags = self._spec_flags()
        exe = self._spec_exec(k, self._attn_bucket(n))
        (toks, self.k_cache, self.v_cache, self.lengths, self.counts,
         self.last_tokens, self.pring, self.mu, self.keys,
         self._gstate) = exe(
            self.params, self.k_cache, self.v_cache, self.lengths,
            self.counts, self.last_tokens, self.pring, self.mu, self.sp,
            self.keys, self._active_dev, self.mask_bits, self._constr_dev,
            self._rln_dev, self._gstate, self._gmask_dev,
            self._gtrans_dev, self._g(flags, self._slot_sh),
            self._g(drafts, self._slot_sh2), self._tables_dev())
        # inactive slots get budget 0, not 1: they neither advance at
        # launch nor emit, so their rollback is exactly zero — a slot
        # that goes inactive AND is re-admitted between launch and ack
        # must never absorb the old occupant's overshoot
        budgets = np.where(self.active,
                           np.where(flags == 1, n, 1), 0).astype(np.int32)
        self._host_lengths[self.active] += budgets[self.active]
        epoch = self._pt.advance_epoch() if self.paged else 0
        return DecodeHandle(self, toks, t0, epoch, budgets=budgets)

    def spec_ack(self, rollback: np.ndarray) -> None:
        """Reconcile host lengths after a speculative dispatch
        materialises: subtract the per-slot overshoot (launch budget
        minus tokens actually emitted — the rejected draft tail). Called
        by the scheduler right after wait() and BEFORE any release/admit
        can reuse a slot; MIRRORED, so followers roll back at the same
        call-stream position without ever waiting themselves. Slots
        released since launch are masked out (their lengths were already
        reset), and the clamp keeps a stale ack from ever driving a
        length negative."""
        rb = np.asarray(rollback, np.int64)  # lint: allow(host-sync-hot-path): rollback vector is host numpy
        rb = np.minimum(np.where(self.active, rb, 0), self._host_lengths)
        self._host_lengths -= rb

    def step_budgets(self, n: int) -> np.ndarray:
        """Per-slot decode-step budget for a chunk of ``n``: HOST-masked
        constrained slots advance one token per dispatch (their PDA mask
        refreshes on the host between dispatches); device-grammar slots
        and everyone else take the full chunk — the device table refreshes
        their mask per step, and an on-device escape freezes the slot so
        the overshoot rolls back through spec_ack."""
        host_masked = self._constrained & ~self._gdev_mode
        return np.where(host_masked, 1, n).astype(np.int32)

    def release(self, slot: int, park: bool = False):
        """Free ``slot``. With ``park=True`` the KV cache and slot state
        are left in place so a later ``extend`` can reuse the prefix (the
        slot still counts as free and may be overwritten by any admit)."""
        self.clear_mask(slot)
        self.active[slot] = False
        self._opts.pop(slot, None)
        self._active_dev = self._g(self.active.astype(np.int32),
                                   self._slot_sh)
        if park and self.supports_extend:
            # paged: the parked prefix keeps its pages until an admit
            # overwrites the slot or the scheduler evicts via
            # free_slot_pages under pool pressure
            return
        if self.paged:
            self._pt.release(slot)
        self._host_lengths[slot] = 0
        self._repeat_n[slot] = max(1, self.ecfg.repeat_last_n)
        self._rln_dev = self._g(self._repeat_n, self._slot_sh)
        (self.lengths, self.counts, self.last_tokens, self.pring,
         self.mu) = self._release_fn(
            self.lengths, self.counts, self.last_tokens, self.pring,
            self.mu, self._gr(np.int32(slot)))

    def slot_length(self, slot: int) -> int:
        return int(self._fetch(self.lengths)[slot])

    @property
    def kv_bytes(self) -> int:
        leaves = jax.tree_util.tree_leaves((self.k_cache, self.v_cache))
        return sum(l.size * l.dtype.itemsize for l in leaves)
