"""Typed request-validation error for the serving stack.

The HTTP layer maps ``BadRequest`` to 400. Internal ``ValueError``s (jax,
numpy, bugs) are NOT caught as client errors — they surface as 500s, so
server defects aren't silently reclassified as bad requests (round-1
advisor finding on server/app.py's blanket ValueError handler).
"""


class BadRequest(ValueError):
    """The request is malformed or unsatisfiable; client's fault (HTTP 400)."""


class DeadlineExceeded(RuntimeError):
    """A request ran out of wall-clock budget (``deadline_ms``).

    ``while_queued`` distinguishes the two HTTP mappings: a request shed
    before it ever held a slot maps to 503 + ``Retry-After`` (the caller
    lost nothing and should retry elsewhere); a request cut off
    mid-generation maps to a terminal stream frame with finish reason
    ``timeout`` (partial output was already sent) or 504 pre-stream.
    """

    def __init__(self, msg: str, *, while_queued: bool, retry_after_s: int = 1):
        super().__init__(msg)
        self.while_queued = while_queued
        self.retry_after_s = retry_after_s


class FollowerLost(RuntimeError):
    """A multi-host follower connection died; the world is degraded.

    Raised by ``ControlPlane.broadcast`` instead of desyncing the
    leader/follower worlds mid-dispatch. The serving layer maps it to a
    500; recovery is a pod-level restart of the replica group.
    """
