"""Typed request-validation error for the serving stack.

The HTTP layer maps ``BadRequest`` to 400. Internal ``ValueError``s (jax,
numpy, bugs) are NOT caught as client errors — they surface as 500s, so
server defects aren't silently reclassified as bad requests (round-1
advisor finding on server/app.py's blanket ValueError handler).
"""


class BadRequest(ValueError):
    """The request is malformed or unsatisfiable; client's fault (HTTP 400)."""
