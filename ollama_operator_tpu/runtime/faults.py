"""Deterministic fault injection for crash-only serving tests.

Every recovery path in the runtime (supervised engine restarts, request
deadlines, follower loss, kube retries) is driven in tests by *real*
injected faults rather than monkeypatched internals.  Code under test
calls ``FAULTS.check("<point>")`` at a named fault point; the check is a
no-op (one dict lookup on an empty dict) unless a rule has been armed
for that point via the test API or the ``TPU_FAULTS`` env var.

Every wired fault point is registered in the introspectable CATALOG
below (``FAULTS.points()``) with its call-site module and a docstring
describing what an armed fail/delay simulates.  The fault-catalog lint
pass (tools/invariant_lint) holds the registry honest: every
``FAULTS.check`` call site in the tree must be catalogued here and every
catalogued point documented in both docs trees' fault-point tables, so
the randomized chaos campaign (runtime/chaos.py) can enumerate the full
fault surface instead of a hand-maintained list.

Trigger specs (the grammar is intentionally tiny):

    fail            -- raise InjectedFault on every hit
    fail:once       -- raise on the first hit, then disarm the point
    fail:n=K        -- raise on the first K hits, then disarm
    fail:every=K    -- raise on every K-th hit (hit K, 2K, ...)
    fail:after=K    -- pass K hits, then raise on every later hit
    delay:50ms      -- sleep 50ms on every hit (also: delay:0.2s)
    delay:50ms:once / :n=K / :every=K / :after=K
                    -- delays take the same trigger modes as fail, so a
                       drill can wedge exactly one dispatch

Env arming: ``TPU_FAULTS="engine.step=fail:once,kube.request=delay:10ms"``.
Stdlib only; no dependency on jax so the operator can import it too.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class FaultPoint:
    """One registered fault point: where it is wired and what it models."""

    name: str
    site: str    # repo-relative module holding the FAULTS.check call
    doc: str


CATALOG: Dict[str, FaultPoint] = {}


def point(name: str, site: str, doc: str) -> FaultPoint:
    """Register a fault point in the catalog (duplicate names are a bug)."""
    if name in CATALOG:
        raise ValueError(f"fault point {name!r} already registered")
    fp = FaultPoint(name, site, " ".join(doc.split()))
    CATALOG[name] = fp
    return fp


point("engine.step", "ollama_operator_tpu/runtime/engine.py",
      """Top of Engine.decode_n_launch (the decode hot loop; covers sync
      decode_n too). In paged+async mode fires BEFORE the launch advances
      the dispatch epoch — the chaos drills assert the supervised restart
      drains the page quarantine and errors the in-flight dispatch's
      owners exactly once.""")
point("engine.admit", "ollama_operator_tpu/runtime/engine.py",
      """Top of Engine.admit (prefill/admission). An armed fail is a
      per-request error, never a loop failure: no restart, next request
      admits fine.""")
point("engine.watchdog", "ollama_operator_tpu/runtime/scheduler.py",
      """Inside the scheduler's watchdog-bounded dispatch wait, ON the
      waiter thread; an armed delay:Nms simulates a wedged device (the
      wait stalls, the watchdog fires, supervised restart + replay).""")
point("scheduler.replay", "ollama_operator_tpu/runtime/scheduler.py",
      """Per replayable stream in _fail_running restart classification;
      an armed fail forces the stream down the fail-safe exactly-once
      error path (fallback cause="faulted").""")
point("pages.alloc", "ollama_operator_tpu/runtime/paged.py",
      """PageTable.grow page allocation; an armed fail makes grow return
      False (simulated pool exhaustion) so callers exercise their REAL
      dry-pool paths (preempt/evict/cold-fallback).""")
point("pages.spill", "ollama_operator_tpu/runtime/engine.py",
      """Per page in Engine.radix_evict, before the device gather that
      moves an evicted radix page's KV bytes to the host arena; an armed
      fail skips the spill and the page is plainly freed (tierless
      eviction), never an engine failure.""")
point("pages.restitch", "ollama_operator_tpu/runtime/engine.py",
      """Per page in Engine.stitch, before a tier-1 host page is
      uploaded back into HBM; an armed fail aborts the stitch — the slot
      is released pageless and the scheduler's existing dry-pool path
      admits the request as a clean cold prefill (already-promoted pages
      stay valid: their uploads were enqueued).""")
point("detok.feed", "ollama_operator_tpu/runtime/service.py",
      """Service detokeniser feed, per chunk; an armed fail errors one
      stream without touching the engine.""")
point("admission.predict", "ollama_operator_tpu/runtime/admission.py",
      """admission.predict_queue_wait_s (the TTFT queue model); an armed
      fail proves the predictor fails OPEN — requests are admitted and
      covered by the deadline machinery, never 500ed.""")
point("follower.send", "ollama_operator_tpu/runtime/follower.py",
      """ControlPlane broadcast send to each follower conn; an armed fail
      is caught like a socket error and degrades the world (FollowerLost),
      an armed delay models a stalled follower eating backpressure.""")
point("kube.request", "ollama_operator_tpu/operator/client.py",
      """KubeClient._request before the HTTP call; read-only GETs retry
      transparently, writes surface the typed ApiError.""")
point("operator.scrape", "ollama_operator_tpu/operator/client.py",
      """client.fetch_replica_ps before the replica /api/ps GET; an armed
      fail collapses the scrape to None exactly like a network fault, an
      armed delay stalls like a slow pod — the control loops must hold
      their last decision (fail static) instead of acting on the hole.""")
point("gateway.route", "ollama_operator_tpu/operator/gateway.py",
      """After the gateway has picked a replica but before the request is
      dispatched to it; an armed fail counts as a replica failure
      (circuit feeding), an armed delay models a slow proxy hop.""")
point("gateway.stream", "ollama_operator_tpu/operator/gateway.py",
      """Per upstream response chunk inside the gateway's stream pump; an
      armed fail severs the upstream mid-stream exactly like a replica
      death (the failover drills ride this), an armed delay models a
      stalling replica.""")
point("pages.export", "ollama_operator_tpu/runtime/engine.py",
      """Top of Engine.export_request_kv, before any page is gathered
      for a disagg handoff; an armed fail surfaces as a failed
      /api/kv_export — the gateway downgrades the handoff to journal
      replay on the decode pool, never a client error. An armed delay
      models a slow transfer link.""")
point("pages.import", "ollama_operator_tpu/runtime/engine.py",
      """Top of Engine.import_request_kv, before any page is allocated
      on the decode side of a disagg transfer; an armed fail leaves
      the page table untouched (check() stays clean) and the decode
      replica simply re-prefills the prompt — a transfer is a warm
      start, never a correctness dependency.""")
point("gateway.handoff", "ollama_operator_tpu/operator/gateway.py",
      """Between the prefill replica's first-token handoff frame and
      the decode-pool KV import dispatch; an armed fail kills the
      handoff orchestration mid-flight — replayable streams must fall
      back to journal replay on the decode pool with zero client error
      frames, an armed delay models a saturated transfer link.""")


class InjectedFault(RuntimeError):
    """Raised by an armed ``fail`` rule at a fault point."""

    def __init__(self, point: str, spec: str):
        super().__init__(f"injected fault at {point!r} ({spec})")
        self.point = point
        self.spec = spec


def _parse_mode(spec: str, arg: str) -> Tuple[str, float]:
    """Shared trigger-mode grammar: '' | once | n=K | every=K | after=K."""
    if not arg:
        return "always", 0.0
    if arg == "once":
        return "n", 1.0
    mode, _, val = arg.partition("=")
    if mode in ("n", "every", "after") and val:
        k = int(val)
        if k < 1:
            raise ValueError(f"fault spec {spec!r}: count must be >= 1")
        return mode, float(k)
    raise ValueError(f"unknown fault spec {spec!r}")


def _parse_spec(spec: str) -> Tuple[str, Optional[str], float, float]:
    """Return (kind, mode, count, seconds): kind in {fail, delay};
    ``seconds`` is the sleep for delay rules (0 for fail)."""
    spec = spec.strip()
    kind, _, arg = spec.partition(":")
    kind = kind.strip()
    arg = arg.strip()
    if kind == "fail":
        mode, count = _parse_mode(spec, arg)
        return "fail", mode, count, 0.0
    if kind == "delay":
        dur, _, modearg = arg.partition(":")
        dur = dur.strip()
        if dur.endswith("ms"):
            seconds = float(dur[:-2]) / 1000.0
        elif dur.endswith("s"):
            seconds = float(dur[:-1])
        else:
            raise ValueError(f"delay spec {spec!r} needs a ms/s suffix")
        mode, count = _parse_mode(spec, modearg.strip())
        return "delay", mode, count, seconds
    raise ValueError(f"unknown fault spec {spec!r}")


class FaultInjector:
    """Registry of armed fault rules, keyed by fault-point name."""

    def __init__(self):
        self._lock = threading.Lock()
        # point -> (spec string, kind, mode, count, seconds)
        self._rules: Dict[str, Tuple[str, str, str, float, float]] = {}
        self._counts: Dict[str, int] = {}

    def arm(self, point: str, spec: str) -> None:
        rule = _parse_spec(spec)
        with self._lock:
            self._rules[point] = (spec, *rule)
            self._counts[point] = 0

    def disarm(self, point: str) -> None:
        with self._lock:
            self._rules.pop(point, None)

    def reset(self) -> None:
        with self._lock:
            self._rules.clear()
            self._counts.clear()

    def hits(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    def points(self) -> List[FaultPoint]:
        """The full registered fault-point catalog, sorted by name."""
        return [CATALOG[n] for n in sorted(CATALOG)]

    def check(self, point: str) -> None:
        """Call at a fault point. No-op unless a rule is armed for it."""
        if not self._rules:  # fast path: nothing armed anywhere
            return
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            spec, kind, mode, count, seconds = rule
            if mode == "always":
                fire = True
            elif mode == "n":
                fire = n <= count
                if n >= count:
                    del self._rules[point]
            elif mode == "every":
                fire = n % int(count) == 0
            else:  # after
                fire = n > count
        # act outside the lock so a sleep never blocks other points
        if kind == "fail":
            if fire:
                # flight-recorder breadcrumb BEFORE the raise: a chaos
                # drill's post-mortem dump must show the injected fault
                # ahead of the failure cascade it triggers
                from .trace import FLIGHT
                FLIGHT.record("fault_injected", point=point, spec=spec,
                              hit=n)
                raise InjectedFault(point, spec)
            return
        if fire and seconds > 0:
            from .trace import FLIGHT
            FLIGHT.record("fault_injected", point=point, spec=spec,
                          hit=n)
            time.sleep(seconds)

    def arm_from_env(self, env: str = "TPU_FAULTS") -> None:
        raw = os.environ.get(env, "")
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            point, _, spec = part.partition("=")
            self.arm(point.strip(), spec.strip())


FAULTS = FaultInjector()
FAULTS.arm_from_env()
