"""Deterministic fault injection for crash-only serving tests.

Every recovery path in the runtime (supervised engine restarts, request
deadlines, follower loss, kube retries) is driven in tests by *real*
injected faults rather than monkeypatched internals.  Code under test
calls ``FAULTS.check("<point>")`` at a named fault point; the check is a
no-op (one dict lookup on an empty dict) unless a rule has been armed
for that point via the test API or the ``TPU_FAULTS`` env var.

Fault points wired through the codebase:

    engine.step     -- top of ``Engine.decode_n_launch`` (the decode hot
                       loop; covers sync ``decode_n`` too, and in
                       paged+async mode fires BEFORE the launch advances
                       the dispatch epoch — the chaos drills assert the
                       restart drains the page quarantine and errors the
                       in-flight dispatch's owners exactly once)
    engine.admit    -- top of ``Engine.admit`` (prefill/admission)
    pages.alloc     -- ``PageTable.grow`` page allocation; an armed fail
                       makes grow return False (simulated pool
                       exhaustion), so callers exercise their REAL
                       dry-pool paths (preempt/evict/cold-fallback)
    detok.feed      -- service detokeniser feed, per chunk
    follower.send   -- ``ControlPlane._send`` to each follower conn
    kube.request    -- ``KubeClient._request`` before the HTTP call
    admission.predict -- ``admission.predict_queue_wait_s`` (the TTFT
                       queue model; an armed fail proves the predictor
                       fails OPEN — requests are admitted and covered
                       by the deadline machinery, never 500ed)
    scheduler.replay -- per replayable stream in ``_fail_running``
                       restart classification; an armed fail forces the
                       stream down the fail-safe exactly-once error
                       path (fallback cause="faulted")
    engine.watchdog -- inside the scheduler's watchdog-bounded dispatch
                       wait, ON the waiter thread; an armed delay:Nms
                       simulates a wedged device (the wait stalls, the
                       watchdog fires, supervised restart + replay)
    operator.scrape -- ``client.fetch_replica_ps`` before the replica
                       /api/ps GET; an armed fail collapses the scrape
                       to None exactly like a network fault (replica
                       reads as unreachable), an armed delay stalls
                       like a slow pod — the autoscaler chaos drills
                       assert the control loop holds its last decision
                       (fails static) instead of scaling on the hole
    gateway.route   -- ``gateway.Gateway`` after a replica has been
                       picked but before the request is dispatched to
                       it; an armed fail makes the dispatch attempt
                       count as a replica failure (circuit feeding),
                       an armed delay models a slow proxy hop
    gateway.stream  -- per upstream response chunk inside the gateway's
                       stream pump; an armed fail severs the upstream
                       mid-stream exactly like a replica death (the
                       failover drills ride this), an armed delay
                       models a stalling replica

Trigger specs (the grammar is intentionally tiny):

    fail            -- raise InjectedFault on every hit
    fail:once       -- raise on the first hit, then disarm the point
    fail:n=K        -- raise on the first K hits, then disarm
    fail:every=K    -- raise on every K-th hit (hit K, 2K, ...)
    fail:after=K    -- pass K hits, then raise on every later hit
    delay:50ms      -- sleep 50ms on every hit (also: delay:0.2s)
    delay:50ms:once / :n=K / :every=K / :after=K
                    -- delays take the same trigger modes as fail, so a
                       drill can wedge exactly one dispatch

Env arming: ``TPU_FAULTS="engine.step=fail:once,kube.request=delay:10ms"``.
Stdlib only; no dependency on jax so the operator can import it too.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple


class InjectedFault(RuntimeError):
    """Raised by an armed ``fail`` rule at a fault point."""

    def __init__(self, point: str, spec: str):
        super().__init__(f"injected fault at {point!r} ({spec})")
        self.point = point
        self.spec = spec


def _parse_mode(spec: str, arg: str) -> Tuple[str, float]:
    """Shared trigger-mode grammar: '' | once | n=K | every=K | after=K."""
    if not arg:
        return "always", 0.0
    if arg == "once":
        return "n", 1.0
    mode, _, val = arg.partition("=")
    if mode in ("n", "every", "after") and val:
        k = int(val)
        if k < 1:
            raise ValueError(f"fault spec {spec!r}: count must be >= 1")
        return mode, float(k)
    raise ValueError(f"unknown fault spec {spec!r}")


def _parse_spec(spec: str) -> Tuple[str, Optional[str], float, float]:
    """Return (kind, mode, count, seconds): kind in {fail, delay};
    ``seconds`` is the sleep for delay rules (0 for fail)."""
    spec = spec.strip()
    kind, _, arg = spec.partition(":")
    kind = kind.strip()
    arg = arg.strip()
    if kind == "fail":
        mode, count = _parse_mode(spec, arg)
        return "fail", mode, count, 0.0
    if kind == "delay":
        dur, _, modearg = arg.partition(":")
        dur = dur.strip()
        if dur.endswith("ms"):
            seconds = float(dur[:-2]) / 1000.0
        elif dur.endswith("s"):
            seconds = float(dur[:-1])
        else:
            raise ValueError(f"delay spec {spec!r} needs a ms/s suffix")
        mode, count = _parse_mode(spec, modearg.strip())
        return "delay", mode, count, seconds
    raise ValueError(f"unknown fault spec {spec!r}")


class FaultInjector:
    """Registry of armed fault rules, keyed by fault-point name."""

    def __init__(self):
        self._lock = threading.Lock()
        # point -> (spec string, kind, mode, count, seconds)
        self._rules: Dict[str, Tuple[str, str, str, float, float]] = {}
        self._counts: Dict[str, int] = {}

    def arm(self, point: str, spec: str) -> None:
        rule = _parse_spec(spec)
        with self._lock:
            self._rules[point] = (spec, *rule)
            self._counts[point] = 0

    def disarm(self, point: str) -> None:
        with self._lock:
            self._rules.pop(point, None)

    def reset(self) -> None:
        with self._lock:
            self._rules.clear()
            self._counts.clear()

    def hits(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    def check(self, point: str) -> None:
        """Call at a fault point. No-op unless a rule is armed for it."""
        if not self._rules:  # fast path: nothing armed anywhere
            return
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            spec, kind, mode, count, seconds = rule
            if mode == "always":
                fire = True
            elif mode == "n":
                fire = n <= count
                if n >= count:
                    del self._rules[point]
            elif mode == "every":
                fire = n % int(count) == 0
            else:  # after
                fire = n > count
        # act outside the lock so a sleep never blocks other points
        if kind == "fail":
            if fire:
                # flight-recorder breadcrumb BEFORE the raise: a chaos
                # drill's post-mortem dump must show the injected fault
                # ahead of the failure cascade it triggers
                from .trace import FLIGHT
                FLIGHT.record("fault_injected", point=point, spec=spec,
                              hit=n)
                raise InjectedFault(point, spec)
            return
        if fire and seconds > 0:
            from .trace import FLIGHT
            FLIGHT.record("fault_injected", point=point, spec=spec,
                          hit=n)
            time.sleep(seconds)

    def arm_from_env(self, env: str = "TPU_FAULTS") -> None:
        raw = os.environ.get(env, "")
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            point, _, spec = part.partition("=")
            self.arm(point.strip(), spec.strip())


FAULTS = FaultInjector()
FAULTS.arm_from_env()
