"""Deterministic fault injection for crash-only serving tests.

Every recovery path in the runtime (supervised engine restarts, request
deadlines, follower loss, kube retries) is driven in tests by *real*
injected faults rather than monkeypatched internals.  Code under test
calls ``FAULTS.check("<point>")`` at a named fault point; the check is a
no-op (one dict lookup on an empty dict) unless a rule has been armed
for that point via the test API or the ``TPU_FAULTS`` env var.

Fault points wired through the codebase:

    engine.step     -- top of ``Engine.decode_n_launch`` (the decode hot
                       loop; covers sync ``decode_n`` too, and in
                       paged+async mode fires BEFORE the launch advances
                       the dispatch epoch — the chaos drills assert the
                       restart drains the page quarantine and errors the
                       in-flight dispatch's owners exactly once)
    engine.admit    -- top of ``Engine.admit`` (prefill/admission)
    pages.alloc     -- ``PageTable.grow`` page allocation; an armed fail
                       makes grow return False (simulated pool
                       exhaustion), so callers exercise their REAL
                       dry-pool paths (preempt/evict/cold-fallback)
    detok.feed      -- service detokeniser feed, per chunk
    follower.send   -- ``ControlPlane._send`` to each follower conn
    kube.request    -- ``KubeClient._request`` before the HTTP call
    admission.predict -- ``admission.predict_queue_wait_s`` (the TTFT
                       queue model; an armed fail proves the predictor
                       fails OPEN — requests are admitted and covered
                       by the deadline machinery, never 500ed)

Trigger specs (the grammar is intentionally tiny):

    fail            -- raise InjectedFault on every hit
    fail:once       -- raise on the first hit, then disarm the point
    fail:n=K        -- raise on the first K hits, then disarm
    fail:every=K    -- raise on every K-th hit (hit K, 2K, ...)
    fail:after=K    -- pass K hits, then raise on every later hit
    delay:50ms      -- sleep 50ms on every hit (also: delay:0.2s)

Env arming: ``TPU_FAULTS="engine.step=fail:once,kube.request=delay:10ms"``.
Stdlib only; no dependency on jax so the operator can import it too.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple


class InjectedFault(RuntimeError):
    """Raised by an armed ``fail`` rule at a fault point."""

    def __init__(self, point: str, spec: str):
        super().__init__(f"injected fault at {point!r} ({spec})")
        self.point = point
        self.spec = spec


def _parse_spec(spec: str) -> Tuple[str, Optional[str], float]:
    """Return (kind, mode, value): kind in {fail, delay}."""
    spec = spec.strip()
    kind, _, arg = spec.partition(":")
    kind = kind.strip()
    arg = arg.strip()
    if kind == "fail":
        if not arg:
            return "fail", "always", 0.0
        if arg == "once":
            return "fail", "n", 1.0
        mode, _, val = arg.partition("=")
        if mode in ("n", "every", "after") and val:
            k = int(val)
            if k < 1:
                raise ValueError(f"fault spec {spec!r}: count must be >= 1")
            return "fail", mode, float(k)
        raise ValueError(f"unknown fail spec {spec!r}")
    if kind == "delay":
        if arg.endswith("ms"):
            return "delay", "always", float(arg[:-2]) / 1000.0
        if arg.endswith("s"):
            return "delay", "always", float(arg[:-1])
        raise ValueError(f"delay spec {spec!r} needs a ms/s suffix")
    raise ValueError(f"unknown fault spec {spec!r}")


class FaultInjector:
    """Registry of armed fault rules, keyed by fault-point name."""

    def __init__(self):
        self._lock = threading.Lock()
        # point -> (spec string, kind, mode, value)
        self._rules: Dict[str, Tuple[str, str, str, float]] = {}
        self._counts: Dict[str, int] = {}

    def arm(self, point: str, spec: str) -> None:
        rule = _parse_spec(spec)
        with self._lock:
            self._rules[point] = (spec, *rule)
            self._counts[point] = 0

    def disarm(self, point: str) -> None:
        with self._lock:
            self._rules.pop(point, None)

    def reset(self) -> None:
        with self._lock:
            self._rules.clear()
            self._counts.clear()

    def hits(self, point: str) -> int:
        with self._lock:
            return self._counts.get(point, 0)

    def check(self, point: str) -> None:
        """Call at a fault point. No-op unless a rule is armed for it."""
        if not self._rules:  # fast path: nothing armed anywhere
            return
        with self._lock:
            rule = self._rules.get(point)
            if rule is None:
                return
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            spec, kind, mode, value = rule
            if kind == "fail":
                if mode == "always":
                    fire = True
                elif mode == "n":
                    fire = n <= value
                    if n >= value:
                        del self._rules[point]
                elif mode == "every":
                    fire = n % int(value) == 0
                else:  # after
                    fire = n > value
            else:  # delay
                fire = True
        # act outside the lock so a sleep never blocks other points
        if kind == "fail":
            if fire:
                # flight-recorder breadcrumb BEFORE the raise: a chaos
                # drill's post-mortem dump must show the injected fault
                # ahead of the failure cascade it triggers
                from .trace import FLIGHT
                FLIGHT.record("fault_injected", point=point, spec=spec,
                              hit=n)
                raise InjectedFault(point, spec)
            return
        if fire and value > 0:
            time.sleep(value)

    def arm_from_env(self, env: str = "TPU_FAULTS") -> None:
        raw = os.environ.get(env, "")
        for part in raw.split(","):
            part = part.strip()
            if not part:
                continue
            point, _, spec = part.partition("=")
            self.arm(point.strip(), spec.strip())


FAULTS = FaultInjector()
FAULTS.arm_from_env()
