"""Multi-host serving control plane: process 0 leads, the rest follow.

A multi-host slice is ONE jax.distributed world (parallel/distributed.py)
— every process must dispatch the SAME compiled programs in the SAME
order or the SPMD collectives deadlock. The reference never faces this:
its replicas are independent single-host servers (SURVEY.md §2.3). Here:

- **process 0** runs the full HTTP server + scheduler. Its engine is
  wrapped in :class:`MirroredEngine`, which broadcasts every
  device-dispatching call (admit / extend / decode_n / release / masks /
  warm) over a TCP control stream BEFORE executing it locally.
- **processes 1..n-1** run :func:`run_follower`: connect to process 0's
  control port, then replay the stream — load the same model from their
  own store (the StatefulSet init container pulled it), build the same
  Engine, execute the same calls with the same (replicated) arguments.
  Ordering is the socket's FIFO; synchronisation is the collectives
  themselves.

All host-side decision state is deterministic by construction: prompt
buckets, page tables, penalty windows, and PRNG seeds derive from the
call arguments alone (engine.py avoids per-process `hash()`), so replayed
calls produce byte-identical device programs and inputs.

Admission/fairness policy state (priority queues, WDRR deficits, tenant
rate buckets, the TTFT queue model — runtime/admission.py) lives on
process 0 ONLY: followers see just the admit/extend/decode calls that
survive admission. Policy decisions must never enter the broadcast
stream — they depend on wall-clock throughput observations that differ
per process and would desynchronise the replay.

The control port is the jax.distributed coordinator's port + 1, rendered
by the operator as TPU_DIST_CONTROL (operator/pod.py).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import sys
import threading
import time
import weakref
from typing import Any, List, Optional

from ..server.metrics import GLOBAL as METRICS
from .errors import FollowerLost
from .faults import FAULTS, InjectedFault
# flight-recorder events here are strictly host-side observability —
# they never enter the broadcast stream, so leader tracing can never
# desync a follower's replay (each process records into its OWN ring)
from .trace import FLIGHT

CONTROL_PORT_OFFSET = 1      # coordinator port + 1

# live control planes for the follower-lag gauge: weakly held so a
# torn-down leader doesn't pin a stale series (same pattern as the
# gateway's per-state replica gauges)
_LIVE_CPS: "weakref.WeakSet[ControlPlane]" = weakref.WeakSet()
METRICS.gauge_fn(
    "tpu_model_follower_lag_seconds",
    lambda: max((cp.lag_s for cp in _LIVE_CPS), default=0.0))


def log(msg: str) -> None:
    print(f"follower-cp: {msg}", file=sys.stderr, flush=True)


def _send(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv(sock: socket.socket) -> Any:
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("control stream closed")
        hdr += chunk
    n = struct.unpack(">I", hdr)[0]
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("control stream closed mid-message")
        buf += chunk
    return pickle.loads(bytes(buf))


class ControlPlane:
    """Process 0's broadcast channel to the followers."""

    def __init__(self, n_followers: int, port: int, bind: str = "0.0.0.0",
                 heartbeat_s: Optional[float] = None):
        self.n = n_followers
        # serializes broadcast+local-dispatch pairs: the follower replays
        # the stream single-threaded in FIFO order, so every leader
        # thread that dispatches SPMD programs (scheduler decode loop,
        # HTTP embed threads, unload) must enter the stream AND the
        # device queue in the same order — holding this lock across both
        # is what guarantees it
        self.dispatch_lock = threading.RLock()
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._ready = threading.Event()
        # set on the first failed send: once any follower is gone the
        # SPMD world cannot make progress (a collective would hang), so
        # every later broadcast fails fast with FollowerLost instead of
        # half-dispatching and desyncing the survivors
        self.degraded = False
        self.degraded_reason: Optional[str] = None
        # bounded send backpressure: a follower whose TCP buffer stays
        # full for longer than this is DEAD, not slow — without the bound
        # one stalled host wedges every dispatch forever. Sends that
        # complete but slowly are the SLOW case: dispatch proceeds and
        # the lag shows up in tpu_model_follower_lag_seconds.
        self.send_timeout_s = float(
            os.environ.get("TPU_CP_SEND_TIMEOUT_S", "20"))
        self.lag_s = 0.0         # slowest send in the latest broadcast
        self._hb_stop = threading.Event()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((bind, port))
        self._srv.listen(n_followers)
        threading.Thread(target=self._accept_loop, daemon=True).start()
        log(f"awaiting {n_followers} follower(s) on :{port}")
        # idle-path failure detection: a dead follower pod otherwise goes
        # unnoticed until the next real dispatch blocks a request. 0
        # disables (tests drive broadcast() directly).
        if heartbeat_s is None:
            heartbeat_s = float(os.environ.get("TPU_CP_HEARTBEAT_S", "10"))
        self.heartbeat_s = heartbeat_s
        if heartbeat_s > 0:
            threading.Thread(target=self._heartbeat_loop,
                             daemon=True).start()
        _LIVE_CPS.add(self)

    def _accept_loop(self):
        while len(self._conns) < self.n:
            try:
                conn, addr = self._srv.accept()
            except OSError:     # listener closed during shutdown
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self.send_timeout_s > 0:
                conn.settimeout(self.send_timeout_s)
            with self._lock:
                self._conns.append(conn)
            log(f"follower connected from {addr} "
                f"({len(self._conns)}/{self.n})")
        self._ready.set()

    def _heartbeat_loop(self):
        self._ready.wait()
        while not self._hb_stop.wait(self.heartbeat_s):
            try:
                with self.dispatch_lock:
                    # the heartbeat rides the same FIFO stream as
                    # mirrored ops — holding dispatch_lock across the
                    # send IS the ordering guarantee
                    # lint: allow(lock-order): FIFO heartbeat send by design
                    self.broadcast(("ping",))
            except FollowerLost:
                return          # degraded is set; nothing left to probe

    def _mark_degraded(self, reason: str) -> FollowerLost:
        if not self.degraded:
            self.degraded = True
            self.degraded_reason = reason
            METRICS.inc("tpu_model_followers_lost_total")
            FLIGHT.record("follower_lost", reason=reason[:200])
            log(f"DEGRADED: {reason}")
        return FollowerLost(reason)

    def broadcast(self, msg: tuple) -> None:
        """FIFO broadcast; blocks until the full follower set has joined
        (a call dispatched before the world is complete would desync).
        A send failure closes the dead conn, marks the world degraded,
        and raises :class:`FollowerLost` — the typed error surfaces to
        the caller instead of a half-dispatched desync."""
        if self.degraded:
            raise FollowerLost(
                f"control plane degraded: {self.degraded_reason}")
        self._ready.wait()
        with self._lock:
            worst = 0.0
            for c in list(self._conns):
                t0 = time.monotonic()
                try:
                    FAULTS.check("follower.send")
                    # serialising sends under _lock is the point — the
                    # per-follower byte streams must not interleave; the
                    # per-conn send timeout (TPU_CP_SEND_TIMEOUT_S) is
                    # the backpressure bound, so a stalled follower can
                    # block a dispatch for at most one window
                    # lint: allow(lock-order): frame send serialised by design
                    _send(c, msg)
                except (OSError, InjectedFault) as e:
                    try:
                        c.close()
                    except OSError:
                        pass
                    self._conns.remove(c)
                    if isinstance(e, socket.timeout):
                        # slow-vs-dead verdict: the kernel buffer stayed
                        # full for the whole window — that is a dead (or
                        # unrecoverably wedged) host, not a slow one
                        raise self._mark_degraded(
                            f"follower send exceeded the "
                            f"{self.send_timeout_s:.0f}s backpressure "
                            f"bound: {e}") from e
                    raise self._mark_degraded(
                        f"send to follower failed: {e}") from e
                worst = max(worst, time.monotonic() - t0)
            # slow-but-alive: the send completed within the bound; the
            # lag gauge is how operators see a follower eating into the
            # backpressure window before it ever trips the bound
            self.lag_s = worst

    def close(self):
        self._hb_stop.set()
        with self._lock:
            for c in self._conns:
                try:
                    c.close()
                except OSError:
                    pass
            self._conns.clear()
        try:
            self._srv.close()
        except OSError:
            pass


class MirroredEngine:
    """Engine proxy for process 0: broadcast-then-execute for every call
    that dispatches a device program or mutates replay-relevant host
    state (page tables). Everything else delegates transparently."""

    MIRRORED = ("admit", "admit_many", "extend", "decode", "decode_n",
                # decode_n_launch is the ONE decode dispatch surface —
                # its drafts= kwarg covers fused speculative dispatches
                # (the standalone decode_spec op is gone); spec_ack
                # reconciles speculative host-length overshoot at the
                # exact call-stream position the leader waited, so
                # followers never need to wait a handle to stay
                # bit-identical
                "decode_n_launch", "spec_ack", "release", "set_mask",
                "clear_mask", "install_grammar", "warm_buckets",
                "free_slot_pages", "prepare_decode",
                # radix prefix cache: stitching/donation/eviction mutate
                # page refcounts and (for COW) dispatch a page copy, so
                # every host must replay them in order; prefix_probe is
                # read-only and deliberately NOT mirrored
                "stitch", "donate_prefix", "radix_evict", "radix_reset",
                # tier-2 prefix snapshot install mutates the radix tree
                # and the host arena (replay-relevant: later stitches
                # branch on tier state); export_prefixes is read-only
                # and deliberately NOT mirrored
                "import_prefixes",
                # epoch fence: quiesce blocks on each host's OWN devices
                # and drains that host's quarantine — replayed at the
                # same call-stream position, every host's free list
                # stays bit-identical (DecodeHandle.wait, which followers
                # never run, deliberately does NOT retire epochs)
                "fence_quiesce")

    def __init__(self, inner, cp: ControlPlane):
        object.__setattr__(self, "_inner", inner)
        object.__setattr__(self, "_cp", cp)

    def __getattr__(self, name):
        value = getattr(self._inner, name)
        if name in self.MIRRORED:
            cp = self._cp

            def mirrored(*a, __value=value, __name=name, **kw):
                with cp.dispatch_lock:
                    cp.broadcast(("call", __name, a, kw))
                    return __value(*a, **kw)
            return mirrored
        return value


def control_address(env=None) -> Optional[tuple]:
    """(host, port) of the control stream, from the operator env:
    TPU_DIST_CONTROL if present, else coordinator host at port+1."""
    import os
    e = env if env is not None else os.environ
    ctl = e.get("TPU_DIST_CONTROL")
    if ctl:
        host, _, port = ctl.rpartition(":")
        return host, int(port)
    coord = e.get("TPU_DIST_COORDINATOR")
    if not coord:
        return None
    host, _, port = coord.rpartition(":")
    return host, int(port) + CONTROL_PORT_OFFSET


def run_follower(manager, host: str, port: int,
                 health_port: Optional[int] = None) -> None:
    """Replay the leader's stream forever (process_index > 0).

    ``manager`` is a follower-mode ModelManager (server/app.py): load()
    builds a bare Engine — no scheduler, no HTTP app — against the same
    store this pod's init container populated."""
    if health_port:
        _serve_health(health_port)
    sock = None
    for attempt in range(240):       # leader may still be compiling
        try:
            sock = socket.create_connection((host, port), timeout=10)
            break
        except OSError:
            time.sleep(2.0)
    if sock is None:
        raise ConnectionError(f"leader control port {host}:{port} "
                              f"unreachable")
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    # silent-leader watchdog: the leader's heartbeat guarantees traffic
    # every TPU_CP_HEARTBEAT_S, so a recv gap past this bound means the
    # leader is dead or partitioned away. Fail static to a CLEAN exit
    # instead of hanging on the broadcast socket forever — the pod
    # restarts and rejoins the next world. 0 disables (tests drive the
    # stream by hand).
    leader_timeout_s = float(os.environ.get("TPU_CP_LEADER_TIMEOUT_S",
                                            "60"))
    if leader_timeout_s > 0:
        sock.settimeout(leader_timeout_s)
    log(f"joined control stream {host}:{port}")
    engine = None
    while True:
        try:
            msg = _recv(sock)
        except socket.timeout:
            # lint: allow(follower-purity): own per-process metrics — local observability, never broadcast back
            METRICS.inc("tpu_model_leader_lost_total")
            # lint: allow(follower-purity): own per-process flight ring — local diagnosis, never broadcast back
            FLIGHT.record("leader_lost", timeout_s=leader_timeout_s)
            log(f"leader silent for {leader_timeout_s:g}s "
                f"(TPU_CP_LEADER_TIMEOUT_S) — failing static, clean exit")
            return
        op = msg[0]
        if op == "ping":
            continue             # leader heartbeat; liveness only
        if op == "load":
            lm = manager.load(msg[1])
            engine = lm.engine
            log(f"loaded {msg[1]}")
        elif op == "unload":
            manager.unload_now()
            engine = None
        elif op == "lm_call":
            _, method, a = msg
            try:
                getattr(manager.loaded, method)(*a)
            except Exception as e:   # noqa: BLE001
                log(f"replayed lm {method} raised {type(e).__name__}: {e}")
        elif op == "call":
            _, method, a, kw = msg
            try:
                getattr(engine, method)(*a, **kw)
            except Exception as e:   # noqa: BLE001
                # deterministic failures (PagesExhausted, too-long prompt)
                # happen on the leader too, BEFORE any device dispatch —
                # replaying them (incl. their page-table side effects)
                # keeps host state in lockstep; anything else will show
                # up here loudly and then desync visibly
                # lint: allow(follower-purity): own per-process flight ring — local diagnosis, never broadcast back
                FLIGHT.record("replay_error", method=method,
                              error=f"{type(e).__name__}: {e}"[:200])
                log(f"replayed {method} raised {type(e).__name__}: {e}")
        elif op == "shutdown":
            log("leader shut down")
            return
        else:
            raise ValueError(f"unknown control op {op!r}")


def _serve_health(port: int) -> None:
    """Minimal /healthz endpoint so the follower pod's readinessProbe
    (same template as the leader's) reports Ready."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.end_headers()
            self.wfile.write(b"ok")

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("0.0.0.0", port), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
