"""Tier-1 host-RAM arena for spilled radix-tree KV pages.

The radix prefix cache (runtime/radix.py) lives in the HBM page pool, so
a prefix survives exactly as long as page pressure allows — minutes of
multi-turn chat working set against seconds of HBM residency.  This
module adds the host tier: when the engine's LRU eviction would free a
quiescent tree page, it instead gathers the page across all layers
(one jitted dynamic-slice program), ``device_get``s the bytes into a
bounded host arena, and tags the radix node ``tier=1``.  A later hit on
that node *restitches* — a host→HBM ``dynamic_update_slice`` upload is
enqueued per page (JAX async dispatch overlaps it with the tail
chunked-prefill), the node is promoted back to tier 0 and its fresh
page re-enters normal refcount sharing.  SGLang's HiCache / vLLM's CPU
offload connector play this role in the reference stacks.

The arena is pure host bookkeeping:

- **Bounded** by ``TPU_HOST_CACHE_GB`` (fractional GiB accepted; 0
  disables the tier entirely and eviction frees pages exactly as
  before).  When ``store`` would overflow, the engine first drops
  least-recently-used tier-1 entries; if the arena is still full the
  page is plainly freed.
- **Accounted** by real bytes (``sum(leaf.nbytes)`` of the gathered
  page tree), so int8/int4 quantised pools automatically fit ~4-8x more
  spilled pages than f32 pools.
- **Deterministic**: spill/restitch decisions depend only on mirrored
  host state (epoch fence, tree stamps) and environment knobs, so
  multi-host follower replay takes identical branches at identical
  call-stream positions.

Break-even model (PR 10's FLOPs accounting): restitching ``n`` tokens
costs ``n_bytes / (TPU_HOST_CACHE_BW_GBPS · 1e9)`` seconds of DMA;
recomputing them costs ``prefill_flops(cfg, start, n) / peak`` seconds
of device time.  Short prefixes recompute (the prefill is cheaper than
the copy below the crossover); long prefixes restitch.
``TPU_HOST_CACHE_BREAK_EVEN`` overrides the model with a flat token
floor ("restitch runs of >= K tokens"); on hosts with no detectable
peak (CPU meshes, ``TPU_PEAK_FLOPS`` unset) the copy always wins above
the engine's normal reuse floor, which keeps CI deterministic.
"""

from __future__ import annotations

import os
from typing import Any, List, Optional, Tuple

from .accounting import detect_peak_flops, prefill_flops


def host_cache_bytes(env: Optional[str] = None) -> int:
    """Arena capacity in bytes from ``TPU_HOST_CACHE_GB`` (0 = off).
    Fractional values are honoured so tests can build arenas a few
    pages wide."""
    raw = env if env is not None else os.environ.get("TPU_HOST_CACHE_GB",
                                                     "0")
    try:
        gb = float(raw or 0)
    except ValueError:
        return 0
    return max(int(gb * (1 << 30)), 0)


class HostEntry:
    """One spilled page: the gathered (k, v) numpy trees + accounting.

    ``snapshot`` marks entries imported from a tier-2 fleet snapshot
    (gguf/store prefix snapshots) rather than spilled locally — the
    scheduler attributes their hits to ``tier="2"`` in the metrics."""

    __slots__ = ("kv", "nbytes", "snapshot")

    def __init__(self, kv: Tuple[Any, Any], nbytes: int,
                 snapshot: bool = False):
        self.kv = kv
        self.nbytes = nbytes
        self.snapshot = snapshot


def _tree_nbytes(tree: Any) -> int:
    import jax
    return sum(int(leaf.nbytes) for leaf in jax.tree_util.tree_leaves(tree))


class HostArena:
    """Bounded byte-accounted store of spilled KV pages.

    The arena never walks the radix tree itself — LRU order lives in the
    tree's stamps, and the engine asks the tree which tier-1 entries to
    drop under pressure.  This object only owns capacity accounting, so
    ``clear()`` (supervised restart, radix_reset) is O(1): entries die
    with their nodes."""

    def __init__(self, capacity_bytes: int, page_bytes: int):
        assert capacity_bytes > 0
        self.capacity_bytes = int(capacity_bytes)
        # nominal per-page footprint, used for room checks BEFORE the
        # gather runs (actual accounting uses each entry's real bytes)
        self.page_bytes = max(int(page_bytes), 1)
        self._used = 0
        self._n = 0

    @property
    def used_bytes(self) -> int:
        return self._used

    @property
    def n_entries(self) -> int:
        return self._n

    def room_for(self, n_pages: int = 1) -> bool:
        return self._used + n_pages * self.page_bytes <= self.capacity_bytes

    def store(self, kv: Tuple[Any, Any], snapshot: bool = False
              ) -> HostEntry:
        nbytes = _tree_nbytes(kv)
        entry = HostEntry(kv, nbytes, snapshot)
        self._used += nbytes
        self._n += 1
        return entry

    def free(self, entry: Optional[HostEntry]):
        if entry is None:
            return
        self._used -= entry.nbytes
        self._n -= 1
        assert self._used >= 0 and self._n >= 0, "host arena double free"
        entry.kv = None  # type: ignore[assignment]

    def free_all(self, entries: List[Optional[HostEntry]]):
        for e in entries:
            self.free(e)

    def clear(self):
        """Drop all accounting (the tree holding the entries was reset)."""
        self._used = 0
        self._n = 0


def worth_restitch(cfg, start: int, n_tokens: int, n_bytes: int) -> bool:
    """Copy-vs-recompute break-even for a tier-1 run of ``n_tokens``
    tokens (``n_bytes`` of spilled KV) beginning at absolute position
    ``start``.  True = upload the pages; False = let the tail prefill
    recompute them.  Pure function of (args, env), identical on every
    host of a replica."""
    if n_tokens <= 0:
        return False
    floor = 0
    try:
        floor = int(os.environ.get("TPU_HOST_CACHE_BREAK_EVEN", "0") or 0)
    except ValueError:
        floor = 0
    if floor > 0:
        return n_tokens >= floor
    peak, _kind = detect_peak_flops()
    if peak <= 0:
        # no meaningful device peak (CPU smoke): a memcpy always beats
        # re-running the transformer, so restitch whenever the engine's
        # reuse floor admitted the run at all
        return True
    try:
        bw = float(os.environ.get("TPU_HOST_CACHE_BW_GBPS", "8") or 8)
    except ValueError:
        bw = 8.0
    copy_s = n_bytes / max(bw, 1e-3) / 1e9
    recompute_s = prefill_flops(cfg, start, n_tokens) / peak
    return copy_s < recompute_s
