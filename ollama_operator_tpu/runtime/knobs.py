"""Single declaration point for every ``TPU_*`` environment variable.

Every knob the package reads — directly via ``os.environ`` or through a
helper (``pick_i``/``pick_f`` in the autoscaler, ``arm_from_env`` in the
fault injector, ``_parse_kv_floats`` in admission) — is declared here
exactly once with its type, default, owning subsystem and a one-line
doc.  The ``knob-registry`` lint pass (tools/invariant_lint) enforces
the contract in three directions:

- a ``TPU_*`` read anywhere in the package must have a declaration here;
- a declaration here must still be mentioned by code (no stale rows);
- every declared knob must appear in the docs/en *and* docs/zh-CN knob
  tables, and the docs must not mention undeclared names.

The registry is data, not plumbing: call sites keep their existing
``os.environ.get(...)`` reads (so defaults stay next to the logic that
interprets them) and this module is the place a human or the linter
looks to see the full surface.  ``python -m
ollama_operator_tpu.runtime.knobs`` prints the catalog.

Types are informal: ``int`` / ``float`` / ``bool`` (0/1 or
false-ish strings) / ``str`` / ``enum`` (closed value set) / ``map``
(``k=v,k=v`` grammar).  ``default=None`` means "unset = feature off or
value derived elsewhere"; the doc says which.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    type: str
    default: Any
    subsystem: str
    doc: str


REGISTRY: Dict[str, Knob] = {}


def declare(name: str, type: str, default: Any, subsystem: str,
            doc: str) -> Knob:
    """Register one knob.  Raises on duplicate declaration so the file
    can't silently shadow an earlier row."""
    if name in REGISTRY:
        raise ValueError(f"knob {name} declared twice")
    k = Knob(name, type, default, subsystem, doc)
    REGISTRY[name] = k
    return k


def lookup(name: str) -> Optional[Knob]:
    return REGISTRY.get(name)


def all_knobs() -> List[Knob]:
    return [REGISTRY[n] for n in sorted(REGISTRY)]


# -- engine -----------------------------------------------------------------

declare("TPU_ENGINE_DTYPE", "enum", None, "engine",
        "weight dtype override (bfloat16|bf16|float32|int8|int4); unset = "
        "resolved per model at load")
declare("TPU_KV_DTYPE", "enum", None, "engine",
        "KV-cache storage dtype (bfloat16|float32|int8|int4); int4 is "
        "paged-only (nibble-packed pages); unset = int8 on TPU, float32 "
        "on CPU")
declare("TPU_MAX_SLOTS", "int", 0, "engine",
        "continuous-batching slots; 0 = per-model default (32 paged, "
        "8 dense)")
declare("TPU_MAX_SEQ_LEN", "int", 4096, "engine",
        "maximum sequence length a slot can hold")
declare("TPU_DECODE_CHUNK", "int", 0, "engine",
        "decode steps per device round-trip; 0 = backend default "
        "(32 TPU, 8 CPU)")
declare("TPU_MIN_PREFILL_BUCKET", "int", 0, "engine",
        "floor for the padded prefill bucket ladder; 0 = engine-config "
        "default")
declare("TPU_FUSED_QKV", "bool", 0, "engine",
        "1 fuses the QKV projections into one matmul on single-device "
        "meshes")
declare("TPU_SPEC_DECODE", "int", 0, "engine",
        "speculative-decoding draft length k; 0 disables")
declare("TPU_WARM_SNAPSHOT_EXECS", "bool", None, "engine",
        "0 skips serialising warm executables into the snapshot; unset = "
        "backend default")

# -- paged KV ---------------------------------------------------------------

declare("TPU_PAGED", "bool", None, "paged",
        "1 forces the paged KV cache, 0 forces dense; unset = per-model "
        "default (paged for GQA)")
declare("TPU_PAGE_SIZE", "int", 0, "paged",
        "KV pool page size in tokens; 0 = backend default (128 paged TPU, "
        "else 64)")
declare("TPU_N_PAGES", "int", 0, "paged",
        "KV pool page count; 0 = dense-equivalent "
        "max_slots*max_seq_len/page_size")
declare("TPU_PAGED_V3", "bool", 1, "paged",
        "0 disables the v3 double-buffered paged attention kernel "
        "(falls back to v2)")
declare("TPU_PAGED_V4", "bool", 0, "paged",
        "1 opts in to the v4 epoch-fenced paged kernel variant")
declare("TPU_PAGED_DEPTH", "int", 2, "paged",
        "paged kernel pipeline depth (double-buffering stages)")
declare("TPU_PAGED_FUSED", "bool", 1, "paged",
        "0 disables the fused paged-attention pallas kernels entirely "
        "(gather+einsum reference path; A/B control and parity oracle)")

# -- ops / kernels ----------------------------------------------------------

declare("TPU_MHA_KERNEL", "bool", 0, "ops",
        "1 routes MHA decode through the head-tiled pallas kernel instead "
        "of the XLA einsum")

# -- scheduler --------------------------------------------------------------

declare("TPU_ASYNC_DISPATCH", "bool", 1, "scheduler",
        "0 disables double-buffered async decode dispatch")
declare("TPU_GRAMMAR_DEVICE", "bool", 1, "scheduler",
        "0 disables device-side constrained decode (precomputed grammar "
        "mask/transition tables indexed by a device-resident FSM state); "
        "constrained slots then pay one sync dispatch per token")
declare("TPU_GRAMMAR_STATES", "int", 64, "scheduler",
        "device grammar-table capacity in automaton states; walks that "
        "leave the table escape to host masks for that request")
declare("TPU_PREFILL_CHUNK", "int", None, "scheduler",
        "prefill chunk size in tokens; unset = adaptive per-model choice")
declare("TPU_PREFIX_CACHE", "bool", 1, "scheduler",
        "0 disables the radix prefix cache")
declare("TPU_MIN_PREFIX_REUSE", "int", 16, "scheduler",
        "minimum shared-token run before the prefix cache reuses pages")
declare("TPU_HOST_CACHE_GB", "float", 0, "scheduler",
        "tier-1 host-RAM arena size in GiB for spilled radix KV pages "
        "(fractional OK); 0 disables tiering and eviction frees pages")
declare("TPU_HOST_CACHE_BW_GBPS", "float", 8, "scheduler",
        "assumed host-to-HBM copy bandwidth in GB/s for the "
        "restitch-vs-recompute break-even model")
declare("TPU_HOST_CACHE_BREAK_EVEN", "int", 0, "scheduler",
        "flat token floor overriding the break-even model: restitch "
        "spilled runs of >= this many tokens, recompute shorter ones; "
        "0 = use the FLOPs/bandwidth model")
declare("TPU_HOST_CACHE_SNAPSHOT", "bool", 1, "scheduler",
        "0 disables tier-2 prefix snapshots (export at drain, import "
        "at load) on the shared weight-cache volume")
declare("TPU_HOST_CACHE_SNAPSHOT_MB", "int", 64, "scheduler",
        "byte budget for an exported tier-2 prefix snapshot "
        "(most-recently-used prefixes first)")
declare("TPU_PRIORITY_PREEMPT", "bool", 1, "scheduler",
        "0 disables priority preemption of running low-priority slots")
declare("TPU_DISPATCH_WATCHDOG_MS", "int", None, "scheduler",
        "hung-dispatch watchdog bound in ms; unset = histogram-derived, "
        "0 = off")

# -- admission --------------------------------------------------------------

declare("TPU_DEFAULT_PRIORITY", "enum", "normal", "admission",
        "priority class for requests that don't set one "
        "(high|normal|best_effort)")
declare("TPU_TTFT_SLO_MS", "int", None, "admission",
        "TTFT SLO for admission control in ms; unset disables SLO-aware "
        "shedding")
declare("TPU_ADMIT_THROUGHPUT_TPS", "float", None, "admission",
        "fixed tokens/s throughput for the TTFT queue model; unset = "
        "measured online")
declare("TPU_WDRR_QUANTUM", "float", 256, "admission",
        "weighted deficit round-robin quantum in tokens per tenant turn")
declare("TPU_TENANT_WEIGHTS", "map", None, "admission",
        "per-tenant WDRR weights, e.g. teamA=2,teamB=1")
declare("TPU_TENANT_LIMITS", "map", None, "admission",
        "per-tenant token-rate limits, e.g. teamA=50,teamB=100")
declare("TPU_TENANT_TOKEN_RATE", "float", 0, "admission",
        "default per-tenant token refill rate; 0 disables rate limiting")
declare("TPU_TENANT_BURST_S", "float", 2, "admission",
        "token-bucket burst window in seconds of refill")
declare("TPU_TENANT_MAX_QUEUED", "int", 0, "admission",
        "per-tenant queued-request cap; 0 = unlimited")

# -- server / HTTP ----------------------------------------------------------

declare("TPU_PRELOAD_MODEL", "str", None, "server",
        "model name to load at startup")
declare("TPU_WEIGHT_CACHE", "str", None, "server",
        "transcoded-weights cache directory")
declare("TPU_STORE_ONLY", "bool", 0, "server",
        "1 runs registry/store mode with no inference engine")
declare("TPU_XLA_CACHE", "bool", 1, "server",
        "0 disables the persistent XLA compilation cache beside the "
        "weight cache")
declare("TPU_EXPECT_PLATFORM", "str", None, "server",
        "fail startup unless the JAX backend matches (tpu|cpu); set by "
        "the operator on TPU pods")
declare("TPU_HTTP_WORKERS", "int", 64, "server",
        "HTTP server thread-pool size")
declare("TPU_STREAM_FLUSH_TOKENS", "int", 16, "server",
        "stream chunk coalescing: flush after this many tokens")
declare("TPU_STREAM_FLUSH_MS", "int", 25, "server",
        "stream chunk coalescing: flush after this many milliseconds")
declare("TPU_REQUEST_DEADLINE_MS", "int", None, "server",
        "server-side request deadline in ms; unset disables")
declare("TPU_PROFILE_PORT", "int", 0, "server",
        "jax.profiler server port; 0 = off")
declare("TPU_DEBUG_PROFILE", "bool", 0, "server",
        "1 enables the /debug/profile capture endpoint")

# -- parallelism ------------------------------------------------------------

declare("TPU_TENSOR_PARALLEL", "int", 0, "parallel",
        "tensor-parallel ways; 0 = all local devices")
declare("TPU_SEQUENCE_PARALLEL", "int", 1, "parallel",
        "sequence-parallel ways (ring attention, sequence-sharded KV)")
declare("TPU_EXPERT_PARALLEL", "int", 1, "parallel",
        "expert-parallel ways for MoE meshes")
declare("TPU_DATA_PARALLEL", "int", 0, "parallel",
        "in-engine data-parallel ways; 0 = derive from leftover devices")

# -- multi-host -------------------------------------------------------------

declare("TPU_DIST_HOSTS", "int", 1, "multihost",
        "number of processes in the slice (StatefulSet replicas); "
        "operator-injected")
declare("TPU_DIST_CHIPS_PER_HOST", "int", None, "multihost",
        "chips each process owns (informational); operator-injected")
declare("TPU_DIST_COORDINATOR", "str", None, "multihost",
        "host:port of process 0 for jax.distributed; operator-injected")
declare("TPU_DIST_POD_NAME", "str", None, "multihost",
        "this pod's name; the trailing -<ordinal> is the process index")
declare("TPU_DIST_STS_NAME", "str", None, "multihost",
        "StatefulSet name used to derive peer DNS names; "
        "operator-injected")
declare("TPU_DIST_CONTROL", "str", None, "multihost",
        "host:port of the leader control stream the follower replays; "
        "operator-injected")
declare("TPU_CP_HEARTBEAT_S", "float", 10, "multihost",
        "control-plane heartbeat period in seconds; 0 disables")
declare("TPU_CP_LEADER_TIMEOUT_S", "float", 60, "multihost",
        "follower exits cleanly (fail static) when the leader control "
        "stream is silent this long; 0 disables the watchdog")
declare("TPU_CP_SEND_TIMEOUT_S", "float", 20, "multihost",
        "leader-side per-follower send backpressure bound; a broadcast "
        "blocked past this counts the follower dead (FollowerLost) "
        "instead of wedging every dispatch; 0 disables")

# -- lifecycle --------------------------------------------------------------

declare("TPU_DRAIN_TIMEOUT_S", "float", 30, "lifecycle",
        "graceful-drain budget on SIGTERM before hard stop")
declare("TPU_ENGINE_MAX_RESTARTS", "int", 3, "lifecycle",
        "supervisor restart budget before the pod fails")
declare("TPU_ENGINE_RESTART_BACKOFF_S", "float", 0.05, "lifecycle",
        "base backoff between supervised engine restarts")
declare("TPU_RESTART_REPLAY_MAX", "int", 64, "lifecycle",
        "max in-flight streams the restart replays; 0 disables replay")
declare("TPU_RESTART_REPLAY_TOKENS", "int", 65536, "lifecycle",
        "max total tokens a restart replay may regenerate before "
        "fail-safe erroring")
declare("TPU_WARM_BUCKETS", "bool", 1, "lifecycle",
        "0 skips prefill-bucket warm-up compilation at startup")
declare("TPU_WARM_SNAPSHOT", "bool", 1, "lifecycle",
        "0 disables warm-state snapshot save/restore across restarts")

# -- observability ----------------------------------------------------------

declare("TPU_TRACE", "bool", 1, "observability",
        "0 disables per-request timeline tracing")
declare("TPU_TRACE_KEEP", "int", 256, "observability",
        "finished request timelines kept for /debug/trace")
declare("TPU_FLIGHT_EVENTS", "int", 512, "observability",
        "flight-recorder ring size in structured events")
declare("TPU_ACCOUNTING", "bool", 1, "observability",
        "0 disables TPU utilization/goodput accounting")
declare("TPU_ACCOUNTING_RING_S", "int", 120, "observability",
        "seconds of per-second aggregates /debug/utilization keeps")
declare("TPU_PEAK_FLOPS", "float", None, "observability",
        "per-chip peak FLOP/s override for MFU; unset = detected from "
        "the device kind")

# -- faults -----------------------------------------------------------------

declare("TPU_FAULTS", "str", None, "faults",
        "fault-injection arming grammar, e.g. "
        "engine.step=fail:once,kube.request=delay:10ms")

# -- operator ---------------------------------------------------------------

declare("TPU_SERVER_IMAGE", "str", None, "operator",
        "model-server image the operator deploys; unset = built-in "
        "release image")

# -- autoscale --------------------------------------------------------------

declare("TPU_AUTOSCALE", "bool", 0, "autoscale",
        "1 enables the closed-loop replica autoscaler")
declare("TPU_AUTOSCALE_MIN", "int", 1, "autoscale",
        "replica floor; 0 allows scale-to-zero")
declare("TPU_AUTOSCALE_MAX", "int", 8, "autoscale",
        "replica ceiling")
declare("TPU_AUTOSCALE_TARGET_OCCUPANCY", "float", 0.75, "autoscale",
        "sustained slot occupancy above this scales up")
declare("TPU_AUTOSCALE_LOW_OCCUPANCY", "float", 0.30, "autoscale",
        "sustained occupancy at/below this with an empty queue scales "
        "down")
declare("TPU_AUTOSCALE_UP_COOLDOWN_S", "float", 30, "autoscale",
        "minimum gap between up moves")
declare("TPU_AUTOSCALE_DOWN_COOLDOWN_S", "float", 120, "autoscale",
        "minimum gap between down moves")
declare("TPU_AUTOSCALE_UP_STREAK", "int", 2, "autoscale",
        "consecutive hot observations required to scale up")
declare("TPU_AUTOSCALE_DOWN_STREAK", "int", 3, "autoscale",
        "consecutive cold observations required to scale down")
declare("TPU_AUTOSCALE_IDLE_TTL_S", "float", 0, "autoscale",
        "idle seconds before scale-to-zero; 0 = never")
declare("TPU_AUTOSCALE_BACKLOG_TOKENS", "int", 4096, "autoscale",
        "queued prompt tokens per replica that force an up move")
declare("TPU_AUTOSCALE_STALE_S", "float", 30, "autoscale",
        "metrics older than this are ignored by the loop")
declare("TPU_AUTOSCALE_FLAP_WINDOW_S", "float", 300, "autoscale",
        "window for flap detection")
declare("TPU_AUTOSCALE_FLAP_MAX_FLIPS", "int", 4, "autoscale",
        "direction changes inside the window that freeze the loop")
declare("TPU_AUTOSCALE_FLAP_HOLD_S", "float", 180, "autoscale",
        "freeze duration after flap detection")
declare("TPU_REMEDIATION_BACKOFF_S", "float", 10, "autoscale",
        "base backoff between replica remediation deletes")
declare("TPU_REMEDIATION_BACKOFF_CAP_S", "float", 300, "autoscale",
        "remediation backoff ceiling")

# -- gateway ----------------------------------------------------------------

declare("TPU_GATEWAY_PORT", "int", 11434, "gateway",
        "listen port of the fleet gateway process")
declare("TPU_GATEWAY_REPLICAS", "str", None, "gateway",
        "comma-separated replica base URLs (static discovery); unset = "
        "discover via TPU_GATEWAY_SELECTOR")
declare("TPU_GATEWAY_SELECTOR", "str", None, "gateway",
        "namespace/app pod selector for in-cluster replica discovery; "
        "operator-injected")
declare("TPU_GATEWAY_HASH_CHUNK", "int", 256, "gateway",
        "prompt characters per page-aligned prefix-hash chunk in the "
        "routing law")
declare("TPU_GATEWAY_PROBE", "bool", 1, "gateway",
        "0 skips the /api/prefix_probe scatter on an affinity miss "
        "(route straight to least-loaded)")
declare("TPU_GATEWAY_EJECT_FAILURES", "int", 3, "gateway",
        "consecutive request/scrape failures that open a replica's "
        "circuit")
declare("TPU_GATEWAY_EJECT_S", "float", 10, "gateway",
        "seconds a replica's circuit stays open before half-open "
        "admits one probe request")
declare("TPU_GATEWAY_SLOW_SCRAPE_MS", "float", 1000, "gateway",
        "scrape latency above this counts as a health failure")
declare("TPU_GATEWAY_SCRAPE_S", "float", 2, "gateway",
        "period of the gateway's background health/load scrape loop")
declare("TPU_GATEWAY_HEDGE_MS", "float", 0, "gateway",
        "first-byte wait before a queued-but-unstarted request fails "
        "over to another replica; 0 = only on replica death")
declare("TPU_GATEWAY_JOURNAL", "int", 512, "gateway",
        "completed-request journal entries kept for failover replay "
        "bookkeeping")
declare("TPU_GATEWAY_PERSIST", "str", None, "gateway",
        "crash-recovery journal: unset/0 disables, 1 writes the "
        "append-log to <TPU_WEIGHT_CACHE>/gateway-journal.ndjson, "
        "anything else is an explicit log path")
declare("TPU_GATEWAY_PERSIST_FLUSH_MS", "float", 50, "gateway",
        "persist-log fsync batching window in ms; a crash loses at most "
        "this much journal progress (downgrading a resume to the "
        "exactly-once error frame)")

# -- disaggregated prefill/decode pools (ISSUE 20) --------------------------

declare("TPU_DISAGG", "enum", "auto", "disagg",
        "gateway disaggregation gate: auto (default) hands off whenever "
        "both a prefill and a decode replica are routable, 0 disables "
        "routing-level disaggregation even if pool Deployments exist")
declare("TPU_DISAGG_ROLE", "enum", None, "disagg",
        "this replica's pool, set by the operator on pool Deployments "
        "(prefill|decode); unset = unified replica. Informational on "
        "the server (surfaced in /api/ps lifecycle) — routing is the "
        "gateway's job")
declare("TPU_DISAGG_HANDOFF_TIMEOUT_S", "float", 30, "disagg",
        "bound on one prefill->decode handoff leg (the gateway's "
        "/api/kv_import call and the decode replica's pull from the "
        "prefill replica); expiry downgrades the handoff to journal "
        "replay on the decode pool — never a client error")
declare("TPU_DISAGG_TRANSFER_MB_S", "float", 0, "disagg",
        "KV page transfer pacing in MB/s applied on the export side's "
        "chunked writes; 0 = unthrottled (page copies already ride the "
        "host arena, not HBM bandwidth)")
declare("TPU_DISAGG_PREFILL_MIN", "int", 1, "disagg",
        "prefill pool autoscale floor when spec.disaggregate.prefill "
        "sets no minReplicas")
declare("TPU_DISAGG_PREFILL_MAX", "int", 4, "disagg",
        "prefill pool autoscale ceiling when spec.disaggregate.prefill "
        "sets no maxReplicas (prefill scales on queued backlog tokens)")
declare("TPU_DISAGG_DECODE_MIN", "int", 1, "disagg",
        "decode pool autoscale floor when spec.disaggregate.decode "
        "sets no minReplicas")
declare("TPU_DISAGG_DECODE_MAX", "int", 8, "disagg",
        "decode pool autoscale ceiling when spec.disaggregate.decode "
        "sets no maxReplicas (decode scales on slot occupancy)")


def _main() -> None:
    by_sub: Dict[str, List[Knob]] = {}
    for k in all_knobs():
        by_sub.setdefault(k.subsystem, []).append(k)
    for sub in sorted(by_sub):
        print(f"[{sub}]")
        for k in by_sub[sub]:
            d = "unset" if k.default is None else k.default
            print(f"  {k.name:34s} {k.type:6s} default={d!s:8s} {k.doc}")
    print(f"{len(REGISTRY)} knobs")


if __name__ == "__main__":
    _main()
