"""Shared wire format for serialized KV page records.

One codec, two consumers: tier-2 fleet prefix snapshots (engine
``export_prefixes``/``import_prefixes``, persisted via gguf/store.py)
and the disaggregated prefill→decode KV transfer (engine
``export_request_kv``/``import_request_kv`` over ``/api/kv_export`` /
``/api/kv_import``).  Before ISSUE 20 the format lived inline in the
snapshot methods; factoring it here puts the version guard and every
geometry/corruption check in ONE place, so the two paths cannot drift
into almost-compatible blobs.

A blob is ``pickle`` protocol 4 of::

    {"v": WIRE_VERSION, "ps": <page_size>, "recs": [record, ...]}

where each record is ``{"p": parent_index, "c": np.int32 token chunk,
"k": k_page, "v": v_page}``.  ``p`` indexes an EARLIER record in the
same blob (-1 = child of the radix root), so every decodable path is
rooted by construction.  ``k``/``v`` are per-layer trees of one-page
arrays (page axis 1 kept, length 1) exactly as gathered from the paged
pool — geometry is checked against the importing engine's cache spec
record-by-record, because a blob may legitimately mix importable and
foreign records (e.g. a fleet snapshot from a differently-sharded
replica).

``decode`` raises :class:`WireError` on anything structurally wrong
(bad pickle, wrong version, page-size mismatch, malformed record
list); callers that treat a bad blob as "no warm start" catch it and
carry on.
"""

from __future__ import annotations

import pickle
from typing import Any, Dict, List, Tuple

import numpy as np

WIRE_VERSION = 1


class WireError(ValueError):
    """A KV wire blob failed a structural or version check."""


def spec(tree: Any, page_axis1: bool = False):
    """Shape/dtype signature of a KV tree.  ``page_axis1`` collapses
    axis 1 (the page axis of the pooled cache) to 1 so a full cache's
    spec compares equal to a single gathered page's."""
    import jax
    return jax.tree_util.tree_map(
        lambda a: ((tuple(a.shape[:1]) + (1,) + tuple(a.shape[2:]))
                   if page_axis1 else tuple(a.shape),
                   np.dtype(a.dtype)), tree)


def cache_spec(k_cache: Any, v_cache: Any):
    """The signature one exported page must match to be importable
    into an engine holding ``k_cache``/``v_cache``."""
    return (spec(k_cache, True), spec(v_cache, True))


def kv_spec(kv: Tuple[Any, Any]):
    """Signature of one ``(k_page, v_page)`` record payload."""
    return (spec(kv[0]), spec(kv[1]))


def kv_nbytes(kv: Tuple[Any, Any]) -> int:
    """Payload bytes of one record (budget accounting)."""
    import jax
    return sum(int(leaf.nbytes) for leaf in jax.tree_util.tree_leaves(kv))


def record(parent_idx: int, chunk: Any, kv: Tuple[Any, Any]
           ) -> Dict[str, Any]:
    """Build one wire record: ``chunk`` is the page's token ids,
    ``parent_idx`` the index of its parent record in the same blob
    (-1 = root child)."""
    return {"p": int(parent_idx), "c": np.asarray(chunk, np.int32),
            "k": kv[0], "v": kv[1]}


def encode(recs: List[Dict[str, Any]], page_size: int) -> bytes:
    """Serialize records into a self-contained versioned blob."""
    return pickle.dumps(
        {"v": WIRE_VERSION, "ps": int(page_size), "recs": recs},
        protocol=4)


def decode(blob: bytes, page_size: int) -> List[Dict[str, Any]]:
    """Parse + validate a blob for an engine with ``page_size`` pages.
    Returns the record list; raises :class:`WireError` on corruption,
    version skew, or page-geometry mismatch.  Per-record KV geometry
    is NOT checked here (records may individually miss the importer's
    cache spec — see module docstring); use :func:`kv_spec` against
    :func:`cache_spec` at the import site."""
    if not blob:
        raise WireError("empty blob")
    try:
        data = pickle.loads(blob)
    except Exception as e:  # noqa: BLE001 — any unpickle failure is corruption
        raise WireError(f"undecodable blob: {type(e).__name__}: {e}")
    if not isinstance(data, dict):
        raise WireError(f"blob root is {type(data).__name__}, not dict")
    v = data.get("v")
    if v != WIRE_VERSION:
        raise WireError(f"wire version {v!r}, want {WIRE_VERSION}")
    ps = data.get("ps")
    if ps != page_size:
        raise WireError(f"page size {ps!r}, want {page_size}")
    recs = data.get("recs")
    if not isinstance(recs, list):
        raise WireError("recs is not a list")
    for i, rec in enumerate(recs):
        if not isinstance(rec, dict) or "c" not in rec \
                or "k" not in rec or "v" not in rec:
            raise WireError(f"record {i} malformed")
        p = int(rec.get("p", -1))
        if p >= i:
            raise WireError(f"record {i} parent {p} not an earlier record")
    return recs
