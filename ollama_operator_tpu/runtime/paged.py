"""Host-side page accounting for the paged KV cache.

The device side is a physical page pool ``[L, P, KvH, page_size, hd]``
(``models/decoder.forward_with_cache_paged`` + the pallas kernel in
``ops/pallas/paged.py``); this module owns which physical page backs which
logical block of which slot. Pure host bookkeeping — numpy block tables are
uploaded per dispatch (a few KB), never read back.

Page 0 is the **trash page**: bucket-padding positions beyond a prompt's
valid length scatter their garbage K/V there, so admissions only allocate
pages for real tokens and no masking depends on page contents.

Pages are **reference counted** so the radix prefix cache
(``runtime/radix.py``) can map one physical page into many slots at once:
a page's refcount is the number of slot block-table entries mapping it
plus the number of radix-tree pins holding it. ``grow`` allocates private
pages (rc=1); ``map_shared`` stitches an already-resident page into
another slot read-only (rc+=1); ``pin``/``unpin`` are the tree's share.
A page returns to the free list exactly when its refcount hits zero —
``check()`` asserts that accounting invariant and the test suite runs it
after every test (autouse fixture in conftest.py).

**Epoch-fenced reclamation** (ISSUE 5): double-buffered async dispatch
launches decode program N+1 before materialising N's tokens, so a page
freed between the two launches may still be read (or written, for the
slot's new positions) by the in-flight program through the block table it
captured at launch. The table therefore carries a monotonic dispatch
epoch: ``advance_epoch()`` stamps each ``decode_n_launch``; while any
launched epoch is un-retired, a page whose refcount hits zero goes to a
FIFO **quarantine** stamped with the current epoch instead of the free
list, and becomes allocatable only once ``retire_epoch(e)`` certifies the
program launched at its stamp has been materialised (vLLM's deferred
block reclamation / SGLang's radix fencing, host-side). Retirement is
driven by CALLERS at deterministic call-stream positions (the scheduler
after waiting a handle, supervised restart via ``drain_quarantine``) so
multi-host follower replay — which never materialises tokens — keeps
byte-identical free lists. When no dispatch is outstanding
(epoch == retired, the synchronous path) frees hit the pool directly,
exactly as before.

Fused speculative decoding needs NO states beyond these: a spec dispatch
maps pages for its worst case (k+1 positions) via the same
``prepare_decode`` growth path, draft tokens write into those
already-mapped pages, and rejection just moves ``lengths`` back
(``Engine.spec_ack``) — the rejected positions sit above the advanced
length, are never attended, and are overwritten by the next dispatch.
Nothing is freed on rejection, so nothing new can race the fence.

Design notes vs the reference: llama.cpp's unified KV cell pool inside the
delegated `ollama/ollama` image plays this role
(/root/reference/pkg/model/pod.go:11); here the allocator is explicit so
the engine can admit many more concurrent slots than dense max_slots ×
max_seq_len HBM would allow, preempt (victim-select) when the pool runs
dry (SURVEY.md §7 hard-part 2), and share prefix pages across requests
the way vLLM/SGLang block pools do.
"""

from __future__ import annotations

import weakref
from collections import Counter
from typing import Dict, List, Optional, Sequence

import numpy as np

from .faults import FAULTS, InjectedFault

TRASH_PAGE = 0

# every live PageTable, so the test suite can sweep the accounting
# invariant after each test without plumbing engine internals around
_LIVE: "weakref.WeakSet[PageTable]" = weakref.WeakSet()


def live_tables() -> List["PageTable"]:
    """Snapshot of every PageTable still referenced anywhere (test hook)."""
    return list(_LIVE)


class PagesExhausted(RuntimeError):
    """No free pages for the requested allocation (caller may preempt)."""


class PageTable:
    """Block tables + free-list for ``n_slots`` sequences over ``n_pages``
    physical pages of ``page_size`` tokens (page 0 reserved as trash)."""

    def __init__(self, n_slots: int, n_pages: int, page_size: int,
                 max_blocks: int):
        assert n_pages > 1, "need at least one non-trash page"
        self.page_size = page_size
        self.n_pages = n_pages
        self.max_blocks = max_blocks
        # LIFO free list → recently-freed pages are reused first (warm HBM)
        self._free: List[int] = list(range(n_pages - 1, TRASH_PAGE, -1))
        self._owned: Dict[int, List[int]] = {s: [] for s in range(n_slots)}
        self.tables = np.full((n_slots, max_blocks), TRASH_PAGE, np.int32)
        # per-page refcount = slot mappings + radix pins; _pins is the
        # radix tree's share of it (rc - pins = live slot mappings)
        self._rc = np.zeros((n_pages,), np.int32)
        self._pins = np.zeros((n_pages,), np.int32)
        # epoch fence: dispatches launched / known-materialised, plus the
        # FIFO of (launch-epoch stamp, page) entries whose reclamation is
        # deferred until their stamp retires (module docstring)
        self._epoch = 0
        self._retired = 0
        self._quarantine: List[tuple] = []
        _LIVE.add(self)

    @property
    def n_free(self) -> int:
        return len(self._free)

    def blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def grow(self, slot: int, n_tokens: int) -> bool:
        """Ensure ``slot`` owns pages covering logical positions
        [0, n_tokens). Returns False (allocating nothing) when the pool
        can't satisfy it — the caller preempts or queues."""
        owned = self._owned[slot]
        need = self.blocks_for(n_tokens) - len(owned)
        if need <= 0:
            return True
        try:
            # chaos hook: an injected fault here behaves exactly like a
            # dry pool, so callers exercise their real exhaustion paths
            FAULTS.check("pages.alloc")
        except InjectedFault:
            return False
        if need > len(self._free):
            return False
        if len(owned) + need > self.max_blocks:
            raise ValueError(
                f"slot {slot}: {n_tokens} tokens exceed "
                f"{self.max_blocks} blocks of {self.page_size}")
        for _ in range(need):
            pg = self._free.pop()
            assert self._rc[pg] == 0, f"free page {pg} had rc {self._rc[pg]}"
            self._rc[pg] = 1
            self.tables[slot, len(owned)] = pg
            owned.append(pg)
        return True

    def map_shared(self, slot: int, pages: Sequence[int]):
        """Stitch already-resident ``pages`` (radix prefix hits) into
        ``slot``'s block table read-only, after its current blocks, in
        order. Each page's refcount is bumped — the slot is now one of
        its co-owners and MUST NOT write into it (copy-on-write first)."""
        owned = self._owned[slot]
        if len(owned) + len(pages) > self.max_blocks:
            raise ValueError(
                f"slot {slot}: {len(owned)}+{len(pages)} shared blocks "
                f"exceed {self.max_blocks}")
        for pg in pages:
            assert pg != TRASH_PAGE and self._rc[pg] >= 1, \
                f"page {pg} is not live (rc={int(self._rc[pg])})"
            self._rc[pg] += 1
            self.tables[slot, len(owned)] = pg
            owned.append(pg)

    def _reclaim(self, pg: int):
        """A page's refcount just hit zero: return it to the pool — via
        the epoch quarantine while a launched dispatch is un-retired (its
        captured block table may still reference the page), directly
        otherwise (synchronous flow, today's semantics)."""
        if self._epoch > self._retired:
            self._quarantine.append((self._epoch, pg))
        else:
            self._free.append(pg)

    def release(self, slot: int):
        """Drop all of ``slot``'s page mappings (table row resets to
        trash); pages whose refcount reaches zero return to the pool
        (through the epoch fence while a dispatch is in flight)."""
        owned = self._owned[slot]
        for pg in owned:
            self._rc[pg] -= 1
            assert self._rc[pg] >= 0, f"double free of page {pg}"
            if self._rc[pg] == 0:
                self._reclaim(pg)
        owned.clear()
        self.tables[slot, :] = TRASH_PAGE

    def alloc_pinned(self) -> Optional[int]:
        """Allocate one page owned solely by the radix tree (rc = pins
        = 1, no slot mapping): the disagg KV import uploads transferred
        bytes into it and grafts it into the tree, with no slot in the
        picture. ``check()`` stays clean (rc == mappings + pins).
        Returns None on a dry pool — the caller evicts or stops."""
        if not self._free:
            return None
        pg = self._free.pop()
        assert self._rc[pg] == 0, f"free page {pg} had rc {self._rc[pg]}"
        self._rc[pg] = 1
        self._pins[pg] = 1
        return pg

    def pin(self, pg: int):
        """Take a radix-tree reference on a live page: it survives the
        owning slot's release until ``unpin``."""
        assert pg != TRASH_PAGE and self._rc[pg] >= 1, \
            f"cannot pin dead page {pg}"
        self._rc[pg] += 1
        self._pins[pg] += 1

    def unpin(self, pg: int):
        """Drop a radix-tree reference; frees the page at rc zero
        (through the epoch fence while a dispatch is in flight — radix
        eviction must not recycle a page an in-flight program reads)."""
        assert self._pins[pg] >= 1, f"page {pg} is not pinned"
        self._pins[pg] -= 1
        self._rc[pg] -= 1
        if self._rc[pg] == 0:
            self._reclaim(pg)

    # ------------------------------------------------------------------
    # dispatch-epoch fence (async double-buffering; module docstring)
    # ------------------------------------------------------------------
    @property
    def quarantined(self) -> int:
        """Pages parked in the epoch quarantine (not yet allocatable)."""
        return len(self._quarantine)

    @property
    def quiescent(self) -> bool:
        """True when every launched dispatch has retired — no in-flight
        program can still read or write ANY page through a captured
        block table. This is the gate for spilling a page's bytes to the
        host tier (ISSUE 18): a host copy taken while a dispatch is in
        flight could race the device writes; a quiescent copy cannot.
        Pure mirrored host state, so followers take identical spill
        branches at identical call-stream positions."""
        return self._epoch <= self._retired

    def advance_epoch(self) -> int:
        """Stamp one launched dispatch; returns its epoch. Pages freed
        from now on quarantine under this stamp until it retires."""
        self._epoch += 1
        return self._epoch

    def retire_epoch(self, epoch: int):
        """The program launched at ``epoch`` (and, by the donated-state
        device ordering, every earlier one) has been materialised: drain
        quarantine entries stamped at or before it into the free list, in
        FIFO order — deterministic from call order alone, so follower
        replay reproduces the exact free list."""
        e = min(int(epoch), self._epoch)
        if e <= self._retired:
            return
        self._retired = e
        q = self._quarantine
        i = 0
        while i < len(q) and q[i][0] <= e:
            self._free.append(q[i][1])
            i += 1
        if i:
            del q[:i]

    def drain_quarantine(self) -> int:
        """Retire everything outstanding (supervised restart / verified-
        idle pipeline: no launched program can still read these pages).
        Returns the number of pages returned to the pool."""
        n = len(self._quarantine)
        self.retire_epoch(self._epoch)
        return n

    def shared_refs(self, pg: int) -> int:
        """Slot mappings of ``pg`` beyond the tree's pins — a pinned page
        with shared_refs == 0 is referenced only by the radix tree and is
        safe to evict (unpin frees it immediately)."""
        return int(self._rc[pg]) - int(self._pins[pg])

    def slot_pages(self, slot: int) -> List[int]:
        """The physical pages backing ``slot``, in block order (copy)."""
        return list(self._owned[slot])

    def owned_blocks(self, slot: int) -> int:
        return len(self._owned[slot])

    def free_for(self, slot: int) -> int:
        """Pages available to ``slot`` (its allocation domain's free count
        — the whole pool here; a dp shard's pool in ShardedPageTable)."""
        return len(self._free)

    @property
    def data_pages(self) -> int:
        """Max pages one slot could ever hold (pool minus the trash page)."""
        return self.n_pages - 1

    def check(self):
        """Accounting invariant: every non-trash page is EXACTLY ONE of —
        on the free list once with no references, in the epoch quarantine
        once with no references (rc 0, unmapped, unpinned: a quarantined
        page is dead to every slot and to the radix tree, merely not yet
        reallocatable), or referenced with rc == slot mappings + pins ≥ 1.
        Nothing leaked, nothing double freed, block-table rows consistent
        with the ownership lists, quarantine stamps sane. Debug/test hook
        (an autouse fixture runs it after every test)."""
        free = Counter(self._free)
        quar = Counter(pg for _, pg in self._quarantine)
        mapped: Counter = Counter()
        for owned in self._owned.values():
            mapped.update(owned)
        assert free[TRASH_PAGE] == 0, "trash page on the free list"
        assert quar[TRASH_PAGE] == 0, "trash page in quarantine"
        assert mapped[TRASH_PAGE] == 0, "trash page mapped to a slot"
        assert self._retired <= self._epoch, (
            f"retired epoch {self._retired} ahead of launched "
            f"{self._epoch}")
        stamps = [e for e, _ in self._quarantine]
        assert stamps == sorted(stamps), "quarantine stamps out of order"
        assert all(self._retired < e <= self._epoch for e in stamps), (
            f"quarantine stamp outside ({self._retired}, {self._epoch}]")
        for pg in range(TRASH_PAGE + 1, self.n_pages):
            f, m, p = free[pg], mapped[pg], int(self._pins[pg])
            rc, qn = int(self._rc[pg]), quar[pg]
            assert f <= 1, f"page {pg} on the free list {f} times"
            assert qn <= 1, f"page {pg} quarantined {qn} times"
            assert not (f and qn), f"page {pg} both free and quarantined"
            if f or qn:
                assert rc == 0 and m == 0 and p == 0, (
                    f"page {pg} {'free' if f else 'quarantined'} but "
                    f"referenced (rc={rc}, mapped={m}, pins={p})")
            else:
                assert rc == m + p and rc >= 1, (
                    f"page {pg} leaked or miscounted "
                    f"(rc={rc}, mapped={m}, pins={p})")
        for slot, owned in self._owned.items():
            row = self.tables[slot]
            assert list(row[:len(owned)]) == owned, (
                f"slot {slot}: table row disagrees with ownership")
            assert (row[len(owned):] == TRASH_PAGE).all(), (
                f"slot {slot}: stale table entries past owned blocks")


class ShardedPageTable:
    """dp-sharded page accounting: one independent PageTable per dp shard.

    The device pool's PAGE axis is sharded over ``dp``
    (engine.py: ``P(None, "dp", ...)``), so inside the dp-manual
    shard_map each device sees only its local ``pages_per_shard + 1``
    pages — table entries are therefore LOCAL page indices, and each
    shard's local page 0 is its own trash page. Slot ``s`` lives on shard
    ``s // (n_slots // dp)`` (the contiguous-block layout GSPMD gives a
    batch axis), and allocates only from that shard's free list: page
    locality is a placement invariant, not a runtime check."""

    def __init__(self, n_slots: int, dp: int, pages_per_shard: int,
                 page_size: int, max_blocks: int):
        assert n_slots % dp == 0
        self.dp = dp
        self.page_size = page_size
        self.n_pages = pages_per_shard + 1   # per-shard incl. trash
        self.max_blocks = max_blocks
        self._slots_per = n_slots // dp
        self._pts = [PageTable(self._slots_per, pages_per_shard + 1,
                               page_size, max_blocks) for _ in range(dp)]

    def _loc(self, slot: int):
        return self._pts[slot // self._slots_per], slot % self._slots_per

    @property
    def tables(self):
        import numpy as np
        return np.concatenate([pt.tables for pt in self._pts], axis=0)

    @property
    def n_free(self) -> int:
        return sum(pt.n_free for pt in self._pts)

    def blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def grow(self, slot: int, n_tokens: int) -> bool:
        pt, ls = self._loc(slot)
        return pt.grow(ls, n_tokens)

    def release(self, slot: int):
        pt, ls = self._loc(slot)
        pt.release(ls)

    def owned_blocks(self, slot: int) -> int:
        pt, ls = self._loc(slot)
        return pt.owned_blocks(ls)

    def free_for(self, slot: int) -> int:
        pt, _ = self._loc(slot)
        return pt.n_free

    @property
    def data_pages(self) -> int:
        return self.n_pages - 1

    def shard_of(self, slot: int) -> int:
        return slot // self._slots_per

    # -- epoch fence (delegated per shard) --------------------------------
    # dp > 1 double-buffers like the flat layout: every shard's table
    # advances/retires at the same call-stream position (epochs are
    # global, page quarantines per-shard), so freed pages stay fenced
    # until the dispatch that captured their block-table row lands.

    @property
    def quarantined(self) -> int:
        return sum(pt.quarantined for pt in self._pts)

    @property
    def quiescent(self) -> bool:
        return all(pt.quiescent for pt in self._pts)

    def advance_epoch(self) -> int:
        return max(pt.advance_epoch() for pt in self._pts)

    def retire_epoch(self, epoch: int):
        for pt in self._pts:
            pt.retire_epoch(epoch)

    def drain_quarantine(self) -> int:
        return sum(pt.drain_quarantine() for pt in self._pts)

    def check(self):
        for pt in self._pts:
            pt.check()
