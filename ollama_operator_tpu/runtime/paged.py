"""Host-side page accounting for the paged KV cache.

The device side is a physical page pool ``[L, P, KvH, page_size, hd]``
(``models/decoder.forward_with_cache_paged`` + the pallas kernel in
``ops/pallas/paged.py``); this module owns which physical page backs which
logical block of which slot. Pure host bookkeeping — numpy block tables are
uploaded per dispatch (a few KB), never read back.

Page 0 is the **trash page**: bucket-padding positions beyond a prompt's
valid length scatter their garbage K/V there, so admissions only allocate
pages for real tokens and no masking depends on page contents.

Design notes vs the reference: llama.cpp's unified KV cell pool inside the
delegated `ollama/ollama` image plays this role
(/root/reference/pkg/model/pod.go:11); here the allocator is explicit so
the engine can admit many more concurrent slots than dense max_slots ×
max_seq_len HBM would allow, and preempt (victim-select) when the pool
runs dry (SURVEY.md §7 hard-part 2).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

TRASH_PAGE = 0


class PagesExhausted(RuntimeError):
    """No free pages for the requested allocation (caller may preempt)."""


class PageTable:
    """Block tables + free-list for ``n_slots`` sequences over ``n_pages``
    physical pages of ``page_size`` tokens (page 0 reserved as trash)."""

    def __init__(self, n_slots: int, n_pages: int, page_size: int,
                 max_blocks: int):
        assert n_pages > 1, "need at least one non-trash page"
        self.page_size = page_size
        self.n_pages = n_pages
        self.max_blocks = max_blocks
        # LIFO free list → recently-freed pages are reused first (warm HBM)
        self._free: List[int] = list(range(n_pages - 1, TRASH_PAGE, -1))
        self._owned: Dict[int, List[int]] = {s: [] for s in range(n_slots)}
        self.tables = np.full((n_slots, max_blocks), TRASH_PAGE, np.int32)

    @property
    def n_free(self) -> int:
        return len(self._free)

    def blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def grow(self, slot: int, n_tokens: int) -> bool:
        """Ensure ``slot`` owns pages covering logical positions
        [0, n_tokens). Returns False (allocating nothing) when the pool
        can't satisfy it — the caller preempts or queues."""
        owned = self._owned[slot]
        need = self.blocks_for(n_tokens) - len(owned)
        if need <= 0:
            return True
        if need > len(self._free):
            return False
        if len(owned) + need > self.max_blocks:
            raise ValueError(
                f"slot {slot}: {n_tokens} tokens exceed "
                f"{self.max_blocks} blocks of {self.page_size}")
        for _ in range(need):
            pg = self._free.pop()
            self.tables[slot, len(owned)] = pg
            owned.append(pg)
        return True

    def release(self, slot: int):
        """Free all of ``slot``'s pages (table row resets to trash)."""
        owned = self._owned[slot]
        self._free.extend(owned)
        owned.clear()
        self.tables[slot, :] = TRASH_PAGE

    def owned_blocks(self, slot: int) -> int:
        return len(self._owned[slot])

    def free_for(self, slot: int) -> int:
        """Pages available to ``slot`` (its allocation domain's free count
        — the whole pool here; a dp shard's pool in ShardedPageTable)."""
        return len(self._free)

    @property
    def data_pages(self) -> int:
        """Max pages one slot could ever hold (pool minus the trash page)."""
        return self.n_pages - 1


class ShardedPageTable:
    """dp-sharded page accounting: one independent PageTable per dp shard.

    The device pool's PAGE axis is sharded over ``dp``
    (engine.py: ``P(None, "dp", ...)``), so inside the dp-manual
    shard_map each device sees only its local ``pages_per_shard + 1``
    pages — table entries are therefore LOCAL page indices, and each
    shard's local page 0 is its own trash page. Slot ``s`` lives on shard
    ``s // (n_slots // dp)`` (the contiguous-block layout GSPMD gives a
    batch axis), and allocates only from that shard's free list: page
    locality is a placement invariant, not a runtime check."""

    def __init__(self, n_slots: int, dp: int, pages_per_shard: int,
                 page_size: int, max_blocks: int):
        assert n_slots % dp == 0
        self.dp = dp
        self.page_size = page_size
        self.n_pages = pages_per_shard + 1   # per-shard incl. trash
        self.max_blocks = max_blocks
        self._slots_per = n_slots // dp
        self._pts = [PageTable(self._slots_per, pages_per_shard + 1,
                               page_size, max_blocks) for _ in range(dp)]

    def _loc(self, slot: int):
        return self._pts[slot // self._slots_per], slot % self._slots_per

    @property
    def tables(self):
        import numpy as np
        return np.concatenate([pt.tables for pt in self._pts], axis=0)

    @property
    def n_free(self) -> int:
        return sum(pt.n_free for pt in self._pts)

    def blocks_for(self, n_tokens: int) -> int:
        return -(-n_tokens // self.page_size)

    def grow(self, slot: int, n_tokens: int) -> bool:
        pt, ls = self._loc(slot)
        return pt.grow(ls, n_tokens)

    def release(self, slot: int):
        pt, ls = self._loc(slot)
        pt.release(ls)

    def owned_blocks(self, slot: int) -> int:
        pt, ls = self._loc(slot)
        return pt.owned_blocks(ls)

    def free_for(self, slot: int) -> int:
        pt, _ = self._loc(slot)
        return pt.n_free

    @property
    def data_pages(self) -> int:
        return self.n_pages - 1

    def shard_of(self, slot: int) -> int:
        return slot // self._slots_per
