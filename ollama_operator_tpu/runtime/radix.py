"""Radix-tree prefix cache over physical KV pages.

SGLang's RadixAttention adapted to the paged pool (``runtime/paged.py``):
one tree node = one ``page_size``-aligned token chunk backed by exactly
ONE physical page, so matching, insertion and eviction are all
page-granular. The tree stores only page *ids* plus an LRU stamp — the
KV bytes live in the device pool and refcounts live in the PageTable
(each resident node holds one ``pin`` on its page).

Ownership protocol (driven by Engine.stitch/donate_prefix/radix_evict):

- ``match`` is read-only: the longest cached chunk path for a token
  sequence, plus at most one *partial* boundary node whose first ``q``
  tokens match (the engine copies that page before the new slot writes
  its tail into it — copy-on-write).
- ``insert`` walks/creates nodes for a finished request's full-page
  chunks and returns the nodes it newly created; the engine pins those
  nodes' pages (chunks already present keep the tree's original page and
  the donor's duplicate page is simply freed by its release).
- ``evict`` pops least-recently-used LEAF nodes one page at a time —
  children always leave before parents, so every resident path stays
  contiguous from the root — skipping pages some slot still maps.

A logical clock (bumped per match/insert) orders recency; no wall time,
so multi-host replays stay deterministic.

Epoch-fence interplay (ISSUE 5): the tree itself never frees a page —
eviction hands page ids back to the engine, whose ``unpin`` routes any
refcount-zero page through the PageTable's epoch fence. Under async
dispatch an evicted page therefore sits in quarantine until the decode
dispatch whose block tables captured it materialises, so LRU eviction is
safe to run with a program in flight; under sync dispatch the fence is
pass-through and eviction frees immediately, exactly as before.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple


class _Node:
    __slots__ = ("chunk", "page", "parent", "children", "stamp")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_Node"], stamp: int):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.stamp = stamp


class RadixCache:
    """Trie keyed on page_size token chunks; nodes hold physical pages."""

    def __init__(self, page_size: int):
        assert page_size >= 1
        self.page_size = page_size
        self._root = _Node((), -1, None, 0)
        self._clock = 0
        self._n = 0

    @property
    def n_nodes(self) -> int:
        """Resident nodes == resident pages (one page per node)."""
        return self._n

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, ids: Sequence[int], limit: int, bump: bool = True
              ) -> Tuple[List[_Node], Optional[_Node], int]:
        """Longest cached prefix of ``ids`` within ``limit`` tokens:
        ``(full_nodes, partial_node, partial_len)`` — full-chunk path
        nodes in order, then optionally ONE boundary node whose first
        ``partial_len`` (1 ≤ q < page_size) tokens extend the match.
        ``bump=False`` probes without touching LRU recency."""
        ps = self.page_size
        limit = min(limit, len(ids))
        node = self._root
        full: List[_Node] = []
        pos = 0
        while pos + ps <= limit:
            child = node.children.get(tuple(int(t) for t in ids[pos:pos + ps]))
            if child is None:
                break
            full.append(child)
            node = child
            pos += ps
        part, part_q = None, 0
        room = min(ps, limit - pos)
        if room > 0:
            head = [int(t) for t in ids[pos:pos + room]]
            for chunk, child in node.children.items():
                q = 0
                while q < room and chunk[q] == head[q]:
                    q += 1
                if q > part_q:
                    part, part_q = child, q
        if bump and (full or part is not None):
            stamp = self._tick()
            for n in full:
                n.stamp = stamp
            if part is not None:
                part.stamp = stamp
        return full, part, part_q

    def insert(self, ids: Sequence[int], pages: Sequence[int]) -> List[_Node]:
        """Walk/create the chunk path for ``ids`` (page-aligned,
        ``len(pages)`` chunks); chunk ``i`` is backed by ``pages[i]`` when
        newly created. Returns the NEW nodes — the caller must pin their
        pages; chunks already resident keep the tree's existing page."""
        ps = self.page_size
        assert len(ids) >= len(pages) * ps
        node = self._root
        stamp = self._tick()
        adopted: List[_Node] = []
        for i, pg in enumerate(pages):
            chunk = tuple(int(t) for t in ids[i * ps:(i + 1) * ps])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, int(pg), node, stamp)
                node.children[chunk] = child
                self._n += 1
                adopted.append(child)
            child.stamp = stamp
            node = child
        return adopted

    def evict(self, n_pages: int, evictable: Callable[[int], bool]
              ) -> List[int]:
        """Pop up to ``n_pages`` least-recently-used leaves whose page
        satisfies ``evictable`` (e.g. no slot maps it). Page-by-page:
        each removal may expose its parent as the next leaf. Returns the
        evicted page ids (caller unpins them)."""
        freed: List[int] = []
        while len(freed) < n_pages:
            lru: Optional[_Node] = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                for child in node.children.values():
                    if child.children:
                        stack.append(child)
                    elif evictable(child.page) and (
                            lru is None or child.stamp < lru.stamp):
                        lru = child
            if lru is None:
                break
            del lru.parent.children[lru.chunk]
            self._n -= 1
            freed.append(lru.page)
        return freed

    def reset(self) -> List[int]:
        """Drop every node; returns all resident pages (caller unpins)."""
        pages: List[int] = []
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            pages.append(node.page)
            stack.extend(node.children.values())
        self._root.children.clear()
        self._n = 0
        return pages
