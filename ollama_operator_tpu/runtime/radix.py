"""Radix-tree prefix cache over physical KV pages.

SGLang's RadixAttention adapted to the paged pool (``runtime/paged.py``):
one tree node = one ``page_size``-aligned token chunk backed by exactly
ONE physical page, so matching, insertion and eviction are all
page-granular. The tree stores only page *ids* plus an LRU stamp — the
KV bytes live in the device pool and refcounts live in the PageTable
(each resident node holds one ``pin`` on its page).

Ownership protocol (driven by Engine.stitch/donate_prefix/radix_evict):

- ``match`` is read-only: the longest cached chunk path for a token
  sequence, plus at most one *partial* boundary node whose first ``q``
  tokens match (the engine copies that page before the new slot writes
  its tail into it — copy-on-write).
- ``insert`` walks/creates nodes for a finished request's full-page
  chunks and returns the nodes it newly created; the engine pins those
  nodes' pages (chunks already present keep the tree's original page and
  the donor's duplicate page is simply freed by its release).
- ``evict`` pops least-recently-used LEAF nodes one page at a time —
  children always leave before parents, so every resident path stays
  contiguous from the root — skipping pages some slot still maps.

**Tiered residency** (ISSUE 18): a node's KV may live in HBM
(``tier == 0``, ``page`` is a live pool page) or in the host-RAM arena
(``tier == 1``, ``page == -1`` and ``host`` holds the spilled bytes —
``runtime/host_cache.py``).  The path invariant generalises: every
root→node path is a run of tier-0 nodes followed by a run of tier-1
nodes (never tier-0 below tier-1), because spilling takes the deepest
tier-0 node first (``spill_lru``) and tier-1 pressure drops leaves
first (``drop_host_lru``).  ``match`` is tier-agnostic — the engine
splits the matched path into the shareable tier-0 run and the
restitchable tier-1 run.  Entries imported from a tier-2 fleet snapshot
are ordinary tier-1 nodes whose ``host.snapshot`` flag attributes their
hits to tier 2 in the metrics.

A logical clock (bumped per match/insert) orders recency; no wall time,
so multi-host replays stay deterministic.

Epoch-fence interplay (ISSUE 5): the tree itself never frees a page —
eviction hands page ids back to the engine, whose ``unpin`` routes any
refcount-zero page through the PageTable's epoch fence. Under async
dispatch an evicted page therefore sits in quarantine until the decode
dispatch whose block tables captured it materialises, so LRU eviction is
safe to run with a program in flight; under sync dispatch the fence is
pass-through and eviction frees immediately, exactly as before.
Spilling is stricter: the engine only gathers a page's bytes while the
fence is fully quiescent (no launched dispatch un-retired), so the
host copy can never capture a page an in-flight program still writes.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple


class _Node:
    __slots__ = ("chunk", "page", "parent", "children", "stamp", "tier",
                 "host")

    def __init__(self, chunk: Tuple[int, ...], page: int,
                 parent: Optional["_Node"], stamp: int):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.stamp = stamp
        self.tier = 0
        self.host = None  # HostEntry when tier == 1


class RadixCache:
    """Trie keyed on page_size token chunks; nodes hold physical pages."""

    def __init__(self, page_size: int):
        assert page_size >= 1
        self.page_size = page_size
        self._root = _Node((), -1, None, 0)
        self._clock = 0
        self._n = 0       # all resident nodes (any tier)
        self._n_t0 = 0    # tier-0 nodes == pages the tree pins in HBM
        # host entries orphaned by insert() promotions, drained by the
        # engine (take_dropped_hosts) so the arena accounting stays exact
        self._dropped_hosts: List[object] = []

    @property
    def n_nodes(self) -> int:
        """Resident nodes across all tiers."""
        return self._n

    @property
    def n_pages(self) -> int:
        """Tier-0 nodes == physical pages the tree pins (one each)."""
        return self._n_t0

    @property
    def n_hosted(self) -> int:
        """Tier-1 nodes (KV spilled to the host arena)."""
        return self._n - self._n_t0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def match(self, ids: Sequence[int], limit: int, bump: bool = True
              ) -> Tuple[List[_Node], Optional[_Node], int]:
        """Longest cached prefix of ``ids`` within ``limit`` tokens:
        ``(full_nodes, partial_node, partial_len)`` — full-chunk path
        nodes in order, then optionally ONE boundary node whose first
        ``partial_len`` (1 ≤ q < page_size) tokens extend the match.
        Nodes of any tier are returned; the caller splits by ``tier``.
        ``bump=False`` probes without touching LRU recency."""
        ps = self.page_size
        limit = min(limit, len(ids))
        node = self._root
        full: List[_Node] = []
        pos = 0
        while pos + ps <= limit:
            child = node.children.get(tuple(int(t) for t in ids[pos:pos + ps]))
            if child is None:
                break
            full.append(child)
            node = child
            pos += ps
        part, part_q = None, 0
        room = min(ps, limit - pos)
        if room > 0:
            head = [int(t) for t in ids[pos:pos + room]]
            for chunk, child in node.children.items():
                q = 0
                while q < room and chunk[q] == head[q]:
                    q += 1
                if q > part_q:
                    part, part_q = child, q
        if bump and (full or part is not None):
            stamp = self._tick()
            for n in full:
                n.stamp = stamp
            if part is not None:
                part.stamp = stamp
        return full, part, part_q

    def insert(self, ids: Sequence[int], pages: Sequence[int]) -> List[_Node]:
        """Walk/create the chunk path for ``ids`` (page-aligned,
        ``len(pages)`` chunks); chunk ``i`` is backed by ``pages[i]`` when
        newly created. Returns the nodes that ADOPTED the donor's page —
        the caller must pin those pages. Chunks already resident at
        tier 0 keep the tree's existing page; a chunk resident at
        tier 1 is *promoted*: it adopts the donor's page (also returned
        for pinning) and its host entry lands in ``take_dropped_hosts``
        for the engine to release from the arena."""
        ps = self.page_size
        assert len(ids) >= len(pages) * ps
        node = self._root
        stamp = self._tick()
        adopted: List[_Node] = []
        for i, pg in enumerate(pages):
            chunk = tuple(int(t) for t in ids[i * ps:(i + 1) * ps])
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, int(pg), node, stamp)
                node.children[chunk] = child
                self._n += 1
                self._n_t0 += 1
                adopted.append(child)
            elif child.tier != 0:
                # promotion: the donor hands the tree a live HBM copy of
                # a chunk currently spilled — adopt the page, retire the
                # host bytes (donor path visits parents first, so the
                # tier0*-then-tier1* path invariant is preserved)
                child.page = int(pg)
                child.tier = 0
                self._n_t0 += 1
                if child.host is not None:
                    self._dropped_hosts.append(child.host)
                    child.host = None
                adopted.append(child)
            child.stamp = stamp
            node = child
        return adopted

    def take_dropped_hosts(self) -> List[object]:
        """Host entries orphaned since the last call (insert promotions);
        the engine frees them from the arena."""
        dropped, self._dropped_hosts = self._dropped_hosts, []
        return dropped

    def evict(self, n_pages: int, evictable: Callable[[int], bool]
              ) -> List[int]:
        """Pop up to ``n_pages`` least-recently-used tier-0 leaves whose
        page satisfies ``evictable`` (e.g. no slot maps it). Page-by-page:
        each removal may expose its parent as the next leaf. Returns the
        evicted page ids (caller unpins them). Used on the tierless path
        (host arena off) — with the arena on the engine drives
        ``spill_lru`` instead."""
        freed: List[int] = []
        while len(freed) < n_pages:
            lru: Optional[_Node] = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                for child in node.children.values():
                    if child.children:
                        stack.append(child)
                    elif child.tier == 0 and evictable(child.page) and (
                            lru is None or child.stamp < lru.stamp):
                        lru = child
            if lru is None:
                break
            del lru.parent.children[lru.chunk]
            self._n -= 1
            self._n_t0 -= 1
            freed.append(lru.page)
        return freed

    # ------------------------------------------------------------------
    # tiered residency (host arena)
    # ------------------------------------------------------------------
    def spill_lru(self, evictable: Callable[[int], bool]
                  ) -> Optional[_Node]:
        """The least-recently-used spill candidate: a tier-0 node with NO
        tier-0 children (tier-1 children are fine — they already left
        HBM) whose page satisfies ``evictable``. Deepest-first by
        construction, so spilling keeps every path tier-0-then-tier-1
        contiguous. None when nothing is spillable."""
        lru: Optional[_Node] = None
        stack = [self._root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                if child.tier != 0:
                    continue
                if any(c.tier == 0 for c in child.children.values()):
                    stack.append(child)
                elif evictable(child.page) and (
                        lru is None or child.stamp < lru.stamp):
                    lru = child
        return lru

    def mark_spilled(self, node: _Node, entry) -> int:
        """Transition ``node`` tier 0 → 1: returns its page (the engine
        unpins it) and attaches the arena entry."""
        assert node.tier == 0 and node.page >= 0
        pg, node.page = node.page, -1
        node.tier = 1
        node.host = entry
        self._n_t0 -= 1
        return pg

    def mark_promoted(self, node: _Node, page: int):
        """Transition ``node`` tier 1 → 0 onto a freshly uploaded page
        (the engine pins it); returns the retired host entry for the
        arena to free."""
        assert node.tier != 0 and node.page < 0
        node.page = int(page)
        node.tier = 0
        self._n_t0 += 1
        entry, node.host = node.host, None
        return entry

    def remove(self, node: _Node) -> Tuple[List[int], List[object]]:
        """Remove ``node`` and its whole subtree (eviction fallback when
        a spill is not possible: pruning the subtree keeps paths rooted).
        Returns (tier-0 pages to unpin, host entries to free)."""
        pages: List[int] = []
        hosts: List[object] = []
        stack = [node]
        while stack:
            cur = stack.pop()
            if cur.tier == 0:
                pages.append(cur.page)
                self._n_t0 -= 1
            elif cur.host is not None:
                hosts.append(cur.host)
            self._n -= 1
            stack.extend(cur.children.values())
        del node.parent.children[node.chunk]
        return pages, hosts

    def drop_host_lru(self, n: int = 1) -> List[object]:
        """Drop up to ``n`` least-recently-used tier-1 LEAF nodes (arena
        pressure); returns their host entries for the arena to free.
        Leaf-first keeps tier-1 runs contiguous under their tier-0
        ancestors."""
        dropped: List[object] = []
        while len(dropped) < n:
            lru: Optional[_Node] = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                for child in node.children.values():
                    if child.children:
                        stack.append(child)
                    elif child.tier != 0 and (
                            lru is None or child.stamp < lru.stamp):
                        lru = child
            if lru is None:
                break
            del lru.parent.children[lru.chunk]
            self._n -= 1
            if lru.host is not None:
                dropped.append(lru.host)
        return dropped

    def child(self, parent: Optional[_Node], chunk: Tuple[int, ...]
              ) -> Optional[_Node]:
        """Lookup helper for snapshot import: the existing child of
        ``parent`` (None = root) keyed by ``chunk``."""
        return (parent or self._root).children.get(chunk)

    def insert_host(self, parent: Optional[_Node], chunk: Tuple[int, ...],
                    entry) -> _Node:
        """Attach a NEW tier-1 node under ``parent`` (None = root) —
        tier-2 snapshot import. The caller must have checked ``child``
        first; double-insert is a bug (the arena entry would leak)."""
        node = parent or self._root
        assert chunk not in node.children, "insert_host over existing node"
        stamp = self._tick()
        nn = _Node(chunk, -1, node, stamp)
        nn.tier = 1
        nn.host = entry
        node.children[chunk] = nn
        self._n += 1
        return nn

    def insert_page(self, parent: Optional[_Node], chunk: Tuple[int, ...],
                    page: int) -> _Node:
        """Attach (or promote) ONE tier-0 node under ``parent`` (None =
        root) backed by ``page`` — the disagg KV import's graft. The
        caller already holds the page's pin (``alloc_pinned``). An
        existing tier-1 child is promoted onto ``page`` and its host
        entry lands in ``take_dropped_hosts`` (the import walks parents
        first, so the tier0*-then-tier1* path invariant is preserved);
        an existing tier-0 child is a caller bug — the fresh page would
        leak its pin."""
        node = parent or self._root
        stamp = self._tick()
        child = node.children.get(chunk)
        if child is None:
            child = _Node(chunk, int(page), node, stamp)
            node.children[chunk] = child
            self._n += 1
            self._n_t0 += 1
            return child
        assert child.tier != 0, "insert_page over a tier-0 node"
        child.page = int(page)
        child.tier = 0
        self._n_t0 += 1
        if child.host is not None:
            self._dropped_hosts.append(child.host)
            child.host = None
        child.stamp = stamp
        return child

    def walk(self) -> List[_Node]:
        """Every resident node, parents strictly before children (BFS) —
        the snapshot exporter's traversal order."""
        out: List[_Node] = []
        queue = list(self._root.children.values())
        while queue:
            node = queue.pop(0)
            out.append(node)
            queue.extend(node.children.values())
        return out

    def reset(self) -> List[int]:
        """Drop every node; returns all resident TIER-0 pages (caller
        unpins). Tier-1 host entries die with their nodes — the engine
        clears the arena's accounting wholesale."""
        pages: List[int] = []
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            if node.tier == 0:
                pages.append(node.page)
            stack.extend(node.children.values())
        self._root.children.clear()
        self._n = 0
        self._n_t0 = 0
        self._dropped_hosts = []
        return pages
