"""Continuous-batching scheduler: the host-side loop around the engine.

This is the serving loop of the model server — the piece the reference gets
from `ollama serve` inside the delegated container
(/root/reference/pkg/model/pod.go:14-66). One daemon thread owns the engine:

  admit waiting requests into free slots (prefill) → one decode step for all
  active slots → fan tokens out to per-request queues → retire finished
  slots → repeat; park when idle.

Requests are token-in/token-out here; text concerns (detokenisation, stop
strings, templates) live a layer up in server/. Cancellation is cooperative:
the slot is released on the next loop iteration.
"""

from __future__ import annotations

import dataclasses
import os
import queue
import sys
import threading
import time
import traceback
from typing import Iterator, List, Optional, Sequence

import numpy as np

from ..ops.constrain import GrammarTable
from ..server.metrics import GLOBAL as METRICS
from . import accounting
from . import drafter
from .admission import (DEFAULT_TENANT, PRIORITY_RANK, AdmissionQueue,
                        TenantRateLimited, TenantRateLimiter,
                        observed_throughput_tps, predict_queue_wait_s,
                        retry_after_s, shed_labels)
from .engine import Engine, SlotOptions
from .errors import BadRequest, DeadlineExceeded
from .faults import FAULTS, InjectedFault
from .paged import PagesExhausted
from .trace import FLIGHT, TRACER


class SchedulerBusy(RuntimeError):
    """Raised by submit() when the waiting queue is full (backpressure).
    ``retry_after_s`` rides into the HTTP 503's Retry-After header —
    computed from the admission queue model when one is available."""

    def __init__(self, msg: str, *, retry_after_s: int = 1):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class SchedulerOverloaded(SchedulerBusy):
    """Raised by submit() when the admission queue model predicts the
    request would miss its TTFT SLO — rejected up front (503 + computed
    Retry-After) instead of burning a queue slot and prefill work on a
    doomed request."""


class SchedulerBroken(RuntimeError):
    """Raised by submit() after repeated engine failures wedged the loop."""


class WatchdogTimeout(RuntimeError):
    """Raised on the scheduler thread when a dispatch wait exceeds the
    hung-dispatch watchdog budget (TPU_DISPATCH_WATCHDOG_MS, or the
    auto-derived ceiling from the dispatch histograms). Treated exactly
    like an engine failure: supervised restart, then replay."""


# Lifecycle knobs are read per call, not cached at construction: a test
# (or an operator live-tuning a deployment) can flip them on a running
# scheduler and the next restart/drain honors the new value.

def replay_max_streams() -> int:
    """TPU_RESTART_REPLAY_MAX: streams replayed per restart (0 = replay
    disabled — every in-flight stream errors exactly once, PR 2
    semantics)."""
    return int(os.environ.get("TPU_RESTART_REPLAY_MAX", "64") or "0")


def replay_token_budget() -> int:
    """TPU_RESTART_REPLAY_TOKENS: aggregate prompt+generated tokens the
    replay prefill may re-process per restart — bounds the recovery
    stall a restart can add before fail-safe erroring kicks in."""
    return int(os.environ.get("TPU_RESTART_REPLAY_TOKENS", "65536")
               or "0")


def drain_timeout_s() -> float:
    """TPU_DRAIN_TIMEOUT_S: how long drain() lets running streams finish
    before shedding stragglers (the operator sizes the pod's
    terminationGracePeriodSeconds from this plus shutdown slack)."""
    return float(os.environ.get("TPU_DRAIN_TIMEOUT_S", "30") or "0")


@dataclasses.dataclass
class RequestStats:
    n_prompt: int = 0
    n_generated: int = 0
    n_reused: int = 0       # prompt tokens served from the prefix cache
    t_submit: float = 0.0
    t_admitted: float = 0.0
    t_first_token: float = 0.0
    t_done: float = 0.0

    @property
    def ttft_s(self) -> float:
        return max(self.t_first_token - self.t_submit, 0.0)

    @property
    def decode_tok_s(self) -> float:
        dur = self.t_done - self.t_first_token
        if dur <= 0 or self.n_generated <= 1:
            return 0.0
        return (self.n_generated - 1) / dur


class Request:
    _ids = iter(range(1, 1 << 62))
    _ids_lock = threading.Lock()

    def __init__(self, prompt_ids: Sequence[int], opts: SlotOptions,
                 max_tokens: int, eog_ids: frozenset,
                 embeds: Optional[np.ndarray] = None, constraint=None,
                 deadline: Optional[float] = None,
                 priority: str = "normal",
                 tenant: str = DEFAULT_TENANT):
        with Request._ids_lock:
            self.id = next(Request._ids)
        # admission-policy state (host-side only — never broadcast):
        # priority class, fairness tenant, and the WDRR token cost
        # (prompt + predicted decode tokens, refined by submit())
        self.priority = priority
        self.rank = PRIORITY_RANK.get(priority, 1)
        self.tenant = tenant
        self.cost = float(len(prompt_ids) + max_tokens)
        # throttle-preemption resume gate: _next_waiting must not hand
        # this request a slot again before this monotonic stamp
        self.resume_at = 0.0
        self.prompt_ids = np.asarray(prompt_ids, np.int32)
        self.embeds = embeds          # [n_prompt, D] multimodal embeddings
        self.constraint = constraint  # ops/constrain.py grammar state
        # device-grammar program for this request's token table: None =
        # not yet resolved, False = unavailable (capability off, build
        # failed, or another grammar owns the device tables), else the
        # installed GrammarTable (see Scheduler._grammar_table)
        self._gtable = None
        self.opts = opts
        self.max_tokens = max_tokens
        self.eog_ids = eog_ids
        self.out: queue.Queue = queue.Queue()
        self.cancelled = threading.Event()
        self.stats = RequestStats(n_prompt=len(self.prompt_ids),
                                  t_submit=time.monotonic())
        # span timeline (runtime/trace.py): queued → admit/stitch →
        # prefill pieces → decode dispatches → detok → HTTP flush.
        # begin() returns the shared no-op trace when TPU_TRACE=0.
        self.trace = TRACER.begin(self.id)
        # monotonic stamp of the last token chunk delivered, for the
        # chunk-normalized tpu_model_itl_seconds observation in _fanout
        self._t_last_emit = 0.0
        self.slot: Optional[int] = None
        self.error: Optional[str] = None
        # absolute time.monotonic() budget, or None for no deadline:
        # expired while queued → shed (503), expired mid-generation →
        # terminal frame with finish reason "timeout"
        self.deadline = deadline
        # terminal reason from the ("done", reason) frame, readable after
        # chunks()/tokens() returns — "stop", "length", "timeout", ...
        self.done_reason: Optional[str] = None
        # every sampled token (incl. EOG), for parking the slot's KV as a
        # reusable prefix after the request finishes
        self.all_tokens: List[int] = []
        # prompt-lookup drafting index: final-bigram → position of its
        # continuation in (prompt + generated), maintained incrementally
        # so drafting stays O(k) per step on long contexts
        self._bigram_idx: dict = {}
        self._indexed_upto = 0
        # set when the request is preempted (paged pool pressure): the
        # full prompt + tokens generated so far; re-admission prefills
        # from here and generation continues seamlessly on the same
        # output queue
        self.resume_ids: Optional[np.ndarray] = None

    @property
    def admit_ids(self) -> np.ndarray:
        return (self.resume_ids if self.resume_ids is not None
                else self.prompt_ids)

    def cancel(self):
        self.cancelled.set()

    def tokens(self) -> Iterator[int]:
        """Blocking iterator over generated token ids."""
        for chunk in self.chunks():
            for tid in chunk:
                yield tid

    def chunks(self) -> Iterator[List[int]]:
        """Blocking iterator over per-dispatch batches of token ids.

        The scheduler queues ONE item per decode chunk (plus one for the
        prefill-sampled token), not one per token — consumers that can
        batch (detokenisation, HTTP frame assembly) should iterate here
        instead of tokens() to keep queue/lock traffic per request at
        O(generated / decode_chunk)."""
        while True:
            kind, payload = self.out.get()
            if kind == "tokens":
                yield payload
            elif kind == "done":
                self.done_reason = payload
                return
            elif kind == "shed":
                msg, retry_after_s = payload
                raise DeadlineExceeded(msg, while_queued=True,
                                       retry_after_s=retry_after_s)
            else:  # error
                raise RuntimeError(payload)


class _PrefillJob:
    """A request whose prompt is admitting piece by piece (chunked
    prefill): ``done`` tokens of ``req.admit_ids`` are already in the
    slot's KV cache. Between pieces the slot is parked (engine-inactive),
    so the scheduler — not the engine — must remember it is taken."""

    __slots__ = ("req", "done")

    def __init__(self, req: Request, done: int):
        self.req = req
        self.done = done


class Scheduler:
    # a parked prefix must beat this many cached tokens to be worth an
    # extend over a fresh admit (tiny reuses still pay a full slice+write)
    MIN_PREFIX_REUSE = 16
    # ceiling on the supervised-restart backoff (it doubles per
    # consecutive failure starting from restart_backoff)
    RESTART_BACKOFF_CAP = 2.0

    def __init__(self, engine: Engine, max_queue: int = 256,
                 max_restarts: Optional[int] = None,
                 restart_backoff: Optional[float] = None,
                 prefill_chunk: Optional[int] = None,
                 async_dispatch: Optional[bool] = None):
        self.engine = engine
        # reuse floor (TPU_MIN_PREFIX_REUSE): prefixes shorter than this
        # admit cold — a tiny reuse still pays a full extend dispatch, so
        # raising the floor trades cache hits for fewer small programs;
        # lowering it helps only when dispatch is near-free (colocated
        # host). Parked-slot reuse and radix stitches honor the same
        # floor.
        self.min_prefix_reuse = int(os.environ.get(
            "TPU_MIN_PREFIX_REUSE", "") or self.MIN_PREFIX_REUSE)
        # radix prefix cache (paged, single sub-pool): finished prefixes
        # are donated to a shared page-granular tree instead of parked in
        # one slot, so N concurrent requests can hit the same prefix
        self._use_radix = bool(getattr(engine, "radix_enabled", False))
        # crash-only supervision: after a decode-loop failure the engine
        # state is rebuilt in-process up to max_restarts consecutive
        # times before the scheduler goes terminally `broken` (which
        # needs a model reload / pod restart to clear)
        self.max_restarts = (
            max_restarts if max_restarts is not None
            else int(os.environ.get("TPU_ENGINE_MAX_RESTARTS", "3")))
        self.restart_backoff = (
            restart_backoff if restart_backoff is not None
            else float(os.environ.get("TPU_ENGINE_RESTART_BACKOFF_S",
                                      "0.05")))
        self.n_restarts = 0
        # fused prompt-lookup speculative decoding (TPU_SPEC_DECODE=k):
        # draft up to k tokens PER SLOT from bigram matches in that
        # slot's own prompt+generated history (runtime/drafter.py); ONE
        # bucketed dispatch (engine.decode_n_launch(drafts=...)) then
        # verifies every draft and advances every slot — greedy
        # penalty-free slots accept their matching prefix + a bonus
        # token, everyone else steps exactly one decode-identical token
        # inside the same program. Rejection costs a sentinel mask and a
        # host-length ack (engine.spec_ack), never a second dispatch,
        # and the path double-buffers like dense/paged decode — no
        # cause="spec" sync fallback remains. The old standalone
        # decode_spec surface (623 ms/dispatch in BENCH_r05, compiling
        # per bucket crossing mid-request) is gone; its anomaly is now a
        # warm-pass concern (engine.warm_buckets pre-compiles every
        # (k, bucket) spec program). Opt-in: acceptance is workload-
        # dependent — watch the spec block in /api/ps and keep it
        # enabled only when the acceptance rate holds (docs give
        # guidance).
        self.spec_k = int(os.environ.get("TPU_SPEC_DECODE", "0") or "0")
        # drafted/accepted running totals back the /api/ps acceptance-
        # rate block (counters also exported via metrics)
        self.spec_drafted = 0
        self.spec_accepted = 0
        # stall-free chunked prefill (Sarathi-style): prompts longer than
        # one piece admit bucket-by-bucket through Engine.extend, one
        # piece per scheduler step, so the worst-case stall a DECODING
        # slot sees is one piece's prefill, not one prompt's. 0 disables;
        # unset derives from decode_chunk (rounded up to a real bucket).
        if prefill_chunk is None:
            pc_env = os.environ.get("TPU_PREFILL_CHUNK", "")
            prefill_chunk = (int(pc_env) if pc_env
                             else engine.ecfg.decode_chunk * 8)
        self.prefill_chunk = (
            engine.bucket_for(min(int(prefill_chunk), engine.max_seq))
            if prefill_chunk and engine.supports_extend else 0)
        # double-buffered async dispatch: launch decode dispatch N+1
        # before materialising N's tokens, so host fan-out/detokenise
        # overlaps device compute (JAX async dispatch). The only
        # remaining sync fallback is HOST-masked grammar (a fresh host
        # PDA mask per token — device-table grammar slots ride async,
        # see _fanout); fused speculation double-buffers with its
        # stages reordered — see the spec branch in _step. Paged mode
        # double-buffers too, dp-sharded pools included: the page
        # table's epoch fence quarantines freed pages until the
        # dispatch that captured their block table materialises
        # (ShardedPageTable delegates the fence per shard), so
        # recycling can never corrupt an in-flight program's reads
        # (runtime/paged.py).
        if async_dispatch is None:
            async_dispatch = os.environ.get(
                "TPU_ASYNC_DISPATCH", "1").lower() not in ("0", "false")
        self.async_dispatch = bool(async_dispatch)
        # epoch of the newest decode handle already materialised — the
        # next launch passes it back as retire= so the engine unfences
        # pages quarantined at or before it (and so followers, which
        # never wait on handles, retire at the identical call position)
        self._fence_ack = 0
        # slot → _PrefillJob for requests mid-chunked-prefill (the slot
        # is engine-inactive between pieces; without this map
        # free_slots() would hand it to someone else)
        self._prefilling: dict = {}
        # (DecodeHandle, {slot: request-at-launch}, per-slot drafted
        # counts or None) of the in-flight decode dispatch, when
        # double-buffering — drafted counts feed the acceptance metrics
        # when the handle materialises
        self._pending = None
        # device-grammar escape bookkeeping: slot → request whose
        # ALREADY-LAUNCHED next dispatch ran with the slot frozen
        # (its automaton escaped the device table mid-chunk); that
        # dispatch's rows for the slot are garbage and its launch-time
        # length advance rolls back at fan-out (see _fanout)
        self._gdiscard: dict = {}
        # the waiting line: strict-priority classes + per-tenant WDRR
        # over token budgets + SLO-aware early rejection
        # (runtime/admission.py). Host-side policy state only — nothing
        # here is ever mirrored to multi-host followers.
        self._admission = AdmissionQueue(max_queue=max_queue)
        # per-tenant decode-token rate limiting (TPU_TENANT_TOKEN_RATE);
        # over-rate best-effort requests are throttle-preempted into
        # _throttled and resume on the same stream once their bucket
        # refills
        self._limiter = TenantRateLimiter.from_env()
        self._throttled: List[Request] = []
        self.n_throttles = 0
        # priority preemption: a queued high-class request may evict a
        # running strictly-lower-class one (resumable preempt) instead
        # of waiting a full generation for a slot — the mechanism that
        # keeps high-priority TTFT flat at 5× offered load
        self._priority_preempt = os.environ.get(
            "TPU_PRIORITY_PREEMPT", "1").lower() not in ("0", "false")
        # EWMA of generated tokens per finished request — the "predicted
        # decode tokens" half of a request's WDRR token cost (max_tokens
        # alone over-charges every short completion)
        self._avg_decode = 64.0
        # preempted requests (paged pool pressure) re-admit before the
        # waiting queue — they already hold a place in the line
        self._preempted: List[Request] = []
        self.n_preemptions = 0
        # restart replay (stream-preserving recovery): _fail_running
        # moves replayable in-flight requests here instead of erroring
        # them; _supervised_restart re-admits them through the preempt
        # resume machinery once the engine is rebuilt. Scheduler-thread
        # owned between shutdown()/drain() joins.
        self._recovering: List[Request] = []
        self.n_replays = 0
        self.n_replay_fallbacks = 0
        # graceful drain: submit() sheds (503 + Retry-After) while set;
        # running streams keep going until drain()'s timeout
        self.draining = False
        # hung-dispatch watchdog: a persistent helper thread runs each
        # blocking dispatch wait so the scheduler thread can bound it;
        # on a fire the worker is abandoned (fresh queues next time — a
        # late result must never be delivered to the wrong generation)
        self._wd_thread: Optional[threading.Thread] = None
        self._wd_req: Optional[queue.Queue] = None
        self._wd_resp: Optional[queue.Queue] = None
        self.n_watchdog_fires = 0
        self._running: List[Optional[Request]] = [None] * engine.n_slots
        # slot → token ids (prompt + generated) still resident in its KV
        # cache; candidates for prefix-cache reuse (ollama keeps the same
        # conversation hot in a llama.cpp slot; here any shared prefix —
        # system prompt, earlier chat turns — is reusable)
        self._parked: dict = {}
        # exclusive tasks (disagg KV export/import): closures drained at
        # the top of _step, ON the scheduler thread, so page gathers and
        # radix grafts never race a dispatch (run_exclusive)
        self._tasks: List = []
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self.broken = False
        self._consecutive_failures = 0
        self.total_generated = 0
        self.total_prompt = 0
        # utilization & goodput accounting (runtime/accounting.py):
        # per-dispatch FLOPs/goodput splits + the dispatch-wait/host/idle
        # wall-clock breakdown. make_accounting honors TPU_ACCOUNTING=0
        # at construction (bench A/B flips the module flag between arms).
        self.acct = accounting.make_accounting(getattr(engine, "cfg", None))
        self.finished: List[RequestStats] = []  # ring of recent stats
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpu-scheduler")
        self._thread.start()

    # ------------------------------------------------------------------
    def run_exclusive(self, fn, timeout_s: float = 30.0):
        """Run ``fn()`` on the scheduler thread, between steps, and
        return its result (re-raising its exception).  The disagg KV
        export/import paths ride this: they touch the page table, the
        radix tree, and the KV pool, none of which may be mutated while
        a dispatch is being assembled.  The scheduler drains queued
        tasks at the top of every ``_step`` — under load that is after
        the in-flight dispatch lands; idle, the wake event pops the
        0.05s wait immediately.  Raises TimeoutError if the scheduler
        thread is wedged (or broken) past ``timeout_s``; the task is
        then abandoned (a late run finds its waiter gone and discards
        the result via the ``dead`` flag)."""
        done = threading.Event()
        cell: dict = {"dead": False}

        def task():
            try:
                r = fn()
                if not cell["dead"]:
                    cell["r"] = r
            except BaseException as e:  # noqa: BLE001 — relayed to caller
                if not cell["dead"]:
                    cell["e"] = e
            finally:
                done.set()

        with self._lock:
            if self.broken:
                raise SchedulerBroken(
                    "scheduler stopped after repeated engine failures")
            self._tasks.append(task)
        self._wake.set()
        if not done.wait(timeout_s):
            cell["dead"] = True
            raise TimeoutError(
                f"scheduler did not run exclusive task in {timeout_s}s")
        if "e" in cell:
            raise cell["e"]
        return cell.get("r")

    def _run_tasks(self):
        """Drain queued exclusive tasks (scheduler thread only).  A task
        raising is the task's problem — relayed to its waiter by the
        wrapper, never a scheduler failure."""
        if not self._tasks:
            return
        with self._lock:
            tasks, self._tasks = self._tasks, []
        for t in tasks:
            t()

    def _tokens_done(self) -> float:
        """Tokens the engine has pushed through so far (prompt +
        generated), live — the numerator of the queue model's observed
        throughput."""
        return float(self.total_prompt + self.total_generated)

    def submit(self, prompt_ids: Sequence[int],
               opts: SlotOptions = SlotOptions(),
               max_tokens: int = 128,
               eog_ids: frozenset = frozenset(),
               embeds: Optional[np.ndarray] = None,
               constraint=None,
               deadline_s: Optional[float] = None,
               priority: str = "normal",
               tenant: str = DEFAULT_TENANT,
               ttft_slo_s: Optional[float] = None) -> Request:
        if len(prompt_ids) >= self.engine.max_seq:
            raise BadRequest(
                f"prompt of {len(prompt_ids)} tokens exceeds context window "
                f"{self.engine.max_seq}")
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None and deadline_s > 0 else None)
        req = Request(prompt_ids, opts, max_tokens, eog_ids, embeds=embeds,
                      constraint=constraint, deadline=deadline,
                      priority=priority, tenant=tenant)
        # WDRR token cost: prompt + predicted decode tokens (EWMA of
        # recent completions, capped by this request's own budget)
        req.cost = float(len(prompt_ids)
                         + min(max_tokens, max(16, int(self._avg_decode))))
        # broken-check + enqueue under the lock: the failure path flips
        # `broken` and drains under the same lock, so a request can never
        # slip into the queue after the final drain (its reader would hang)
        victim = None
        with self._lock:
            if self.broken:
                raise SchedulerBroken(
                    "scheduler stopped after repeated engine failures")
            if self.draining:
                # graceful drain: running streams finish, NEW work goes
                # to the next replica — 503 + Retry-After sized to the
                # drain window so the client's retry lands post-rollout
                retry = min(120, max(1, int(drain_timeout_s())))
                METRICS.inc("tpu_model_requests_shed_total")
                METRICS.inc("tpu_model_drain_shed_total")
                FLIGHT.record("shed", rid=req.id, cause="draining",
                              cls=priority, tenant=tenant,
                              retry_after_s=retry)
                raise SchedulerBusy("server draining",
                                    retry_after_s=retry)
            cap = int(os.environ.get("TPU_TENANT_MAX_QUEUED", "0") or 0)
            if cap > 0 and self._admission.queued_for(tenant) >= cap:
                # this tenant specifically is over its share: 429, not
                # 503 — global backpressure signals would be a lie
                METRICS.inc("tpu_model_requests_shed_total")
                METRICS.inc("tpu_model_shed_total",
                            labels=shed_labels(priority, "tenant_cap"))
                FLIGHT.record("shed", rid=req.id, cause="tenant_cap",
                              cls=priority, tenant=tenant, cap=cap)
                raise TenantRateLimited(
                    f"tenant {tenant!r} already has {cap} requests "
                    f"queued", retry_after_s=min(30, max(1, cap)))
            if ttft_slo_s is not None:
                # queue model: token backlog at equal-or-higher priority
                # ÷ observed throughput. A request predicted to miss its
                # TTFT SLO is rejected NOW, with a Retry-After computed
                # from how long that backlog needs to drain — not after
                # wasting a queue slot and prefill work on a timeout.
                backlog = self._admission.backlog_tokens(req.rank)
                try:
                    predicted = predict_queue_wait_s(backlog,
                                                     self._tokens_done())
                except Exception as e:  # noqa: BLE001 — incl. injected
                    # faults at admission.predict: the predictor is an
                    # optimisation, so it fails OPEN (admit; the
                    # deadline machinery still covers the request)
                    FLIGHT.record("admission_predict_failed",
                                  rid=req.id, error=str(e)[:120])
                    predicted = 0.0
                if predicted > ttft_slo_s:
                    tps = observed_throughput_tps(self._tokens_done())
                    retry = retry_after_s(predicted, ttft_slo_s, tps)
                    METRICS.inc("tpu_model_requests_shed_total")
                    METRICS.inc("tpu_model_shed_total",
                                labels=shed_labels(priority,
                                                   "slo_predict"))
                    FLIGHT.record(
                        "early_reject", rid=req.id, cls=priority,
                        tenant=tenant,
                        predicted_ms=int(predicted * 1e3),
                        slo_ms=int(ttft_slo_s * 1e3), retry_after_s=retry)
                    raise SchedulerOverloaded(
                        f"predicted queue wait {predicted:.2f}s exceeds "
                        f"ttft_slo {ttft_slo_s:.2f}s",
                        retry_after_s=retry)
            accepted, victim = self._admission.offer(req)
            if not accepted:
                # full and nothing lower-priority to displace: reject
                # the incoming request with a computed Retry-After and
                # record its (zero-length) queue wait — the same
                # accounting every other shed path gets
                retry = self._retry_after_estimate(req.rank)
                self._observe_wait(req)
                METRICS.inc("tpu_model_requests_shed_total")
                METRICS.inc("tpu_model_shed_total",
                            labels=shed_labels(priority, "queue_full"))
                FLIGHT.record("shed", rid=req.id, cause="queue_full",
                              cls=priority, tenant=tenant,
                              qsize=self._admission.max_queue,
                              retry_after_s=retry)
                raise SchedulerBusy(
                    f"request queue full ({self._admission.max_queue} "
                    f"waiting)", retry_after_s=retry) from None
        if victim is not None:
            # queue pressure displaced a strictly lower-priority queued
            # request (shed-lowest-first); outside the lock — _shed
            # takes it for the finished ring. The dedicated "displaced"
            # event (distinct from the victim's own "shed") puts the
            # *eviction* in the flight-recorder timeline with both sides'
            # identities.
            FLIGHT.record("displaced", rid=victim.id, cls=victim.priority,
                          tenant=victim.tenant, by=req.id,
                          by_cls=req.priority)
            self._shed(victim, cause="queue_full")
        req.trace.set_identity(priority, tenant)
        req.trace.event("queued", n_prompt=len(prompt_ids),
                        max_tokens=max_tokens, cls=priority,
                        tenant=tenant)
        self._wake.set()
        return req

    def _retry_after_estimate(self, rank: int) -> int:
        """Retry-After for a rejected request: queue-model drain time of
        the backlog at its priority, floored at 1s (falls back to a
        depth heuristic when the model has no throughput signal yet)."""
        backlog = self._admission.backlog_tokens(rank)
        tps = observed_throughput_tps(self._tokens_done())
        if tps > 0:
            return int(min(max(1, round(backlog / tps + 0.5)), 120))
        return min(30, max(1, self.qsize))

    def shutdown(self):
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
        # idle watchdog worker exits on the sentinel; an ABANDONED one
        # (post-fire) is a daemon parked on a dead queue — harmless
        if self._wd_req is not None:
            self._wd_req.put(None)
            self._wd_thread = None
        # an in-flight dispatch's tokens die with the loop; its owners
        # are still in _running and drain below
        self._pending = None
        self._prefilling.clear()
        # unfence anything the dropped dispatch was holding: the engine
        # may outlive this scheduler (model swap builds a fresh one), and
        # a page parked in quarantine forever is a pool leak
        try:
            if self.engine.quarantined_pages:
                self.engine.fence_quiesce()
        except Exception:  # lint: allow(exception-hygiene): engine may already be torn down
            pass
        # drain everything still attached so no caller blocks forever on
        # req.tokens() after an unload (model swap, server shutdown)
        for slot, req in enumerate(self._running):
            if req is not None:
                self._running[slot] = None
                req.stats.t_done = time.monotonic()
                req.out.put(("done", "unloaded"))
        for req in self._preempted + self._throttled + self._recovering:
            req.out.put(("done", "unloaded"))
        self._preempted.clear()
        self._throttled.clear()
        self._recovering.clear()
        for req in self._admission.drain():
            req.out.put(("done", "unloaded"))

    def begin_drain(self):
        """Flip into draining (the SIGTERM path): new submits shed with
        503 + Retry-After, running streams keep generating. Idempotent;
        cleared only by tearing the scheduler down."""
        with self._lock:
            if self.draining or self.broken:
                return
            self.draining = True
        METRICS.inc("tpu_model_drain_started_total")
        FLIGHT.record("drain", phase="begin", running=self.n_active,
                      queued=self.qsize)

    def drain(self, timeout_s: Optional[float] = None) -> int:
        """Graceful drain: begin_drain(), wait up to ``timeout_s``
        (default TPU_DRAIN_TIMEOUT_S) for every attached stream to
        finish, then shed stragglers — running streams get a terminal
        ``("done", "drain")`` frame (partial output stands, finish
        reason tells the client it was a rollout, not a stop token),
        waiting ones shed 503. Returns the straggler count. The decode
        loop is stopped before straggler teardown (drain is always
        followed by shutdown), so the teardown can't race a dispatch."""
        self.begin_drain()
        if timeout_s is None:
            timeout_s = drain_timeout_s()
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            if not self.has_pending:
                break
            time.sleep(0.02)
        shed = 0
        if self.has_pending:
            self._stop.set()
            self._wake.set()
            self._thread.join(timeout=10)
            self._pending = None
            self._prefilling.clear()
            try:
                if self.engine.quarantined_pages:
                    self.engine.fence_quiesce()
            except Exception:  # lint: allow(exception-hygiene): engine may be torn down
                pass
            retry = min(120, max(1, int(timeout_s) or 1))
            for slot, req in enumerate(self._running):
                if req is None:
                    continue
                self._running[slot] = None
                req.stats.t_done = time.monotonic()
                req.out.put(("done", "drain"))
                try:
                    self.engine.release(slot)
                except Exception:  # lint: allow(exception-hygiene): best-effort teardown
                    pass
                shed += 1
            for req in (self._preempted + self._throttled
                        + self._recovering):
                req.out.put(("shed", ("server draining", retry)))
                shed += 1
            self._preempted.clear()
            self._throttled.clear()
            self._recovering.clear()
            for req in self._admission.drain():
                req.out.put(("shed", ("server draining", retry)))
                shed += 1
            if shed:
                METRICS.inc("tpu_model_drain_shed_total", float(shed))
        FLIGHT.record("drain", phase="complete", shed=shed)
        return shed

    @property
    def n_active(self) -> int:
        return sum(r is not None for r in self._running)

    @property
    def qsize(self) -> int:
        """Requests waiting for a slot (queued + preempted + throttled +
        recovering). Public API for metrics and the server's load probes
        — external code must not reach into the admission queue."""
        return (len(self._admission) + len(self._preempted)
                + len(self._throttled) + len(self._recovering))

    @property
    def has_pending(self) -> bool:
        """True while any request is running, queued, preempted,
        throttled, or awaiting restart replay — i.e. unloading the model
        now would strand a caller."""
        return (self.n_active > 0 or bool(self._preempted)
                or bool(self._throttled) or bool(self._recovering)
                or not self._admission.empty())

    def admission_stats(self) -> dict:
        """Live admission-policy snapshot for /api/ps: per-class queue
        depth/backlog, throttle state, and the policy knobs in force."""
        out = self._admission.stats()
        out.update({
            "default_priority": os.environ.get("TPU_DEFAULT_PRIORITY",
                                               "normal") or "normal",
            "ttft_slo_ms": float(os.environ.get("TPU_TTFT_SLO_MS", "0")
                                 or 0),
            "priority_preempt": self._priority_preempt,
            "rate_limited_tenants": self._limiter.enabled,
            "throttled": len(self._throttled),
            "throttles": self.n_throttles,
            "shed_by_class": {
                p: int(sum(METRICS.get("tpu_model_shed_total",
                                       shed_labels(p, c))
                           for c in ("queue_full", "deadline",
                                     "slo_predict", "tenant_cap")))
                for p in PRIORITY_RANK},
        })
        return out

    def lifecycle_stats(self) -> dict:
        """Lifecycle snapshot for /api/ps: serving/draining/broken state,
        the restart-replay budget in force, and watchdog posture."""
        return {
            "state": ("broken" if self.broken
                      else "draining" if self.draining else "serving"),
            # disagg pool role stamped by the operator on pool
            # Deployments; "" = unified replica (routing is the
            # gateway's job — this is the observable, not the switch)
            "pool": os.environ.get("TPU_DISAGG_ROLE", ""),
            # live work counters: the operator's drain-first scale-down
            # polls these to know when a victim replica is empty
            "active_streams": self.n_active,
            "queued": self.qsize,
            "restarts": self.n_restarts,
            "replay": {
                "enabled": replay_max_streams() > 0,
                "max_streams": replay_max_streams(),
                "token_budget": replay_token_budget(),
                "replayed_streams": self.n_replays,
                "fallbacks": self.n_replay_fallbacks,
                "recovering": len(self._recovering),
            },
            "watchdog": {
                "timeout_s": round(self._watchdog_timeout_s(), 3),
                "fires": self.n_watchdog_fires,
            },
        }

    def utilization_stats(self, window_s: float = 60.0) -> dict:
        """Utilization snapshot for /api/ps (and the operator's Model CR
        status mirror): MFU, goodput, occupancy/waste, wall-clock
        breakdown, and the engine's mid-serving recompile counts."""
        out = self.acct.snapshot(window_s)
        out["recompiles"] = dict(getattr(self.engine, "recompiles", {}))
        return out

    # ------------------------------------------------------------------
    def _finish(self, slot: int, req: Request, reason: str):
        # the LAST sampled token was never fed back through the model, so
        # its K/V is not (reliably) in the cache — park everything before it
        parkable = (list(req.prompt_ids) + req.all_tokens)[:-1]
        park = (self.engine.supports_extend and req.embeds is None
                and reason in ("stop", "length") and len(parkable) > 0)
        if self._use_radix:
            # radix mode: donate the full-page-aligned prefix to the
            # shared tree (pages pinned, slot freed) instead of parking
            # the whole thing in this one slot
            if park:
                self.engine.donate_prefix(slot, parkable)
            else:
                self.engine.release(slot)
        else:
            self.engine.release(slot, park=park)
            if park:
                self._parked[slot] = parkable
            else:
                self._parked.pop(slot, None)
        self._running[slot] = None
        req.stats.t_done = time.monotonic()
        # EWMA of decode lengths feeds the admission cost model (token
        # budget = prompt + predicted decode, not request counts)
        self._avg_decode += 0.2 * (req.stats.n_generated - self._avg_decode)
        req.trace.event("finish", reason=reason, slot=slot,
                        n_generated=req.stats.n_generated)
        with self._lock:
            self.finished.append(req.stats)
            if len(self.finished) > 512:
                self.finished = self.finished[-256:]
        req.out.put(("done", reason))

    def _emit_first(self, req: Request, tid: int) -> bool:
        """Queue the prefill-sampled token as its own chunk; returns False
        if the request just finished. This token flushes immediately —
        it IS the TTFT token, and holding it back to the first decode
        flush would add a whole chunk dispatch to first-token latency."""
        if req.stats.n_generated == 0:
            # guard: a preempted request re-admitting must keep its
            # original first-token stamp
            req.stats.t_first_token = time.monotonic()
        req.all_tokens.append(tid)  # EOG included: it sits in the KV cache
        if tid in req.eog_ids:
            return False
        req.stats.n_generated += 1
        self.total_generated += 1
        req._t_last_emit = time.monotonic()
        req.trace.event("first_token")
        self._limiter.debit(req.tenant, 1)
        METRICS.inc("tpu_model_tenant_decode_tokens_total", 1.0,
                    f'{{tenant="{req.tenant}"}}')
        req.out.put(("tokens", [tid]))
        return req.stats.n_generated < req.max_tokens

    def _best_prefix(self, req: Request):
        """(slot, reuse_len) of the parked slot sharing the longest token
        prefix with the request, or (None, 0). At least one tail token must
        remain to prefill (the parked last position has no cached logits),
        and the tail's bucket must fit above the reused prefix."""
        if (self._use_radix or req.embeds is not None
                or not self.engine.supports_extend):
            return None, 0
        ids = req.admit_ids
        best, best_m = None, 0
        for slot, parked in self._parked.items():
            k = min(len(parked), len(ids) - 1)
            m = 0
            while m < k and parked[m] == ids[m]:
                m += 1
            if m > best_m:
                best, best_m = slot, m
        if best is None or best_m < self.min_prefix_reuse:
            return None, 0
        tail_bucket = self.engine.bucket_for(len(ids) - best_m)
        if best_m + tail_bucket > self.engine.max_seq:
            return None, 0
        return best, best_m

    def _quiesce(self, cause: str) -> int:
        """engine.fence_quiesce with a flight-recorder breadcrumb:
        quarantine transitions are exactly the events that explain a
        mysterious pool-dry stall after the fact."""
        n_q = self.engine.quarantined_pages
        freed = self.engine.fence_quiesce()
        if n_q or freed:
            FLIGHT.record("fence_quiesce", cause=cause,
                          quarantined=n_q, freed=freed)
        return freed

    def _next_waiting(self) -> Optional[Request]:
        """Priority-aware head of the waiting line. Preempted requests
        still re-admit ahead of queued ones OF THE SAME CLASS (they
        already held a place in line), but a queued higher-priority
        request now beats a preempted lower-priority one — the FIFO
        version of this method is what made overload ordering
        arbitrary."""
        best_i = None
        for i, r in enumerate(self._preempted):
            if best_i is None or r.rank < self._preempted[best_i].rank:
                best_i = i
        qrank = self._admission.peek_rank()
        if best_i is not None:
            if qrank is None or self._preempted[best_i].rank <= qrank:
                return self._preempted.pop(best_i)
        return self._admission.pop()

    def _evict_one_parked(self, n_pages: int = 1) -> bool:
        """Return cached pages to the pool under pressure. Radix mode:
        evict up to ``n_pages`` LRU-unreferenced radix leaves (page
        granular — cold tails of cold prefixes go first). Parked-slot
        mode: drop one whole parked prefix (oldest parked first). False
        when there was nothing to evict."""
        if self._use_radix:
            return self.engine.radix_evict(n_pages) > 0
        for slot in list(self._parked):
            if self._running[slot] is None:
                self._parked.pop(slot)
                self.engine.free_slot_pages(slot)
                return True
        return False

    def _stitch_admission(self, slot: int, req: Request) -> int:
        """Radix-mode admission prep: probe the tree, apply the reuse
        floor and the tail-bucket fit (trimming page-by-page keeps the
        stitch page-aligned — the partial boundary drops first), then
        stitch the shared pages into ``slot``. A dry pool during the
        copy-on-write falls back to a cold admit (stitch leaves the slot
        clean) after nudging eviction along."""
        ids = req.admit_ids
        want = self.engine.prefix_probe(ids)
        ps = self.engine.ecfg.page_size
        while (want >= self.min_prefix_reuse
               and want + self.engine.bucket_for(len(ids) - want)
               > self.engine.max_seq):
            want = (want - 1) // ps * ps
        if want < self.min_prefix_reuse:
            return 0
        try:
            t0 = time.perf_counter()
            got = self.engine.stitch(slot, ids, want)
            ls = getattr(self.engine, "last_stitch", None)
            if got:
                # per-tier breakdown rides the request to _post_admit
                # (metrics attribution); restitch latency is observed
                # enqueue-side — the uploads themselves overlap the tail
                # prefill asynchronously
                req._tier_stitch = ls
                if ls and (ls["t1"] or ls["t2"]):
                    METRICS.observe("tpu_model_restitch_seconds",
                                    time.perf_counter() - t0)
                req.trace.event("stitch", slot=slot, reused=got, tiers=ls)
            return got
        except PagesExhausted:
            if self._pending is not None or self.engine.quarantined_pages:
                # likely fenced, not dry: unfence instead of evicting
                self._drain_pending()
                self._quiesce("pool_dry_stitch")
            else:
                self._evict_one_parked()
            return 0

    def _pages_for(self, n_tokens: int) -> int:
        """Eviction sizing hint: pages a prompt of ``n_tokens`` needs
        (+1 headroom). Radix eviction is page-granular, so freeing one
        page per failed admission would thrash retry passes."""
        ps = getattr(self.engine.ecfg, "page_size", 1) or 1
        return -(-n_tokens // ps) + 1

    def _observe_wait(self, req: Request):
        """Record the request's queue wait (global + per-class series).
        Every way out of the waiting line observes exactly once: first
        admission (_post_admit) or any shed — a shed IS the end of that
        request's wait, and a wait histogram that drops its worst
        entries under overload reads dangerously healthy."""
        wait = max(time.monotonic() - req.stats.t_submit, 0.0)
        METRICS.observe("tpu_model_queue_wait_seconds", wait)
        METRICS.observe("tpu_model_class_queue_wait_seconds", wait,
                        f'{{class="{req.priority}"}}')

    def _shed(self, req: Request, cause: str = "deadline"):
        """Reject a request that will never hold a slot: deadline
        expired while it waited (cause="deadline") or it was displaced
        by a higher-priority arrival under queue pressure
        (cause="queue_full"). The caller never got a token, so this
        maps to 503 + Retry-After (DeadlineExceeded raised from
        chunks()) rather than a terminal stream frame."""
        retry_after = self._retry_after_estimate(req.rank)
        req.error = ("deadline exceeded while queued"
                     if cause == "deadline"
                     else "shed under queue pressure by a "
                          "higher-priority request")
        req.stats.t_done = time.monotonic()
        req.trace.event("shed", cause=cause)
        FLIGHT.record("shed", rid=req.id, cause=cause, cls=req.priority,
                      tenant=req.tenant, retry_after_s=retry_after)
        with self._lock:
            self.finished.append(req.stats)
        self._observe_wait(req)
        METRICS.inc("tpu_model_requests_shed_total")
        METRICS.inc("tpu_model_shed_total",
                    labels=shed_labels(req.priority, cause))
        req.out.put(("shed", (req.error, retry_after)))

    def _shed_expired(self):
        """Drop queued/preempted requests whose deadline already passed
        or that were cancelled while still waiting — without this sweep
        a request deep in the queue behind busy slots would hold its
        reader (and its queue slot) until a decode slot finally freed."""
        now = time.monotonic()

        def expired(r):
            return r.deadline is not None and now > r.deadline

        def dead(r):
            return expired(r) or r.cancelled.is_set()

        for req in self._admission.sweep(dead):
            if req.cancelled.is_set():
                req.out.put(("done", "cancelled"))
            else:
                self._shed(req)
        # throttled requests whose rate-limit debt has drained become
        # ordinary preempted requests again (same resume machinery)
        ripe = [r for r in self._throttled if r.resume_at <= now]
        for req in ripe:
            self._throttled.remove(req)
            self._preempted.append(req)
        # a preempted/throttled request already streamed tokens from its
        # first admission — its expiry is a mid-generation timeout
        # (terminal frame), not a shed
        for pool in (self._preempted, self._throttled):
            for req in [r for r in pool if expired(r)]:
                pool.remove(req)
                req.stats.t_done = time.monotonic()
                with self._lock:
                    self.finished.append(req.stats)
                METRICS.inc("tpu_model_request_timeouts_total")
                req.out.put(("done", "timeout"))

    def _request_error(self, req: Request, msg: str):
        """Terminal error frame for a request that never held (or just
        lost) a slot."""
        req.error = msg
        req.stats.t_done = time.monotonic()
        with self._lock:
            self.finished.append(req.stats)
        req.out.put(("error", msg))

    def _post_admit(self, slot: int, req: Request, first: int):
        """Shared admission tail (one-shot, batched, and the final
        chunked piece): stats, slot ownership, grammar gate, first-token
        emit."""
        req.slot = slot
        if req.stats.t_admitted == 0:
            # first admission only — a preempted request re-admitting
            # must not re-count its prompt in throughput stats (nor
            # re-observe its queue wait: that wait already happened)
            self.total_prompt += req.stats.n_prompt
            self._observe_wait(req)
        req.stats.t_admitted = time.monotonic()
        req.trace.event("admitted", slot=slot,
                        reused=int(req.stats.n_reused))
        FLIGHT.record("admit", rid=req.id, slot=slot,
                      n_prompt=int(req.stats.n_prompt),
                      reused=int(req.stats.n_reused))
        # prefix-cache accounting per ADMISSION (re-admissions re-count:
        # a preempted request's second prefill is real compute): hit =
        # tokens served from cache (radix stitch or parked-slot extend),
        # miss = tokens actually prefilled
        n_re = min(req.stats.n_reused, len(req.admit_ids))
        METRICS.inc("tpu_model_prefix_hit_tokens_total", float(n_re))
        METRICS.inc("tpu_model_prefix_miss_tokens_total",
                    float(len(req.admit_ids) - n_re))
        # tiered attribution of the same tokens (ISSUE 18): which tier
        # served the reuse (0 = HBM-shared, 1 = host restitch, 2 =
        # fleet-snapshot restitch); misses split into never-cached
        # tokens (tier 0) and spilled tokens the break-even model chose
        # to recompute (tier 1/2)
        ls = getattr(req, "_tier_stitch", None) or {}
        t12 = ls.get("t1", 0) + ls.get("t2", 0)
        skip = ls.get("skip1", 0) + ls.get("skip2", 0)
        for tier, n in (("0", max(n_re - t12, 0)),
                        ("1", ls.get("t1", 0)), ("2", ls.get("t2", 0))):
            if n:
                METRICS.inc("tpu_model_tier_hit_tokens_total", float(n),
                            f'{{tier="{tier}"}}')
        for tier, n in (("0", len(req.admit_ids) - n_re - skip),
                        ("1", ls.get("skip1", 0)),
                        ("2", ls.get("skip2", 0))):
            if n > 0:
                METRICS.inc("tpu_model_tier_miss_tokens_total", float(n),
                            f'{{tier="{tier}"}}')
        req._tier_stitch = None
        self._running[slot] = req
        # grammar check before emitting (see _fanout)
        if (req.constraint is not None
                and first not in req.eog_ids
                and not req.constraint.advance(first)):
            self._finish(slot, req, "stop")
        elif not self._emit_first(req, first):
            # EOG is a natural stop; an exhausted max_tokens budget is a
            # truncation — Ollama clients tell them apart by done_reason
            self._finish(slot, req, "stop"
                         if req.all_tokens[-1] in req.eog_ids
                         else "length")
        elif req.constraint is not None:
            self._refresh_mask(slot, req)

    def _expired_at_admission(self, req: Request) -> bool:
        """Deadline re-check at the moment a request is about to touch
        the engine. A request can expire AFTER the `_next_waiting` pop —
        earlier admissions in the same pass block on prefill dispatches —
        and admitting it anyway wastes a full prefill before a
        mid-generation `timeout`. A fresh request (never emitted a
        token) sheds with 503 + Retry-After; a resumed one already
        streamed tokens, so its expiry stays a terminal timeout frame.
        Returns True when the request was terminated here."""
        if req.deadline is None or time.monotonic() <= req.deadline:
            return False
        if req.resume_ids is not None:
            METRICS.inc("tpu_model_request_timeouts_total")
            req.stats.t_done = time.monotonic()
            with self._lock:
                self.finished.append(req.stats)
            req.out.put(("done", "timeout"))
        else:
            self._shed(req)
        return True

    def _admit_one(self, slot: int, req: Request, reuse_len: int) -> bool:
        """One blocking admission (fresh or prefix-reusing). Returns
        False when the paged pool ran dry and the request was requeued —
        the caller should stop admitting this pass."""
        if self._expired_at_admission(req):
            return True
        t0 = time.perf_counter()
        try:
            mask_row = (req.constraint.mask_row()
                        if req.constraint is not None else None)
            try:
                if reuse_len:
                    first = self.engine.extend(slot, req.admit_ids,
                                               reuse_len, req.opts,
                                               mask_row=mask_row)
                else:
                    first = self.engine.admit(slot, req.admit_ids,
                                              req.opts, embeds=req.embeds,
                                              mask_row=mask_row)
            except PagesExhausted:
                if not (reuse_len and self._use_radix):
                    raise
                # the stitched tail ran dry (extend already rolled the
                # shared mappings back): fall back to a COLD admit once —
                # a genuinely dry pool raises again and requeues below
                reuse_len = 0
                req._tier_stitch = None
                first = self.engine.admit(slot, req.admit_ids, req.opts,
                                          embeds=req.embeds,
                                          mask_row=mask_row)
            req.stats.n_reused = reuse_len
        except PagesExhausted as e:
            # paged pool dry: under async dispatch first drain the
            # pipeline and unfence quarantined pages (they may merely be
            # fenced behind the in-flight dispatch, not truly gone), then
            # evict cached pages; either way retry this request next
            # pass — with nothing to reclaim it waits for a finisher
            # (unless it can never fit at all)
            if not self.engine.admissible(len(req.admit_ids)):
                self._request_error(
                    req, f"prompt needs more KV pages than the pool "
                         f"has: {e}")
                return True
            if self._pending is not None or self.engine.quarantined_pages:
                self._drain_pending()
                self._quiesce("pool_dry_admit")
            else:
                self._evict_one_parked(self._pages_for(len(req.admit_ids)))
            self._preempted.insert(0, req)
            return False
        except Exception as e:  # surfacing engine errors to the caller
            self._request_error(req, str(e))
            return True
        dur = time.perf_counter() - t0
        METRICS.inc("tpu_model_admission_stall_ms_total", dur * 1e3)
        kind = "extend" if reuse_len else "admit"
        METRICS.observe("tpu_model_dispatch_seconds", dur,
                        f'{{kind="{kind}"}}')
        n_new = len(req.admit_ids) - reuse_len
        self.acct.on_prefill(dur, reuse_len, n_new,
                             self.engine.bucket_for(n_new))
        self.acct.on_wait(dur)
        req.trace.event("prefill", kind=kind, dur_ms=round(dur * 1e3, 3),
                        n_tokens=n_new)
        self._post_admit(slot, req, first)
        return True

    def _start_chunked(self, slot: int, req: Request,
                       reuse_len: int) -> bool:
        """First piece of a chunked admission: prefill one
        prefill_chunk-sized bucket, park the slot, and register the job —
        the remaining pieces interleave with decode dispatches
        (_advance_prefill). Returns False when the paged pool ran dry and
        the request was requeued."""
        if self._expired_at_admission(req):
            return True
        ids = req.admit_ids
        end = reuse_len + self.prefill_chunk
        t0 = time.perf_counter()
        try:
            try:
                if reuse_len:
                    self.engine.extend(slot, ids[:end], reuse_len)
                else:
                    self.engine.admit(slot, ids[:end])
            except PagesExhausted:
                if not (reuse_len and self._use_radix):
                    raise
                # stitched first piece ran dry mid-COW/tail: cold-start
                # the chunked prefill once (stitch/extend rolled the
                # shared mappings back)
                reuse_len, end = 0, self.prefill_chunk
                req._tier_stitch = None
                self.engine.admit(slot, ids[:end])
            req.stats.n_reused = reuse_len
            # park between pieces: cache and lengths stay, the slot goes
            # engine-inactive so decode dispatches skip it
            self.engine.release(slot, park=True)
        except PagesExhausted as e:
            if not self.engine.admissible(len(ids)):
                self._request_error(
                    req, f"prompt needs more KV pages than the pool "
                         f"has: {e}")
                return True
            if self._pending is not None or self.engine.quarantined_pages:
                # fenced, not dry (see _admit_one): unfence, don't evict
                self._drain_pending()
                self._quiesce("pool_dry_admit")
            else:
                self._evict_one_parked(self._pages_for(len(ids)))
            self._preempted.insert(0, req)
            return False
        except Exception as e:
            self._request_error(req, str(e))
            return True
        dur = time.perf_counter() - t0
        METRICS.inc("tpu_model_prefill_chunks_total")
        METRICS.inc("tpu_model_admission_stall_ms_total", dur * 1e3)
        kind = "extend" if reuse_len else "admit"
        METRICS.observe("tpu_model_dispatch_seconds", dur,
                        f'{{kind="{kind}"}}')
        n_new = end - reuse_len
        self.acct.on_prefill(dur, reuse_len, n_new,
                             self.engine.bucket_for(n_new))
        self.acct.on_wait(dur)
        req.trace.event("prefill_piece", kind=kind, done=end,
                        of=len(ids), dur_ms=round(dur * 1e3, 3))
        req.slot = slot
        self._running[slot] = req
        self._prefilling[slot] = _PrefillJob(req, end)
        return True

    def _abort_prefill(self, slot: int, reason: str):
        job = self._prefilling.pop(slot)
        req = job.req
        self._running[slot] = None
        self.engine.release(slot)
        req.stats.t_done = time.monotonic()
        with self._lock:
            self.finished.append(req.stats)
        req.out.put(("done", reason))

    def _advance_prefill(self):
        """One prefill piece for the oldest chunked-admission job — at
        most one per scheduler step, so decoding slots never stall more
        than one piece per dispatch. The final piece runs with the
        request's real options/grammar mask and samples its TTFT token
        (PRNG-seed-identical to a one-shot admission: the seed derives
        from (slot, full prompt length))."""
        if not self._prefilling:
            return
        slot = next(iter(self._prefilling))
        job = self._prefilling[slot]
        req = job.req
        if req.cancelled.is_set():
            self._abort_prefill(slot, "cancelled")
            return
        if req.deadline is not None and time.monotonic() > req.deadline:
            if req.resume_ids is None:
                # no token ever reached the client: this is a shed
                # (503 + Retry-After), not a mid-generation timeout
                self._prefilling.pop(slot)
                self._running[slot] = None
                req.slot = None
                self.engine.release(slot)
                self._shed(req)
            else:
                METRICS.inc("tpu_model_request_timeouts_total")
                self._abort_prefill(slot, "timeout")
            return
        ids = req.admit_ids
        start = job.done
        end = min(job.done + self.prefill_chunk, len(ids))
        final = end == len(ids)
        t0 = time.perf_counter()
        try:
            if final:
                mask_row = (req.constraint.mask_row()
                            if req.constraint is not None else None)
                first = self.engine.extend(slot, ids, job.done, req.opts,
                                           mask_row=mask_row)
            else:
                self.engine.extend(slot, ids[:end], job.done)
                self.engine.release(slot, park=True)
                job.done = end
        except PagesExhausted:
            # mid-prefill pool pressure: back out and requeue; the
            # re-admission restarts the prompt (no tokens were emitted)
            self._prefilling.pop(slot, None)
            self._running[slot] = None
            req.slot = None
            self.engine.release(slot)
            self._evict_one_parked(self._pages_for(len(ids)))
            self._preempted.insert(0, req)
            return
        # any other engine failure propagates to the supervisor, which
        # errors every running request (this one included) exactly once
        # and restarts — _fail_running clears _prefilling
        dur = time.perf_counter() - t0
        METRICS.inc("tpu_model_prefill_chunks_total")
        METRICS.inc("tpu_model_admission_stall_ms_total", dur * 1e3)
        METRICS.observe("tpu_model_dispatch_seconds", dur,
                        '{kind="extend"}')
        self.acct.on_prefill(dur, start, end - start,
                             self.engine.bucket_for(end - start))
        self.acct.on_wait(dur)
        req.trace.event("prefill_piece", kind="extend", done=end,
                        of=len(ids), dur_ms=round(dur * 1e3, 3))
        if final:
            self._prefilling.pop(slot, None)
            self._post_admit(slot, req, first)

    def _flush_admit_batch(self, batch: dict):
        """Admit the same-bucket groups collected this pass: groups of 4
        then 2 take ONE batched dispatch each; leftovers (and any group
        whose batched dispatch failed) fall back to sequential
        admission."""
        for bucket, items in batch.items():
            # deadlines re-checked here too: earlier groups' dispatches
            # may have burned this batch's remaining budget
            items = [(s, r) for s, r in items
                     if not self._expired_at_admission(r)]
            while len(items) >= 2:
                m = 4 if len(items) >= 4 else 2
                group, items = items[:m], items[m:]
                t0 = time.perf_counter()
                try:
                    toks = self.engine.admit_many(
                        [s for s, _ in group],
                        [r.admit_ids for _, r in group],
                        [r.opts for _, r in group])
                except Exception:  # noqa: BLE001 — pool dry, injected
                    # fault, ...: the batched program mutated nothing
                    # (paged grows roll back), so each request retries
                    # on the single-admit path with its own error
                    # handling
                    for s, r in group:
                        self._admit_one(s, r, 0)
                    continue
                dur = time.perf_counter() - t0
                METRICS.inc("tpu_model_admission_stall_ms_total",
                            dur * 1e3)
                METRICS.observe("tpu_model_dispatch_seconds", dur,
                                '{kind="admit"}')
                # one batched dispatch: split its wall time evenly so the
                # ring's busy_s doesn't count the dispatch m times
                for _, r in group:
                    self.acct.on_prefill(dur / m, 0, len(r.admit_ids),
                                         bucket)
                self.acct.on_wait(dur)
                for (s, r), tok in zip(group, toks):
                    # batched admissions are always cold (a resumed
                    # request must not re-report its first admission's
                    # reuse as a fresh cache hit)
                    r.stats.n_reused = 0
                    r.trace.event("prefill", kind="admit", batched=m,
                                  dur_ms=round(dur * 1e3, 3),
                                  n_tokens=len(r.admit_ids))
                    self._post_admit(s, r, tok)
            for s, r in items:
                self._admit_one(s, r, 0)

    def _admit_waiting(self):
        # slots mid-chunked-prefill are engine-inactive but TAKEN
        free = [s for s in self.engine.free_slots()
                if s not in self._prefilling]
        batch: dict = {}   # prefill bucket → [(slot, req)] to batch-admit
        try:
            while free:
                req = self._next_waiting()
                if req is None:
                    return
                if req.cancelled.is_set():
                    req.out.put(("done", "cancelled"))
                    continue
                if (req.deadline is not None
                        and time.monotonic() > req.deadline):
                    # expired between the sweep and this pop
                    if req.resume_ids is not None:
                        METRICS.inc("tpu_model_request_timeouts_total")
                        req.out.put(("done", "timeout"))
                    else:
                        self._shed(req)
                    continue
                reuse_slot, reuse_len = self._best_prefix(req)
                if reuse_slot is not None:
                    slot = reuse_slot
                    free.remove(slot)
                else:
                    # prefer slots that (a) sit on a dp shard whose
                    # sub-pool can actually hold this prompt (paged×dp:
                    # shard-blind picks would raise PagesExhausted and
                    # thrash evictions while another shard idles) and
                    # (b) have no parked prefix, keeping reusable caches
                    # alive as slots allow
                    n_tok = len(req.admit_ids)

                    def _pick():
                        for cond in (
                                lambda s: s not in self._parked
                                and self.engine.can_admit(s, n_tok),
                                lambda s: self.engine.can_admit(s, n_tok),
                                lambda s: s not in self._parked):
                            for s in free:
                                if cond(s):
                                    return s
                        return free[0]
                    slot = _pick()
                    free.remove(slot)
                # the slot's parked cache is spoken for either way: on
                # success the request owns it; on failure the slot state
                # is unknown and must not be offered for reuse again (a
                # stale entry would also crash the NEXT request's
                # free.remove in this same pass)
                self._parked.pop(slot, None)
                ids = req.admit_ids
                if self._use_radix and req.embeds is None:
                    # radix mode: stitch the tree's longest usable prefix
                    # into the slot; the tail admits via extend below
                    # (reuse 0 = cold admit, slot left clean)
                    reuse_len = self._stitch_admission(slot, req)
                piece = self.prefill_chunk
                if (piece and len(ids) - reuse_len > piece
                        and req.embeds is None
                        and len(ids) + piece <= self.engine.max_seq):
                    # long prompt: admit piecewise, one piece per step
                    if not self._start_chunked(slot, req, reuse_len):
                        return
                    continue
                if (not reuse_len and req.embeds is None
                        and req.constraint is None
                        and self.engine.supports_admit_many):
                    # same-bucket fresh admissions coalesce into one
                    # batched dispatch at the end of the pass
                    bucket = self.engine.bucket_for(len(ids))
                    batch.setdefault(bucket, []).append((slot, req))
                    continue
                if not self._admit_one(slot, req, reuse_len):
                    return
        finally:
            self._flush_admit_batch(batch)

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._step()
            except Exception as e:  # noqa: BLE001 — a decode error must not
                # kill the daemon thread: that would leave every in-flight
                # tokens() reader blocked forever while /healthz stays green.
                traceback.print_exc(file=sys.stderr)
                FLIGHT.record("engine_failure", error=str(e)[:200],
                              consecutive=self._consecutive_failures + 1)
                self._consecutive_failures += 1
                final = self._consecutive_failures > self.max_restarts
                # no replay on the terminal failure: a stream parked in
                # _recovering would only be errored again by the broken
                # drain below — classify it straight to the error frame
                self._fail_running(str(e), replay=not final)
                if final:
                    with self._lock:
                        self.broken = True
                        self._drain_waiting(("error", f"engine failed: {e}"))
                    return
                self._supervised_restart()

    def _supervised_restart(self):
        """Rebuild engine state in-process after a decode-loop failure.

        Crash-only recovery: the requests that were mid-flight on the
        failing step were already errored by _fail_running; everything
        still waiting or preempted stays queued and is re-admitted once
        the engine is clean. Costs a slot-state reset, NOT a model
        reload or pod restart — the weights and compiled executables are
        untouched. Goes terminally `broken` only when max_restarts
        consecutive rebuilds all fail to produce one good step.
        """
        # release EVERY slot (not just the running ones): a failing step
        # leaves cache/page accounting in an unknown state, so parked
        # prefixes are unsafe to reuse and their pages must go back to
        # the pool. release() also resets host-side lengths and masks.
        for slot in range(self.engine.n_slots):
            try:
                self.engine.release(slot)
            except Exception:  # lint: allow(exception-hygiene): best-effort teardown
                pass
        self._parked.clear()
        # the radix tree's pages were released with the slots above only
        # if nothing pinned them — drop every tree reference too, or the
        # rebuilt engine would stitch prefixes whose cache contents are
        # unknown (and the pins would leak pool pages forever)
        radix_reset = getattr(self.engine, "radix_reset", None)
        if radix_reset is not None:
            try:
                radix_reset()
            except Exception:  # lint: allow(exception-hygiene): best-effort teardown
                pass
        self.n_restarts += 1
        METRICS.inc("tpu_model_engine_restarts_total")
        # black-box post-mortem: record the restart itself, then dump
        # the ring so the job log shows the last N structured events
        # (admissions, the injected fault, the failure) BEFORE this
        # recovery — chaos CI greps for this block
        FLIGHT.record("restart", n=self.n_restarts,
                      consecutive=self._consecutive_failures)
        FLIGHT.dump(f"supervised restart #{self.n_restarts}")
        # capped exponential backoff before retrying; interruptible so
        # shutdown() never waits behind a sleeping supervisor
        delay = min(self.restart_backoff
                    * (2 ** (self._consecutive_failures - 1)),
                    self.RESTART_BACKOFF_CAP)
        if delay > 0:
            self._stop.wait(delay)
        # re-admit the replayable streams ahead of the waiting queue:
        # resume_ids is already set, so the normal preempt/resume path
        # re-prefills prompt+generated (chunked for long contexts) and
        # generation continues from the next token on the same output
        # queue — bit-identical for greedy and seeded streams
        if self._recovering:
            recov, self._recovering = self._recovering, []
            self._preempted[:0] = recov
            FLIGHT.record("replay_readmit", n=len(recov))
            self._wake.set()

    @staticmethod
    def _replay_ineligible(req: Request) -> Optional[str]:
        """Why a stream can NOT be replayed bit-identically, or None.

        The determinism contract (engine.py): greedy streams
        (temperature == 0) and seeded streams (opts.seed >= 0, base key
        slot-independent, per-step keys fold_in(key, position)) resume
        byte-identical through the preempt/resume machinery. Unseeded
        temperature sampling derives its base key from (slot, seq_len) —
        both change on resume — and mirostat's mu state is re-seeded at
        admission, so neither can promise the same continuation.
        Multimodal prompts can't re-prefill from token ids at all."""
        if req.embeds is not None:
            return "multimodal"
        o = req.opts
        if o.temperature > 0.0 and o.seed < 0:
            return "nondeterministic"
        if o.mirostat:
            return "nondeterministic"
        return None

    def _fail_running(self, message: str, replay: bool = True):
        # the in-flight async dispatch (and any mid-chunked-prefill
        # state) dies with the engine state; every owner is still in
        # _running. Replayable streams move to _recovering — after the
        # supervised rebuild they re-admit through the preempt/resume
        # machinery and continue on the same output queue, so the client
        # sees a stall, never an error. Everything else (non-
        # deterministic, multimodal, over the replay budget, injected
        # replay fault, or ``replay=False`` because the loop is going
        # terminally broken) falls back to today's exactly-ONE error
        # frame.
        FLIGHT.record("fail_running", error=message[:200],
                      n_running=self.n_active)
        self._pending = None
        self._prefilling.clear()
        budget = replay_token_budget()
        max_streams = replay_max_streams() if replay else 0
        taken = 0
        for slot, req in enumerate(self._running):
            if req is None:
                continue
            self._running[slot] = None
            cause = (self._replay_ineligible(req) if replay
                     else "broken")
            cost = len(req.prompt_ids) + len(req.all_tokens)
            if cause is None and (taken >= max_streams or cost > budget):
                cause = "over_budget"
            if cause is None:
                try:
                    FAULTS.check("scheduler.replay")
                except InjectedFault:
                    cause = "faulted"
            if cause is None:
                budget -= cost
                taken += 1
                req.resume_ids = np.concatenate(
                    [req.prompt_ids,
                     np.asarray(req.all_tokens, np.int32)])
                req.slot = None
                self._recovering.append(req)
                self.n_replays += 1
                METRICS.inc("tpu_model_replayed_requests_total")
                METRICS.inc("tpu_model_replayed_tokens_total",
                            float(cost))
                req.trace.event("replay", slot=slot,
                                n_generated=req.stats.n_generated)
                FLIGHT.record("replay", rid=req.id, slot=slot,
                              outcome="recovered", tokens=cost,
                              n_generated=req.stats.n_generated)
            else:
                self.n_replay_fallbacks += 1
                METRICS.inc("tpu_model_replay_fallback_total",
                            labels=f'{{cause="{cause}"}}')
                FLIGHT.record("replay", rid=req.id, slot=slot,
                              outcome="fallback", cause=cause)
                req.error = message
                req.stats.t_done = time.monotonic()
                req.out.put(("error", message))
            try:
                self.engine.release(slot)
            except Exception:  # lint: allow(exception-hygiene): best-effort slot reset
                pass
        # the releases above (and the restart's parked/radix teardown
        # next) must not strand pages in quarantine — the failed epoch
        # will never be acked by a wait. Drain via the fence if the
        # devices still answer, else reclaim host-side: device programs
        # are serialized by donated-cache data dependencies, so any
        # zombie dispatch finishes before a post-restart program could
        # touch a recycled page.
        try:
            self.engine.fence_quiesce()
        except Exception:  # noqa: BLE001 — poisoned device state
            pt = getattr(self.engine, "_pt", None)
            if pt is not None:
                pt.drain_quarantine()
        self._fence_ack = 0

    def _drain_waiting(self, msg):
        for req in self._preempted + self._throttled + self._recovering:
            req.out.put(msg)
        self._preempted.clear()
        self._throttled.clear()
        self._recovering.clear()
        for req in self._admission.drain():
            req.out.put(msg)

    def _relieve_pressure(self, n_steps: Optional[int]):
        """Paged mode: make sure every active slot has pages for the next
        decode chunk. Pressure relief order: (1) evict parked prefix
        caches, (2) preempt the newest active requests — their generation
        state is requeued (resume_ids) and continues on the same output
        stream after re-admission. Multimodal requests are preempted last
        (their image embeds cannot be re-prefilled from token ids) and
        errored if no alternative exists."""
        while True:
            victims = self.engine.prepare_decode(n_steps)
            if not victims:
                return
            # pipeline stall beats sacrifice: under async dispatch the
            # missing pages may merely be FENCED behind the in-flight
            # dispatch (quarantined until it materialises), not truly
            # exhausted — drain the pipeline and unfence before evicting
            # anyone's cache or preempting a generation. One stall per
            # pool-dry event, vs a re-prefill per needless preemption.
            if self._pending is not None or self.engine.quarantined_pages:
                self._drain_pending()
                self._quiesce("pool_dry_decode")
                continue
            if self._evict_one_parked():
                continue
            cand = [s for s in victims if self._running[s] is not None]
            if not cand:
                return  # nothing actionable; decode_n will surface it
            non_mm = [s for s in cand if self._running[s].embeds is None]
            if non_mm:
                # priority-aware sacrifice: lowest class first, newest
                # admission within a class — a best_effort straggler
                # yields its pages before any high request does
                slot = max(non_mm,
                           key=lambda s: (self._running[s].rank,
                                          self._running[s].stats.t_admitted))
                self._preempt_slot(slot, cause="pool_pressure")
            else:
                slot = cand[0]
                req = self._running[slot]
                self._running[slot] = None
                self.engine.release(slot)
                req.error = ("preempted under KV-pool pressure; multimodal "
                             "requests cannot resume")
                req.stats.t_done = time.monotonic()
                with self._lock:
                    self.finished.append(req.stats)
                req.out.put(("error", req.error))

    def _preempt_slot(self, slot: int, cause: str,
                      resume_delay: float = 0.0) -> Request:
        """Evict a running (non-multimodal) request from its slot,
        recording resume_ids so re-admission re-prefills prompt+generated
        onto the same output stream (seed-identical for greedy). With
        ``resume_delay`` the request parks in _throttled and only
        becomes admissible once its rate-limit debt drains."""
        req = self._running[slot]
        self._running[slot] = None
        self.engine.release(slot)
        req.resume_ids = np.concatenate(
            [req.prompt_ids, np.asarray(req.all_tokens, np.int32)])
        req.slot = None
        self.n_preemptions += 1
        METRICS.inc("tpu_model_preemptions_total")
        req.trace.event("preempted", slot=slot, cause=cause,
                        n_generated=req.stats.n_generated)
        FLIGHT.record("preempt", rid=req.id, slot=slot, cause=cause,
                      n_generated=req.stats.n_generated)
        if resume_delay > 0.0:
            req.resume_at = time.monotonic() + resume_delay
            self._throttled.append(req)
        else:
            self._preempted.append(req)
        return req

    def _preempt_for_priority(self):
        """With every slot busy and a strictly-higher-priority request
        waiting, evict ONE lowest-priority running request (newest
        admission breaks ties) so the high request's TTFT doesn't hide
        behind a best_effort generation. At most one victim per step —
        the freed slot is admitted this same pass, so pressure converges
        without thrashing. Gated by TPU_PRIORITY_PREEMPT (default on)."""
        if not self._priority_preempt:
            return
        if any(s not in self._prefilling
               for s in self.engine.free_slots()):
            return
        ranks = [r.rank for r in self._preempted]
        qrank = self._admission.peek_rank()
        if qrank is not None:
            ranks.append(qrank)
        if not ranks:
            return
        want = min(ranks)
        cand = [s for s, r in enumerate(self._running)
                if r is not None and s not in self._prefilling
                and r.embeds is None and r.rank > want]
        if not cand:
            return
        slot = max(cand, key=lambda s: (self._running[s].rank,
                                        self._running[s].stats.t_admitted))
        self._preempt_slot(slot, cause="priority")

    def _throttle_over_limit(self):
        """Mid-stream rate limiting: a best_effort slot whose tenant's
        decode-token bucket has gone negative is preempted (same
        resume machinery — the surviving stream is bit-identical for
        greedy sampling) and parks in _throttled until the debt drains.
        Higher classes are debited but never throttled."""
        if not self._limiter.enabled:
            return
        for slot, req in list(self._decoding().items()):
            if (req.priority != "best_effort" or req.embeds is not None
                    or req.stats.n_generated <= 0):
                continue
            delay = self._limiter.debt_delay(req.tenant)
            if delay <= 0.0:
                continue
            self.n_throttles += 1
            METRICS.inc(
                "tpu_model_tenant_throttles_total",
                labels=f'{{class="{req.priority}",tenant="{req.tenant}"}}')
            req.trace.event("throttled", tenant=req.tenant,
                            delay_ms=round(delay * 1e3, 1))
            FLIGHT.record("throttle", rid=req.id, slot=slot,
                          tenant=req.tenant, cls=req.priority,
                          delay_ms=round(delay * 1e3, 1))
            self._preempt_slot(slot, cause="throttle", resume_delay=delay)

    def _build_drafts(self, k: int, tails: Optional[dict] = None):
        """Prompt-lookup drafts [B, k] (zero-padded past each slot's
        proposal) plus per-slot drafted counts [B], or (None, None) when
        no eligible slot found an n-gram match — the loop then takes the
        normal chunked path. Per-slot: only greedy penalty-free
        unconstrained slots draft (device acceptance is exact there);
        every other active slot still advances one decode-identical
        token inside the same fused dispatch, and an eligible slot with
        no match drafts nothing and costs nothing. ``tails`` carries
        tokens from a dispatch that has materialised but not yet fanned
        out (async spec pipelining), so drafts always extend the slot's
        true tip."""
        drafts = np.zeros((self.engine.n_slots, k), np.int32)
        drafted = np.zeros((self.engine.n_slots,), np.int32)
        n_drafting = 0
        for slot, req in enumerate(self._running):
            if req is None or slot in self._prefilling:
                continue
            if req.constraint is not None:
                continue
            o = req.opts
            if (o.temperature > 0.0 or o.repeat_penalty != 1.0
                    or o.presence_penalty != 0.0
                    or o.frequency_penalty != 0.0):
                continue
            extra = tails.get(slot) if tails else None
            d = self._lookup_draft(req, k, extra=extra)
            if d:
                drafts[slot, :len(d)] = d
                drafted[slot] = len(d)
                n_drafting += 1
        if n_drafting == 0:
            return None, None
        return drafts, drafted

    @staticmethod
    def _lookup_draft(req: Request, k: int, ngram: int = drafter.NGRAM,
                      extra: Optional[Sequence[int]] = None):
        """Latest earlier occurrence of the context's final n-gram → the
        k tokens that followed it (runtime/drafter.py; llama.cpp-style
        lookup decoding, no draft model needed). The n-gram →
        continuation-position index is maintained incrementally on the
        request, so a step costs O(new tokens + k), not O(context).
        ``extra`` appends tokens a materialised-but-unfanned dispatch
        already produced — the index positions it creates stay valid
        because _fanout appends exactly those tokens to all_tokens."""
        hist = list(req.prompt_ids) + req.all_tokens
        if extra:
            hist += [int(t) for t in extra]
        d, req._indexed_upto = drafter.propose(
            hist, req._bigram_idx, req._indexed_upto, k, ngram=ngram)
        return d

    def _watchdog_timeout_s(self) -> float:
        """Dispatch-wait budget in seconds; 0 disables the watchdog.

        Explicit TPU_DISPATCH_WATCHDOG_MS wins (0 = off). Otherwise the
        ceiling derives from the PR 7 dispatch histograms: once enough
        dispatches are observed, 100x the mean launch-to-host latency
        (clamped to [15s, 120s]) — generous enough that GC pauses and
        bucket recompiles never fire it, tight enough that a wedged
        device stops hiding behind a green /healthz. Before the
        histograms warm up (first dispatches compile) a fixed 120s
        floor applies."""
        ms = os.environ.get("TPU_DISPATCH_WATCHDOG_MS", "").strip()
        if ms:
            v = float(ms)
            return v / 1e3 if v > 0 else 0.0
        n, total = METRICS.hist_totals("tpu_model_dispatch_seconds")
        if n >= 64:
            return min(max(100.0 * (total / n), 15.0), 120.0)
        return 120.0

    @staticmethod
    def _wd_worker(req_q: queue.Queue, resp_q: queue.Queue):
        while True:
            fn = req_q.get()
            if fn is None:
                return
            try:
                resp_q.put((True, fn()))
            except BaseException as e:  # noqa: BLE001 — ferried to caller
                resp_q.put((False, e))

    def _watched(self, fn):
        """Run a blocking dispatch wait under the hung-dispatch
        watchdog: the wait executes on a persistent helper thread while
        the scheduler thread waits on the response queue with a
        timeout. On expiry the worker is abandoned (its eventual result
        goes to queues nothing reads — a fresh worker+queues serve the
        next wait) and WatchdogTimeout rides the normal supervisor
        path: restart, then replay. The engine.watchdog fault point
        runs ON the worker so an armed delay:Nms simulates a wedge."""
        timeout = self._watchdog_timeout_s()
        if timeout <= 0:
            FAULTS.check("engine.watchdog")
            return fn()

        def task():
            FAULTS.check("engine.watchdog")
            return fn()

        if self._wd_thread is None or not self._wd_thread.is_alive():
            self._wd_req = queue.Queue()
            self._wd_resp = queue.Queue()
            self._wd_thread = threading.Thread(
                target=self._wd_worker, args=(self._wd_req, self._wd_resp),
                daemon=True, name="tpu-dispatch-watchdog")
            self._wd_thread.start()
        self._wd_req.put(task)
        try:
            ok, val = self._wd_resp.get(timeout=timeout)
        except queue.Empty:
            self.n_watchdog_fires += 1
            METRICS.inc("tpu_model_watchdog_fires_total")
            FLIGHT.record("watchdog", timeout_s=round(timeout, 3),
                          fires=self.n_watchdog_fires)
            self._wd_thread = None      # abandon: never reuse its queues
            raise WatchdogTimeout(
                f"dispatch wait exceeded watchdog budget "
                f"{timeout:.1f}s (wedged device?)") from None
        if ok:
            return val
        raise val

    # -- device-grammar plumbing ------------------------------------------

    def _grammar_table(self, req: Request):
        """The engine-installed GrammarTable for ``req``'s constraint, or
        None when device grammar is unavailable for it (engine knob off,
        table build failed, or a DIFFERENT grammar currently owns the
        device tables while slots run on it). Resolved once per request
        and cached on it; GrammarTable.for_table itself caches the BFS
        per TokenTable, so repeat requests share one table build."""
        c = req.constraint
        if (c is None or not getattr(c, "grammar_table_ok", False)
                or not getattr(self.engine, "_grammar_device", False)):
            return None
        if req._gtable is not None:
            return req._gtable or None
        try:
            gt = GrammarTable.for_table(c.table,
                                        cap=self.engine._gstates_cap)
        except Exception:  # lint: allow(exception-hygiene): any table-build failure falls back to host masks
            gt = None
        if gt is None or not self.engine.install_grammar(
                ("grammar", id(gt)), gt.mask, gt.trans):
            req._gtable = False
            return None
        req._gtable = gt
        return gt

    def _refresh_mask(self, slot: int, req: Request):
        """Install ``req``'s current PDA mask on ``slot``; when the PDA
        state sits inside the installed device table the slot enters
        device-grammar mode — the mask then refreshes ON DEVICE per
        sampled token and the slot keeps the full decode chunk instead
        of one token per (synchronous) dispatch."""
        gid = -1
        gt = self._grammar_table(req)
        if gt is not None:
            gid = gt.state_id(req.constraint.state)
        self.engine.set_mask(slot, req.constraint.mask_row(), gid=gid)

    def _grammar_ack(self, slot: int, over: int):
        """Roll back a device-grammar slot's launch-time host-length
        over-advance (the frozen steps after an on-device escape) —
        same mirrored reconciliation path fused speculation uses."""
        if over <= 0:
            return
        rb = np.zeros((self.engine.n_slots,), np.int64)
        rb[slot] = over
        self.engine.spec_ack(rb)

    def _wait_handle(self, handle, snapshot=None,
                     drafted=None) -> np.ndarray:
        """Materialise a launched dispatch and reconcile host state: the
        paged fence ack, and — for speculative dispatches — the
        spec_ack rollback of the launch-time length over-advance
        (budgets − accepted), broadcast so followers reconcile at the
        identical call-stream position. The rollback is masked by
        ``snapshot`` occupancy IDENTITY: a slot whose occupant finished
        and was replaced between launch and wait must not have the old
        occupant's overshoot subtracted from the new request's fresh
        length (a parked/donated predecessor's length was already
        reset or is repaired at reuse). Folds per-slot drafted/accepted
        counts into the acceptance metrics."""
        tw0 = time.perf_counter()
        toks_n = self._watched(handle.wait)
        # breakdown: only the time the scheduler actually BLOCKED here is
        # dispatch-wait (under async overlap the device may already be
        # done); `dur` below is the full launch→host device span
        self.acct.on_wait(time.perf_counter() - tw0)
        self._fence_ack = handle.epoch
        self._consecutive_failures = 0
        # dispatch latency: launch → tokens-on-host, per program kind.
        # The handle stamped both ends, so the span event's launch-time
        # anchor makes async overlap visible (a launch far before its
        # materialize = host work hidden behind device compute).
        kind = "spec" if handle.budgets is not None else "decode"
        dur = ((handle.t_done - handle.t_launch)
               if handle.t_done is not None else 0.0)
        METRICS.observe("tpu_model_dispatch_seconds", dur,
                        f'{{kind="{kind}"}}')
        if self.acct.enabled:
            # goodput/FLOPs split of the dispatch grid: active slots'
            # host-mirrored lengths as contexts, the full slot batch as
            # the padded capacity
            hl, act = self.engine._host_lengths, self.engine.active
            ctxs = [int(hl[s]) for s in range(len(act)) if act[s]]
            n_rows = int(np.asarray(toks_n).shape[0])
            if kind == "spec":
                emitted = (float(np.asarray(handle.accepted).sum())
                           if handle.accepted is not None else 0.0)
                self.acct.on_spec(dur, ctxs, max(0, n_rows - 1), emitted,
                                  self.engine.n_slots)
            else:
                self.acct.on_decode(dur, ctxs, n_rows,
                                    self.engine.n_slots)
        if snapshot is not None:
            for s, r in snapshot.items():
                if self._running[s] is not r:
                    continue
                acc = (int(handle.accepted[s])
                       if handle.accepted is not None else None)
                if acc is not None:
                    r.trace.event_at(handle.t_launch, "dispatch",
                                     kind=kind, epoch=handle.epoch,
                                     dur_ms=round(dur * 1e3, 3),
                                     accepted=acc)
                else:
                    r.trace.event_at(handle.t_launch, "dispatch",
                                     kind=kind, epoch=handle.epoch,
                                     dur_ms=round(dur * 1e3, 3))
        if handle.budgets is not None:
            rollback = np.maximum(handle.budgets - handle.accepted, 0)
            if snapshot is not None:
                stable = np.zeros((self.engine.n_slots,), bool)
                for s, r in snapshot.items():
                    stable[s] = (self._running[s] is r
                                 and s not in self._prefilling)
                rollback = np.where(stable, rollback, 0)
            if rollback.any():
                self.engine.spec_ack(rollback)
            if drafted is not None:
                # a slot emits its accepted draft prefix + 1 bonus (or
                # ordinary) token, so accepted drafts = emitted − 1;
                # clamping by drafted keeps zero-pad columns that
                # happened to match the argmax out of the rate
                acc = np.minimum(
                    np.maximum(handle.accepted - 1, 0), drafted)
                d, a = int(drafted.sum()), int(acc.sum())
                if d:
                    self.spec_drafted += d
                    self.spec_accepted += a
                    METRICS.inc("tpu_model_spec_drafted_tokens_total",
                                float(d))
                    METRICS.inc("tpu_model_spec_accepted_tokens_total",
                                float(a))
        return toks_n

    def _pending_tails(self, toks_n, snapshot: dict) -> dict:
        """slot → token tail of a materialised-but-not-yet-fanned-out
        dispatch, for drafting the NEXT dispatch before _fanout runs.
        Only identity-stable slots count (same occupant, not back in
        prefill); sentinel columns (spec padding past the accepted
        prefix) are dropped."""
        vocab = self.engine.cfg.vocab_size
        tails: dict = {}
        for slot, req in snapshot.items():
            if self._running[slot] is not req or slot in self._prefilling:
                continue
            tails[slot] = [int(t) for t in np.asarray(toks_n)[:, slot]
                           if int(t) < vocab]
        return tails

    def _drain_pending(self):
        """Materialise and fan out the in-flight async dispatch, if any.
        Pops BEFORE waiting: if the fetch itself fails (poisoned device
        state) the supervisor must error the owners, never re-deliver."""
        if self._pending is None:
            return
        handle, snapshot, drafted = self._pending
        self._pending = None
        toks_n = self._wait_handle(handle, snapshot, drafted)
        self._fanout(toks_n, snapshot, chunked=drafted is None)

    def _decoding(self) -> dict:
        """slot → request for every slot the NEXT decode dispatch will
        advance (mid-chunked-prefill slots are engine-inactive and
        excluded)."""
        return {s: r for s, r in enumerate(self._running)
                if r is not None and s not in self._prefilling}

    def _step(self):
        if self._tasks:
            # exclusive tasks see a quiet pipeline: land any in-flight
            # dispatch first so a KV import's cache upload never races a
            # decode reading the same buffers
            self._drain_pending()
            self._run_tasks()
        self._shed_expired()
        self._throttle_over_limit()
        self._preempt_for_priority()
        self._advance_prefill()
        self._admit_waiting()
        if not self._decoding():
            self._drain_pending()
            # idle with pages still fenced (the last dispatch's frees):
            # unfence now so a quiet scheduler never parks pool capacity
            # in quarantine (and the conftest leak check sees zero)
            if self.engine.quarantined_pages:
                self._quiesce("idle")
            if not self._prefilling:
                t_idle = time.perf_counter()
                self._wake.wait(timeout=0.05)
                self.acct.on_idle(time.perf_counter() - t_idle)
                self._wake.clear()
            return
        # drop cancelled and over-deadline slots before paying for a
        # step; under double-buffering their in-flight rows are dropped
        # by _fanout's snapshot identity check
        now = time.monotonic()
        for slot, req in self._decoding().items():
            if req.cancelled.is_set():
                self._finish(slot, req, "cancelled")
            elif req.deadline is not None and now > req.deadline:
                # mid-generation wall-clock exceeded: clean terminal
                # frame, slot released and immediately reusable
                METRICS.inc("tpu_model_request_timeouts_total")
                self._finish(slot, req, "timeout")
        decoding = self._decoding()
        if not decoding:
            self._drain_pending()
            return
        # chunked decode: ecfg.decode_chunk steps per device round-trip.
        # A slot that stops mid-chunk has its remaining rows discarded
        # (_running[slot] goes None); the over-decoded cache entries are
        # zeroed by release(). HOST-masked grammar slots need a fresh
        # host-side PDA mask per token, so the engine freezes them after
        # the chunk's FIRST step (per-slot budgets) — they advance one
        # token per dispatch while the rest of the batch keeps the full
        # chunk (round-1 weak #5: one format:"json" request used to drop
        # everyone to n=1). Device-grammar slots (engine._gdev_mode)
        # keep the full chunk: their mask refreshes on device from the
        # installed table. Only when EVERY active slot is host-masked is
        # a 1-step dispatch cheaper.
        gdev = self.engine._gdev_mode
        n_steps = (1 if all(r.constraint is not None and not gdev[s]
                            for s, r in decoding.items())
                   else None)
        spec_usable = (self.spec_k > 0 and self.engine.sp_size == 1
                       and not (self.engine.paged
                                and self.engine._paged_dp > 1)
                       and n_steps is None)
        # drafts are built AFTER the in-flight dispatch lands (they must
        # extend each slot's true tip), so pressure relief sizes for the
        # worst case the coming dispatch could need: spec_k+1 mapped
        # positions for a spec dispatch, decode_chunk for a chunked one
        self._relieve_pressure(
            max(self.engine.ecfg.decode_chunk, self.spec_k + 1)
            if spec_usable else n_steps)
        decoding = self._decoding()
        if not decoding:
            self._drain_pending()
            return
        # only HOST-masked grammar slots force the pipeline empty (fresh
        # PDA mask per token); device-grammar slots advance their
        # automaton on device and ride async like everyone else
        gdev = self.engine._gdev_mode
        constrained = any(r.constraint is not None and not gdev[s]
                          for s, r in decoding.items())
        if not self.async_dispatch or constrained:
            # synchronous path: grammar needs a fresh host PDA mask
            # between dispatches, so the pipeline must be empty before
            # this one dispatches. Fused speculation still works here —
            # the spec program advances constrained slots exactly one
            # (masked) token while drafting slots verify k+1. (In paged
            # mode decode_n self-retires its epoch and the spec launch
            # threads retire=, so sync dispatches also drain any
            # quarantine the async stretch left behind.)
            if self.async_dispatch:
                METRICS.inc("tpu_model_async_fallback_total", 1.0,
                            '{cause="grammar"}')
                FLIGHT.record("async_fallback", cause="grammar")
            self._drain_pending()
            drafts = drafted = None
            if spec_usable:
                drafts, drafted = self._build_drafts(self.spec_k)
            if drafts is not None:
                handle = self.engine.decode_n_launch(
                    retire=(self._fence_ack if self.engine.paged
                            else None),
                    drafts=drafts)
                toks_n = self._wait_handle(handle, decoding,
                                           drafted)         # [k+1, B]
            else:
                t0 = time.perf_counter()
                toks_n = self._watched(
                    lambda: self.engine.decode_n(n_steps))
                self._consecutive_failures = 0
                dur = time.perf_counter() - t0
                METRICS.observe("tpu_model_dispatch_seconds", dur,
                                '{kind="decode"}')
                self.acct.on_wait(dur)
                if self.acct.enabled:
                    hl = self.engine._host_lengths
                    self.acct.on_decode(
                        dur, [int(hl[s]) for s in decoding],
                        int(np.asarray(toks_n).shape[0]),
                        self.engine.n_slots)
                for s, r in decoding.items():
                    if self._running[s] is r:
                        r.trace.event_at(t0, "dispatch", kind="decode",
                                         sync=True,
                                         dur_ms=round(dur * 1e3, 3))
            self._fanout(toks_n, decoding, chunked=drafts is None)
            return
        if spec_usable:
            # fused speculation double-buffers with the stages
            # REORDERED: drafts for dispatch N+1 must extend dispatch
            # N's tokens, so the loop waits N first (spec_ack
            # reconciling the launch-time length over-advance), drafts
            # from the just-landed tails, launches N+1, and only then
            # fans N out — detokenise/queue host work still overlaps
            # N+1's device compute, which is the half of
            # double-buffering that pays. No cause="spec" sync fallback
            # remains.
            prev, self._pending = self._pending, None
            toks_prev = tails = prev_snapshot = None
            if prev is not None:
                prev_handle, prev_snapshot, prev_drafted = prev
                toks_prev = self._wait_handle(prev_handle, prev_snapshot,
                                              prev_drafted)
                tails = self._pending_tails(toks_prev, prev_snapshot)
            drafts, drafted = self._build_drafts(self.spec_k, tails)
            try:
                if drafts is not None:
                    handle = self.engine.decode_n_launch(
                        retire=(self._fence_ack if self.engine.paged
                                else None),
                        drafts=drafts)
                else:   # no slot found a match this round: full chunk
                    handle = (self.engine.decode_n_launch(
                                  retire=self._fence_ack)
                              if self.engine.paged
                              else self.engine.decode_n_launch())
            except Exception:
                # dispatch N's tokens were already materialised —
                # deliver them before the supervisor errors whoever is
                # left
                if toks_prev is not None:
                    self._fanout(toks_prev, prev_snapshot,
                                 chunked=prev_drafted is None)
                raise
            self._pending = (handle, decoding, drafted)
            if toks_prev is not None:
                self._fanout(toks_prev, prev_snapshot,
                             chunked=prev_drafted is None)
            return
        # double-buffered async dispatch: launch dispatch N+1 FIRST,
        # then materialise and fan out dispatch N — detokenise/queue
        # work on the host overlaps device compute. Device programs stay
        # ordered through their donated-state data dependencies. The
        # retire= ack unfences pages freed behind dispatches we have
        # already materialised (paged mode; no-op dense).
        try:
            handle = (self.engine.decode_n_launch(retire=self._fence_ack)
                      if self.engine.paged
                      else self.engine.decode_n_launch())
        except Exception:
            # dispatch N's tokens were already computed — deliver them
            # before the supervisor errors whoever is left
            self._drain_pending()
            raise
        prev, self._pending = self._pending, (handle, decoding, None)
        if prev is not None:
            prev_handle, prev_snapshot, prev_drafted = prev
            toks_n = self._wait_handle(prev_handle, prev_snapshot,
                                       prev_drafted)
            self._fanout(toks_n, prev_snapshot,
                         chunked=prev_drafted is None)

    def _fanout(self, toks_n, snapshot: dict, chunked: bool = True):
        """Deliver one dispatch's token rows [n, B] to the requests in
        ``snapshot`` (slot → request AT LAUNCH time). Under
        double-buffering a slot may have finished, been preempted, or
        been re-admitted since the dispatch launched — rows for a slot
        whose occupant changed are dropped (the over-decoded cache
        positions are never attended; a preempted request resumes from
        exactly the tokens it was delivered).

        Per-slot chunk buffers: ONE queue item (and one monotonic stamp)
        per request per dispatch, not per token — at decode_chunk=32 this
        cuts queue/lock traffic on the consumer path 32×, which is the
        bulk of the HTTP-vs-engine throughput gap (BENCH_r05).

        Device-grammar slots consume MULTIPLE rows per dispatch: the host
        mirrors the device automaton through the installed GrammarTable
        (one trans lookup per token, validated against the exact PDA) and
        stops consuming at the row where the device escaped the table —
        later rows were sampled with the slot frozen and are garbage.
        The escape's launch-time host-length over-advance rolls back via
        _grammar_ack, the mask re-installs from the exact PDA state
        (re-entering device mode when that state is back in the table),
        and the ALREADY-LAUNCHED next dispatch — which ran with the slot
        still frozen — is marked in _gdiscard so its rows are dropped and
        its budget acked when IT fans out. ``chunked`` distinguishes full-
        chunk dispatches from fused-spec ones (budget 1 per constrained
        slot, reconciled by _wait_handle already — no grammar ack)."""
        pend: dict = {}
        # lint: allow(host-sync-hot-path): toks_n was fetched by DecodeHandle.wait — shape read of a host array
        n_rows = int(np.asarray(toks_n).shape[0])
        # slot → [GrammarTable|None, mirrored device state id] for
        # device-grammar slots this dispatch; st < 0 = stop consuming
        gwalk: dict = {}

        def _flush(slot: int, req: Request):
            buf = pend.pop(slot, None)
            if buf:
                # chunk-normalized inter-token latency: one observation
                # per delivered chunk, spread over its tokens — the
                # per-token ITL a client actually experiences under
                # chunked decode, at 1/decode_chunk the observe() cost
                now = time.monotonic()
                if req._t_last_emit:
                    METRICS.observe(
                        "tpu_model_itl_seconds",
                        max(now - req._t_last_emit, 0.0) / len(buf))
                req._t_last_emit = now
                # tenant accounting at delivery time — every class pays
                # into its bucket; only best_effort is throttled on debt
                self._limiter.debit(req.tenant, len(buf))
                METRICS.inc("tpu_model_tenant_decode_tokens_total",
                            float(len(buf)),
                            f'{{tenant="{req.tenant}"}}')
                req.out.put(("tokens", buf))

        def _walk_start(slot: int, req: Request):
            """None = host-masked (1-token budget); else [gt, st] with
            ``st`` the mirrored device automaton state (< 0: discard
            every row of this dispatch for the slot)."""
            marked = self._gdiscard.pop(slot, None)
            if marked is req:
                # this dispatch launched while the slot sat frozen after
                # an escape: every row is garbage, and (full-chunk
                # dispatch) its whole launch budget is overshoot. Spec
                # dispatches emitted all-sentinel rows for the frozen
                # slot and _wait_handle already rolled their budget back.
                if chunked:
                    self._grammar_ack(slot, n_rows)
                return [None, -1]
            if not self.engine._gdev_mode[slot]:
                return None
            gt = self._grammar_table(req)
            if gt is None:
                return None
            st = gt.state_id(req.constraint.state)
            if st < 0:   # host/device bookkeeping diverged: recover
                if chunked:
                    self._grammar_ack(slot, n_rows)
                self._refresh_mask(slot, req)
                return [gt, -1]
            return [gt, st]

        # lint: allow(host-sync-hot-path): toks_n was fetched by DecodeHandle.wait — the sanctioned sync point
        for row_idx, row in enumerate(np.asarray(toks_n)):
            any_running = False
            for slot, req in snapshot.items():
                if (self._running[slot] is not req
                        or slot in self._prefilling):
                    continue   # slot changed hands since launch
                any_running = True
                walk = None
                if req.constraint is not None:
                    if slot not in gwalk:
                        gwalk[slot] = _walk_start(slot, req)
                    walk = gwalk[slot]
                    if walk is None:
                        if row_idx >= 1:
                            continue  # host-masked: frozen after 1 token
                    elif walk[1] < 0:
                        continue  # device walk ended: rows are garbage
                tid = int(row[slot])  # lint: allow(host-sync-hot-path): row is a host array post-wait
                if tid >= self.engine.cfg.vocab_size:
                    continue   # sentinel padding past the slot's
                               # accepted prefix (fused spec verify)
                # grammar check BEFORE emitting: a dead-end state (empty
                # mask → uniform sampling over -inf logits) must not leak
                # an illegal token into the client's JSON stream
                if (req.constraint is not None
                        and tid not in req.eog_ids
                        and not req.constraint.advance(tid)):
                    if walk is not None and chunked:
                        self._grammar_ack(slot, n_rows - (row_idx + 1))
                    _flush(slot, req)
                    self._finish(slot, req, "stop")
                    continue
                if req.stats.n_generated == 0:
                    req.stats.t_first_token = time.monotonic()
                req.all_tokens.append(tid)  # EOG incl.: it's in the cache
                if tid in req.eog_ids:
                    if walk is not None and chunked:
                        # EOG transitions escape on device: the slot
                        # advanced this row then froze — reconcile the
                        # chunk's remaining budget before release
                        self._grammar_ack(slot, n_rows - (row_idx + 1))
                    _flush(slot, req)
                    self._finish(slot, req, "stop")
                    continue
                req.stats.n_generated += 1
                self.total_generated += 1
                pend.setdefault(slot, []).append(tid)
                if req.stats.n_generated >= req.max_tokens:
                    _flush(slot, req)
                    # budget exhausted = truncation, not natural stop
                    # (Ollama semantics: clients distinguish the two)
                    self._finish(slot, req, "length")
                # host-side length tracking (no device sync): the cache
                # holds the prompt plus one entry per decode step so far
                elif (req.stats.n_prompt + req.stats.n_generated
                      >= self.engine.max_seq - 1):
                    _flush(slot, req)
                    self._finish(slot, req, "length")
                elif req.constraint is not None:
                    if walk is None:
                        self._refresh_mask(slot, req)
                        continue
                    gt = walk[0]
                    nid = (int(gt.trans[walk[1], tid])  # lint: allow(host-sync-hot-path): gt.trans is host numpy (GrammarTable)
                           if tid < gt.trans.shape[1] else -1)
                    if nid >= 0:
                        walk[1] = nid   # stay on device: no host mask
                        continue
                    # device escaped AFTER emitting this token: the rest
                    # of the chunk is garbage — reconcile the launch-time
                    # over-advance, re-install the mask from the exact
                    # PDA state (re-entering device mode when it is back
                    # in the table), and mark the already-in-flight next
                    # dispatch, which ran with the slot still frozen
                    walk[1] = -1
                    if chunked:
                        self._grammar_ack(slot, n_rows - (row_idx + 1))
                    if (self._pending is not None
                            and self._pending[1].get(slot) is req):
                        self._gdiscard[slot] = req
                    self._refresh_mask(slot, req)
            if not any_running:
                break
        # end of dispatch: flush every still-running slot's chunk
        for slot in list(pend):
            req = self._running[slot]
            if req is not None:
                _flush(slot, req)
        pend.clear()
