"""LoadedModel: one resident model = engine + scheduler + tokenizer +
prompt template + default options.

This is the text-level API the HTTP layer (server/app.py) calls — the
equivalent of the model-serving half of `ollama serve` in the container the
reference launches per model Deployment (/root/reference/pkg/model/model.go:39,
pod.go:14). Handles prompt templating, stop-sequence holdback, and
per-request option merging; everything below it is token-level.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.config import ModelConfig
from ..server.metrics import GLOBAL as METRICS
from ..server.template import DEFAULT_TEMPLATE, Template
from ..tokenizer import StreamDecoder, Tokenizer
from .admission import (resolve_priority, resolve_tenant,
                        resolve_ttft_slo_s)
from .engine import Engine, EngineConfig, SlotOptions
from .errors import BadRequest
from .faults import FAULTS
from .scheduler import Scheduler


def resolve_deadline_s(defaults: Optional[Dict],
                       options: Optional[Dict]) -> Optional[float]:
    """Per-request wall-clock budget in seconds, or None for unlimited.

    Precedence: request ``deadline_ms`` option > modelfile default >
    ``TPU_REQUEST_DEADLINE_MS`` env. 0 (or absent everywhere) disables.
    """
    o = dict(defaults or {})
    o.update(options or {})
    raw = o.get("deadline_ms")
    if raw is None:
        raw = os.environ.get("TPU_REQUEST_DEADLINE_MS") or None
    if raw is None:
        return None
    try:
        ms = float(raw)
    except (TypeError, ValueError) as e:
        raise BadRequest(f"invalid deadline_ms: {raw!r}") from e
    if ms < 0:
        raise BadRequest("deadline_ms must be >= 0")
    return ms / 1000.0 if ms > 0 else None


@dataclasses.dataclass
class GenerateResult:
    text: str = ""
    prompt_tokens: int = 0
    generated_tokens: int = 0
    ttft_s: float = 0.0
    total_s: float = 0.0
    done_reason: str = "stop"
    context: List[int] = dataclasses.field(default_factory=list)
    # scheduler request id — the handle for GET /debug/trace?id=
    request_id: int = 0
    # per-stage span summary (runtime/trace.py), filled only when the
    # request asked for it (options.trace=true) — rides into the final
    # NDJSON frame as the "timings" block
    timings: Optional[Dict] = None


class _OwnedStream:
    """Iterator that owns its scheduler slot: with eager submission, the
    request exists before the caller ever iterates, so a drop before the
    first next() (e.g. client socket died while writing response headers)
    must still cancel the request — a generator's finally can't cover that
    window because an unstarted generator never entered its try block."""

    def __init__(self, it, req):
        self._it, self._req = it, req
        self._started = False
        # span timeline handle, so the HTTP layer can stamp its flush
        # events onto the same timeline the scheduler writes to
        self.trace = req.trace

    def __iter__(self):
        return self

    def __next__(self):
        self._started = True
        return next(self._it)

    def close(self):
        if not self._started:
            self._req.cancel()  # idempotent event-set
        self._it.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: allow(exception-hygiene): never raise from GC
            pass


_schema_warned = [False]   # once-per-process format-schema downgrade notice


class _Piece(str):
    """A detokenised text piece that remembers how many scheduler tokens
    produced it. The stream protocol stays (str, final) tuples — existing
    consumers see a plain str — but the HTTP frame coalescer and bench
    need token counts per piece, not character counts."""

    n_tokens = 1

    @staticmethod
    def of(text: str, n: int) -> "_Piece":
        p = _Piece(text)
        p.n_tokens = n
        return p


def merge_options(defaults: Dict, request: Optional[Dict]
                  ) -> Tuple[SlotOptions, int, List[str]]:
    """(modelfile params, request options) → (SlotOptions, num_predict, stop)."""
    o = dict(defaults or {})
    o.update(request or {})
    stop = o.get("stop") or []  # tolerate explicit null
    if isinstance(stop, str):
        stop = [stop]
    try:
        so = SlotOptions(
            temperature=float(o.get("temperature", 0.8)),
            top_k=int(o.get("top_k", 40)),
            top_p=float(o.get("top_p", 0.9)),
            min_p=float(o.get("min_p", 0.0)),
            typical_p=float(o.get("typical_p", 1.0)),
            repeat_penalty=float(o.get("repeat_penalty", 1.1)),
            presence_penalty=float(o.get("presence_penalty", 0.0)),
            frequency_penalty=float(o.get("frequency_penalty", 0.0)),
            # llama.cpp treats any value other than 1/2 as off
            mirostat=(int(o.get("mirostat", 0))
                      if int(o.get("mirostat", 0)) in (1, 2) else 0),
            mirostat_tau=float(o.get("mirostat_tau", 5.0)),
            mirostat_eta=float(o.get("mirostat_eta", 0.1)),
            seed=int(o.get("seed", -1)),
            repeat_last_n=int(o.get("repeat_last_n", 64)))
        num_predict = int(o.get("num_predict", 128))
    except (TypeError, ValueError) as e:
        raise BadRequest(f"invalid options: {e}") from e
    if num_predict < 0:
        num_predict = 1 << 30  # -1 = unlimited (bounded by context)
    return so, num_predict, list(stop)


class StopMatcher:
    """Streaming stop-sequence matcher with holdback of partial matches."""

    def __init__(self, stops: Sequence[str]):
        self.stops = [s for s in stops if s]
        self.buf = ""
        self.hit = False

    def feed(self, piece: str) -> str:
        if self.hit:
            return ""
        if not self.stops:
            return piece
        self.buf += piece
        # full match?
        cut = None
        for s in self.stops:
            idx = self.buf.find(s)
            if idx >= 0 and (cut is None or idx < cut):
                cut = idx
        if cut is not None:
            out, self.buf = self.buf[:cut], ""
            self.hit = True
            return out
        # hold back the longest tail that could begin a stop string
        hold = 0
        for s in self.stops:
            for k in range(min(len(s) - 1, len(self.buf)), 0, -1):
                if self.buf.endswith(s[:k]):
                    hold = max(hold, k)
                    break
        if hold:
            out, self.buf = self.buf[:-hold], self.buf[-hold:]
            return out
        out, self.buf = self.buf, ""
        return out

    def flush(self) -> str:
        out, self.buf = self.buf, ""
        return "" if self.hit else out


class LoadedModel:
    def __init__(self, name: str, cfg: ModelConfig, params, tokenizer: Tokenizer,
                 template: Optional[str] = None,
                 system: Optional[str] = None,
                 default_params: Optional[Dict] = None,
                 mesh=None, ecfg: Optional[EngineConfig] = None,
                 digest: str = "", vision: Optional[Tuple] = None,
                 control_plane=None, follower: bool = False,
                 warm_cache_dir: Optional[str] = None):
        self.name = name
        self.cfg = cfg
        # (VisionConfig, vision params) for multimodal models (llava) —
        # the mmproj layer the reference delegates to llama.cpp's clip
        self.vision = vision
        self._vision_fns = {}
        self.digest = digest
        self.tokenizer = tokenizer
        self.template = Template(template or DEFAULT_TEMPLATE)
        self.system = system
        self.default_params = default_params or {}
        self.loaded_at = time.time()
        self.ecfg = ecfg or EngineConfig()
        self.control_plane = control_plane
        self.follower = follower
        self._unloaded = False   # set under dispatch_lock on multi-host
        self.engine = Engine(cfg, params, mesh=mesh, ecfg=self.ecfg)
        if control_plane is not None:
            # multi-host leader: every device-dispatching engine call is
            # broadcast to the follower processes BEFORE running locally,
            # so the whole slice executes identical SPMD programs
            # (runtime/follower.py)
            from .follower import MirroredEngine
            self.engine = MirroredEngine(self.engine, control_plane)
        # AOT-compile every attention-bucket decode program up front —
        # serving must never pay an XLA compile at a bucket crossing (the
        # persistent compilation cache makes this near-free on restarts).
        # Followers warm via the leader's replayed warm_buckets call.
        # When a warm snapshot exists on the weight-cache volume (saved
        # by a drain before scale-to-zero), restore it instead: the
        # woken replica re-enters serving with the full warm plan and
        # tpu_model_recompiles_total untouched.
        import os as _os
        self._warm_cache_dir = warm_cache_dir if not follower else None
        if not follower and _os.environ.get("TPU_WARM_BUCKETS", "1") != "0":
            if not self._restore_warm_snapshot():
                self.engine.warm_buckets()
        # tier-2 prefix snapshot: seed the host arena with the fleet's
        # shared hot prefixes so this replica's first shared-prefix
        # request is a warm tier-2 hit instead of a cold prefill
        # (import_prefixes is MIRRORED — followers replay the same
        # import and the trees stay bit-identical)
        if not follower:
            self._restore_prefix_snapshot()
        # followers replay engine calls from the control stream — they
        # never schedule on their own
        self.scheduler = None if follower else Scheduler(self.engine)
        self._embed_fn = None
        self._embed_lock = threading.Lock()
        # canonical schema JSON → compiled machine, LRU-evicted one at a
        # time (each entry amortises full-vocab mask sweeps)
        self._schemas: OrderedDict[str, object] = OrderedDict()
        # weakrefs: a registered gauge must not keep the engine (and its
        # multi-GB params) alive after unload()
        wself = weakref.ref(self)
        METRICS.gauge_fn("tpu_model_active_slots",
                         lambda: (lm := wself()) is not None
                         and lm.scheduler is not None
                         and lm.scheduler.n_active or 0)
        METRICS.gauge_fn("tpu_model_queue_depth",
                         lambda: (lm := wself()) is not None
                         and lm.scheduler is not None
                         and lm.scheduler.qsize or 0)
        if self.engine.paged:
            # paged-pool pressure signal for autoscaling/alerting (the
            # preemption COUNTER lives in the scheduler — counters survive
            # unload, keeping Prometheus rate() semantics intact)
            METRICS.gauge_fn("tpu_model_kv_free_pages",
                             lambda: (lm := wself()) is not None
                             and lm.engine.free_pages or 0)
        if getattr(self.engine, "radix_enabled", False):
            # radix prefix-cache residency: nodes == chunks, pages ==
            # pool pages the tree pins (hit/miss counters live in the
            # scheduler path and survive unload)
            METRICS.gauge_fn("tpu_model_radix_nodes",
                             lambda: (lm := wself()) is not None
                             and lm.engine.radix_nodes or 0)
            METRICS.gauge_fn("tpu_model_radix_pages",
                             lambda: (lm := wself()) is not None
                             and lm.engine.radix_pages or 0)
        if getattr(self.engine, "host_cache_enabled", False):
            # tier-1 host-arena occupancy: bytes and whole KV pages the
            # spilled radix subtrees hold in pinned host RAM (the spill /
            # tier-hit counters live in the scheduler path and survive
            # unload, keeping Prometheus rate() semantics intact)
            METRICS.gauge_fn("tpu_model_host_cache_bytes",
                             lambda: (lm := wself()) is not None
                             and lm.engine.host_cache_used_bytes or 0)
            METRICS.gauge_fn("tpu_model_host_cache_pages",
                             lambda: (lm := wself()) is not None
                             and lm.engine.host_cache_pages or 0)
        # per-program dispatch latency (launch → tokens on host), one
        # labelled gauge per program kind: decode-chunk, one-shot admit,
        # extend (prefix reuse / chunked-prefill pieces), spec verify —
        # the number behind dispatch-dominated regressions like the
        # BENCH_r05 623ms/spec-dispatch anomaly
        for _kind in ("decode", "admit", "extend", "spec"):
            METRICS.gauge_fn(
                "tpu_model_dispatch_ms",
                lambda k=_kind: (lm := wself()) is not None
                and lm.engine.dispatch_ms.get(k, 0.0) or 0.0,
                labels=f'{{program="{_kind}"}}')

        # utilization gauges (runtime/accounting.py): 60s-window MFU,
        # occupancy, goodput and waste read from the scheduler's
        # accounting snapshot; None (no peak known / idle) renders 0
        def _util(field):
            lm = wself()
            if lm is None or lm.scheduler is None:
                return 0.0
            acct = getattr(lm.scheduler, "acct", None)
            if acct is None or not acct.enabled:
                return 0.0
            return float(acct.snapshot().get(field) or 0.0)
        METRICS.gauge_fn("tpu_model_mfu", lambda: _util("mfu"))
        METRICS.gauge_fn("tpu_model_occupancy", lambda: _util("occupancy"))
        METRICS.gauge_fn("tpu_model_goodput_tokens_per_second",
                         lambda: _util("goodput_tok_s"))
        METRICS.gauge_fn("tpu_model_padding_waste_pct",
                         lambda: _util("waste_pct"))

    # ------------------------------------------------------------------
    # warm-snapshot (scale-to-zero fast cold-start): the AOT warm-bucket
    # executable cache persists on the weight-cache volume across pod
    # generations — saved at drain time, restored at load
    # ------------------------------------------------------------------
    def warm_snapshot_key(self) -> str:
        """Serving-identity hash the snapshot is keyed by: a snapshot is
        only valid for the exact digest + engine geometry + jax backend
        that produced it (the warm plan itself also varies with
        TPU_SPEC_DECODE, so that rides along)."""
        import hashlib
        import os as _os
        import jax
        payload = "|".join([
            self.digest or self.name, repr(self.ecfg), jax.__version__,
            jax.default_backend(),
            _os.environ.get("TPU_SPEC_DECODE", "0") or "0"])
        return hashlib.sha256(payload.encode()).hexdigest()[:24]

    def _restore_warm_snapshot(self) -> bool:
        """Try to warm from a persisted snapshot; False falls back to a
        normal warm_buckets pass (never an error — the snapshot is an
        optimisation, not a dependency)."""
        import os as _os
        if (self._warm_cache_dir is None
                or _os.environ.get("TPU_WARM_SNAPSHOT", "1") == "0"
                or not hasattr(self.engine, "restore_warm")):
            return False
        from ..gguf.store import load_warm_snapshot
        try:
            blob = load_warm_snapshot(self._warm_cache_dir,
                                      self.warm_snapshot_key())
            if blob is None:
                return False
            self.engine.restore_warm(blob)
        except Exception:  # noqa: BLE001 — corrupt/incompatible snapshot
            return False
        METRICS.inc("tpu_model_warm_snapshot_restores_total", 1.0)
        return True

    def save_warm_snapshot(self) -> bool:
        """Persist the warm state (drain path: the operator snapshots
        before a scale-to-zero so the wake is warm). Best-effort."""
        import os as _os
        if (self.follower or self._warm_cache_dir is None
                or _os.environ.get("TPU_WARM_SNAPSHOT", "1") == "0"
                or not hasattr(self.engine, "warm_snapshot")):
            return False
        from ..gguf.store import save_warm_snapshot
        try:
            blob = self.engine.warm_snapshot()
            save_warm_snapshot(self._warm_cache_dir,
                               self.warm_snapshot_key(), blob)
        except Exception:  # noqa: BLE001 — never let a snapshot fail a drain
            return False
        METRICS.inc("tpu_model_warm_snapshot_saves_total", 1.0)
        return True

    # ------------------------------------------------------------------
    # tier-2 prefix snapshots (fleet-shared hot KV prefixes): the hottest
    # radix subtrees persist on the shared weight-cache volume across pod
    # generations — saved at drain time, imported into the host arena at
    # load so a just-woken replica answers shared-prefix traffic warm
    # ------------------------------------------------------------------
    def prefix_snapshot_key(self) -> str:
        """Serving-identity hash the prefix snapshot is keyed by: KV
        pages are only valid for the exact digest + engine geometry
        (page size, kv dtype, head layout all live in ecfg) + jax
        backend that produced them."""
        import hashlib
        import jax
        payload = "|".join([
            self.digest or self.name, repr(self.ecfg), jax.__version__,
            jax.default_backend(), "prefix-v1"])
        return hashlib.sha256(payload.encode()).hexdigest()[:24]

    def _restore_prefix_snapshot(self) -> bool:
        """Try to seed the host arena from a persisted prefix snapshot;
        False means cold (never an error — the snapshot is an
        optimisation, not a dependency)."""
        import os as _os
        if (self._warm_cache_dir is None
                or _os.environ.get("TPU_HOST_CACHE_SNAPSHOT", "1") == "0"
                or not getattr(self.engine, "host_cache_enabled", False)):
            return False
        from ..gguf.store import load_prefix_snapshot
        try:
            blob = load_prefix_snapshot(self._warm_cache_dir,
                                        self.prefix_snapshot_key())
            if blob is None:
                return False
            n = self.engine.import_prefixes(blob)
        except Exception:  # noqa: BLE001 — corrupt/incompatible snapshot
            return False
        return n > 0

    def save_prefix_snapshot(self) -> bool:
        """Persist the hottest prefixes (drain path, beside the warm
        snapshot). Best-effort — never lets a snapshot fail a drain."""
        import os as _os
        if (self.follower or self._warm_cache_dir is None
                or _os.environ.get("TPU_HOST_CACHE_SNAPSHOT", "1") == "0"
                or not getattr(self.engine, "radix_enabled", False)):
            return False
        from ..gguf.store import save_prefix_snapshot
        try:
            budget = int(_os.environ.get("TPU_HOST_CACHE_SNAPSHOT_MB",
                                         "64") or "64") << 20
            blob = self.engine.export_prefixes(budget)
            if blob is None:
                return False
            save_prefix_snapshot(self._warm_cache_dir,
                                 self.prefix_snapshot_key(), blob)
        except Exception:  # noqa: BLE001 — never let a snapshot fail a drain
            return False
        return True

    # ------------------------------------------------------------------
    # disaggregated prefill→decode handoff (ISSUE 20): the prefill
    # replica exports the request's quiescent KV pages; the decode
    # replica imports them as a radix warm start. Both run on the
    # scheduler thread (run_exclusive) so the page gathers / grafts
    # never race a dispatch. Multi-host slices are gated out the same
    # way multimodal is: the paged radix pool is leader-local.
    # ------------------------------------------------------------------
    def kv_export(self, ids: List[int],
                  max_bytes: int = 64 << 20) -> Optional[bytes]:
        """Serialize the KV pages covering ``ids``'s radix prefix.
        None means nothing exportable (dense engine, no prefix parked,
        multi-host) — the gateway downgrades to journal replay, so this
        is a soft answer, never an error."""
        if self.control_plane is not None or self.follower:
            return None
        if not getattr(self.engine, "radix_enabled", False):
            return None
        return self.scheduler.run_exclusive(
            lambda: self.engine.export_request_kv(ids, max_bytes))

    def kv_import(self, blob: bytes) -> int:
        """Graft a transferred KV blob into this replica's radix tree;
        returns pages imported (0 = nothing usable: the decode side
        simply re-prefills — a transfer is a warm start, never a
        correctness dependency)."""
        if self.control_plane is not None or self.follower:
            return 0
        if not getattr(self.engine, "radix_enabled", False):
            return 0
        return self.scheduler.run_exclusive(
            lambda: self.engine.import_request_kv(blob))

    # ------------------------------------------------------------------
    # multimodal (llava): image bytes → projected embeddings → spliced
    # prompt embedding sequence handed to the engine's embeds admission
    # ------------------------------------------------------------------
    def encode_images(self, images_u8) -> "np.ndarray":
        """List of uint8 [H, W, 3] arrays → [n_img, n_patches, D]."""
        if self.control_plane is not None:
            raise RuntimeError(
                "multimodal requests are not supported on multi-host "
                "slices yet (the vision tower jit is leader-only)")
        from ..models import vision as V
        import jax
        vcfg, vparams = self.vision
        batch = np.stack([V.preprocess(im, vcfg) for im in images_u8])
        fn = self._vision_fns.get("encode")
        if fn is None:
            fn = jax.jit(lambda p, x: V.encode(vcfg, p, x))
            self._vision_fns["encode"] = fn
        return np.asarray(fn(vparams, jnp.asarray(batch)))

    def splice_images(self, ids, images_u8):
        """Text ids + decoded images → (padded_ids, embeds [n, D]).

        Image tokens are inserted after the BOS token (llava convention:
        image context precedes the instruction); padded_ids carry a pad id
        at image positions (only the repeat-penalty counts see them).
        """
        import jax
        img = self.encode_images(images_u8)          # [n_img, N, D]
        n_img, N, D = img.shape
        fn = self._vision_fns.get("embed_ids")
        if fn is None:
            from ..models.decoder import _embed
            fn = jax.jit(lambda p, t: _embed(self.cfg, p, t))
            self._vision_fns["embed_ids"] = fn
        text = np.asarray(fn(self.engine.params,
                             jnp.asarray(np.asarray(ids, np.int32)[None]))
                          )[0].astype(np.float32)    # [n_text, D]
        cut = 1 if (ids and self.tokenizer.add_bos
                    and ids[0] == self.tokenizer.bos_id) else 0
        embeds = np.concatenate(
            [text[:cut]] + [img.reshape(n_img * N, D)] + [text[cut:]], axis=0)
        # pad id == vocab_size: definitively not a real token, and the
        # engine's penalty-count scatter drops it as out-of-bounds
        pad = [self.cfg.vocab_size] * (n_img * N)
        padded_ids = list(ids[:cut]) + pad + list(ids[cut:])
        return padded_ids, embeds

    # ------------------------------------------------------------------
    def _make_constraint(self, format):
        """format:"json" → generic grammar; a schema dict → the compiled
        skeleton machine (ops/schema.py) when the schema is in the
        supported subset, else generic JSON with a once-per-process
        downgrade warning (never a silently wrong constraint)."""
        from ..ops.constrain import JsonConstraint
        if isinstance(format, dict):
            import json as _json
            from ..ops.schema import SchemaConstraint, compile_schema
            key = _json.dumps(format, sort_keys=True)
            if key in self._schemas:
                sch = self._schemas[key]
                self._schemas.move_to_end(key)
            else:
                sch = compile_schema(format)
                if len(self._schemas) > 64:
                    # evict ONE stale entry — wholesale clears would
                    # re-pay every compiled machine's per-state mask
                    # cache on schema-rotating workloads (ADVICE r2)
                    self._schemas.popitem(last=False)
                self._schemas[key] = sch   # None cached too (unsupported)
            if sch is not None:
                c = SchemaConstraint.for_tokenizer(sch, self.tokenizer)
                c.mask_row()   # prime the initial mask on the HTTP
                # thread (later novel hole states still fill in the
                # scheduler loop — amortised by the abstract-state cache)
                return c
            if not _schema_warned[0]:
                _schema_warned[0] = True
                print("warning: JSON schema outside the supported subset; "
                      "constraining to generic JSON only",
                      file=sys.stderr, flush=True)
        return JsonConstraint.for_tokenizer(self.tokenizer)

    def render_prompt(self, prompt: str, system: Optional[str] = None,
                      template: Optional[str] = None,
                      suffix: Optional[str] = None) -> str:
        """``suffix`` enables fill-in-middle (code models): it renders
        through the template's ``.Suffix``; a model whose template has no
        suffix section cannot insert — that's a client error (upstream
        ollama answers the same way)."""
        tpl = Template(template) if template else self.template
        if suffix:
            if ".Suffix" not in tpl.src:
                raise BadRequest(
                    f"model {self.name} does not support insert (its "
                    f"template has no .Suffix section)")
            return tpl.render(prompt=prompt, suffix=suffix,
                              system=system if system is not None else
                              (self.system or ""))
        return tpl.render(prompt=prompt,
                          system=system if system is not None else
                          (self.system or ""))

    def render_chat(self, messages: List[Dict],
                    template: Optional[str] = None,
                    tools: Optional[List[Dict]] = None) -> str:
        """Render a messages list. Templates that iterate .Messages get them
        directly; legacy system/prompt templates get a flattened view.

        ``tools`` (OpenAI wire shape) render through the template's
        ``.Tools`` (Go-shaped, server/tools.py); a model whose template has
        no tools section cannot honour them — that's a client error."""
        from ..server.tools import to_template_tool_calls, to_template_tools
        tpl = Template(template) if template else self.template
        if tools and ".Tools" not in tpl.src:
            raise BadRequest(
                f"model {self.name} does not support tools (its template "
                f"has no .Tools section)")
        system = self.system or ""
        sys_parts = [m["content"] for m in messages
                     if m.get("role") == "system"]
        if sys_parts:
            system = "\n".join(([system] if system else []) + sys_parts)
        msgs = []
        for m in messages:
            if m.get("role") == "system":
                continue
            entry = {"Role": m.get("role", "user"),
                     "Content": m.get("content", "") or ""}
            if m.get("tool_calls"):
                entry["ToolCalls"] = to_template_tool_calls(m["tool_calls"])
            msgs.append(entry)
        tpl_tools = to_template_tools(tools) if tools else []
        if ".Messages" in tpl.src:
            if system:
                msgs = [{"Role": "system", "Content": system}] + msgs
            return tpl.render(messages=msgs, system=system, prompt="",
                              tools=tpl_tools)
        prompt = msgs[-1]["Content"] if msgs else ""
        return tpl.render(system=system, prompt=prompt, tools=tpl_tools)

    # ------------------------------------------------------------------
    def generate_stream(self, prompt_text: str,
                        options: Optional[Dict] = None,
                        context: Optional[List[int]] = None,
                        raw: bool = False,
                        cancel_event: Optional[threading.Event] = None,
                        images: Optional[List] = None,
                        format: Optional[object] = None
                        ) -> Iterator[Tuple[str, Optional[GenerateResult]]]:
        """Yields (text_piece, None)… then ("", final GenerateResult).

        ``format``: Ollama structured-output field — ``"json"`` (or any
        JSON-schema dict, honoured as generic JSON mode) turns on
        grammar-constrained decoding (ops/constrain.py): the output is
        guaranteed to be a syntactically complete JSON value.

        Option parsing, tokenization, and scheduler admission run eagerly
        at call time — NOT on first next() — so SchedulerBusy/Broken and
        bad-request errors surface before the HTTP layer commits a 200 +
        chunked headers (a mid-stream error chunk can't carry the 503 that
        load balancers key backpressure on)."""
        so, num_predict, stops = merge_options(self.default_params, options)
        t0 = time.monotonic()
        ids = list(context or [])
        # BOS only at the start of a fresh sequence (continuations carry it)
        ids += self.tokenizer.encode(
            prompt_text, add_bos=(not ids) and self.tokenizer.add_bos)
        embeds = None
        context_ids = ids
        if images:
            if self.vision is None:
                raise BadRequest(
                    f"model {self.name} has no vision projector; it cannot "
                    f"accept images")
            ids, embeds = self.splice_images(ids, images)
        # disagg prefill-only mode (gateway-injected option, ISSUE 20):
        # the prefill replica runs prefill + ONE decoded token — enough
        # to commit the first frame — then finishes; the scheduler's
        # finish path parks the prompt's KV in the radix tree, which is
        # exactly what /api/kv_export ships to the decode pool.
        # merge_options ignores unknown keys, so the flag never reaches
        # SlotOptions (same contract as options.trace).
        prefill_only = bool((options or {}).get("disagg_prefill"))
        max_new = min(num_predict, self.engine.max_seq - len(ids) - 1)
        if prefill_only:
            max_new = min(max_new, 1)
        if max_new < 1:
            raise BadRequest(
                f"prompt of {len(ids)} tokens leaves no room to generate "
                f"within the {self.engine.max_seq}-token context")
        constraint = None
        if format is not None and format != "":
            if format == "json" or isinstance(format, dict):
                constraint = self._make_constraint(format)
            else:
                raise BadRequest(
                    f"unsupported format {format!r}; expected \"json\" or "
                    f"a JSON schema object")
        req = self.scheduler.submit(ids, so, max_new,
                                    eog_ids=frozenset(self.tokenizer.eog_ids),
                                    embeds=embeds, constraint=constraint,
                                    deadline_s=resolve_deadline_s(
                                        self.default_params, options),
                                    priority=resolve_priority(
                                        self.default_params, options),
                                    tenant=resolve_tenant(options),
                                    ttft_slo_s=resolve_ttft_slo_s(
                                        self.default_params, options))
        # opt-in span summary in the final frame: options.trace=true
        # (merge_options ignores unknown keys, so "trace" never reaches
        # SlotOptions)
        want_timings = bool((options or {}).get("trace"))
        # returned context carries only REAL token ids: a continuation
        # re-prefills from context without the image, so image pad ids
        # must not leak into it (they would re-enter as garbage tokens)
        return _OwnedStream(
            self._stream(req, stops, context_ids, max_new, t0, cancel_event,
                         want_timings, prefill_only),
            req)

    def _stream(self, req, stops, ids, max_new, t0, cancel_event,
                want_timings: bool = False, prefill_only: bool = False
                ) -> Iterator[Tuple[str, Optional[GenerateResult]]]:
        sd = StreamDecoder(self.tokenizer)
        sm = StopMatcher(stops)
        # prompt_eval_count includes image tokens (llava counts them);
        # ``ids`` here is the context view, which excludes the image pads
        result = GenerateResult(prompt_tokens=req.stats.n_prompt)
        all_ids: List[int] = []
        finished = False
        try:
            # chunk-granular consumption: one queue item, one batched
            # detokenise, and one StopMatcher pass per decode dispatch
            for chunk in req.chunks():
                if cancel_event is not None and cancel_event.is_set():
                    req.cancel()
                all_ids.extend(chunk)
                FAULTS.check("detok.feed")
                req.trace.event("detok", n=len(chunk))
                piece = sm.feed(sd.feed_many(chunk))
                if piece:
                    result.text += piece
                    yield _Piece.of(piece, len(chunk)), None
                if sm.hit:
                    req.cancel()
                    break
            finished = True
        finally:
            # generator closed early (client disconnect → GeneratorExit):
            # free the decode slot instead of burning it to max_tokens
            if not finished:
                req.cancel()
        tail = sm.feed(sd.flush()) + sm.flush()
        if tail:
            result.text += tail
            yield _Piece.of(tail, 0), None   # tokens already counted above
        st = req.stats
        result.generated_tokens = st.n_generated
        result.ttft_s = st.ttft_s
        result.total_s = time.monotonic() - t0
        if getattr(req, "done_reason", None) in ("timeout", "drain"):
            # deadline_ms expired mid-generation ("timeout"), or the
            # graceful-drain window closed around a running stream
            # ("drain"): the scheduler released the slot and sent a
            # clean terminal frame — surface the real reason instead of
            # misreporting "stop" (a client seeing "drain" knows its
            # partial output was cut by a rollout and can resume via
            # context)
            result.done_reason = req.done_reason
        else:
            result.done_reason = ("stop"
                                  if sm.hit or st.n_generated < max_new
                                  else "length")
            if prefill_only and result.done_reason == "length":
                # cut at the injected 1-token cap, not a real completion:
                # the gateway keys its handoff on this reason. A genuine
                # "stop" (first token was EOG / a stop sequence) stays
                # "stop" — the stream is actually done, no handoff needed.
                result.done_reason = "handoff"
        result.context = ids + all_ids
        METRICS.inc("tpu_model_requests_total")
        METRICS.inc("tpu_model_generated_tokens_total", st.n_generated)
        METRICS.inc("tpu_model_prompt_tokens_total", len(ids))
        if st.n_reused:
            # prompt tokens whose K/V came from a parked prefix (no prefill)
            METRICS.inc("tpu_model_prefix_reused_tokens_total", st.n_reused)
        METRICS.observe("tpu_model_ttft_seconds", st.ttft_s)
        if st.decode_tok_s > 0:
            METRICS.observe("tpu_model_decode_tokens_per_second",
                            st.decode_tok_s)
        result.request_id = req.id
        if want_timings:
            result.timings = req.trace.timings()
        yield "", result

    def generate(self, prompt_text: str, options: Optional[Dict] = None,
                 raw: bool = False) -> GenerateResult:
        final = None
        for _piece, res in self.generate_stream(prompt_text, options,
                                                raw=raw):
            if res is not None:
                final = res
        return final

    # ------------------------------------------------------------------
    def embed(self, texts: List[str]) -> np.ndarray:
        """Mean-pooled final hidden states (ollama /api/embeddings)."""
        from ..models import decoder as D

        with self._embed_lock:
            if self._embed_fn is None:
                cfg = self.cfg

                def _embed(params, tokens, n_valid):
                    x = D._embed(cfg, params, tokens)
                    import jax.numpy as jnp
                    from jax import lax
                    from ..ops.attention import causal_mask
                    import math
                    B, T = tokens.shape
                    # the model's real score scale (granite's exact
                    # multiplier, gemma's query_pre_attn_scalar) — a
                    # hand-rolled 1/sqrt(head_dim) silently mis-scales
                    # those families' embeddings
                    scale = D._attn_scale(cfg)
                    from ..ops.rope import rope_angles_cfg
                    positions = jnp.broadcast_to(
                        jnp.arange(T, dtype=jnp.int32), (B, T))
                    cos, sin = rope_angles_cfg(positions, cfg)
                    mask = causal_mask(T, T, 0,
                                       sliding_window=cfg.sliding_window)
                    mask = jnp.broadcast_to(mask, (B, 1, T, T))

                    mesh = self.engine.mesh

                    def body(x, lp):
                        # mesh keeps pallas inside the shard_map dispatch
                        # on >1-device meshes (GSPMD can't see pallas_call)
                        x, kv = D._block_chunk(cfg, lp, x, cos, sin, mask,
                                               scale, mesh=mesh)
                        return x, None

                    x, _ = lax.scan(body, x, params["layers"])
                    x = D._norm(cfg, x, params["out_norm_w"],
                                params.get("out_norm_b"))
                    valid = (jnp.arange(T)[None, :] < n_valid[:, None]
                             ).astype(x.dtype)
                    pooled = (x * valid[:, :, None]).sum(1) / jnp.maximum(
                        valid.sum(1, keepdims=True), 1)
                    return pooled.astype(jnp.float32)

                # replicated output: multi-controller processes can
                # only read fully-addressable (or replicated) arrays
                self._embed_fn = jax.jit(
                    _embed, out_shardings=self.engine._repl_sh)
        # one device dispatch per LENGTH BUCKET, not per text (round-1
        # weak #9: serial per-text dispatches — fine for probes, weak for
        # real embedding traffic): texts bucket by padded length, each
        # bucket embeds as one [n, T] batch, results return in input order
        all_ids = [self.tokenizer.encode(t) for t in texts]
        buckets: Dict[int, List[int]] = {}
        for i, ids in enumerate(all_ids):
            T = max(16, 1 << (max(len(ids), 1) - 1).bit_length())
            buckets.setdefault(T, []).append(i)
        outs: List[Optional[np.ndarray]] = [None] * len(texts)

        def dispatch():
            for T, idxs in sorted(buckets.items()):
                # batch dim padded to a power of two as well, so compiled
                # program count stays O(log² (texts, len)), not O(requests)
                n_pad = 1 << (len(idxs) - 1).bit_length()
                toks = np.zeros((n_pad, T), np.int32)
                lens = np.zeros((n_pad,), np.int32)
                for row, i in enumerate(idxs):
                    ids = all_ids[i]
                    toks[row, :len(ids)] = ids
                    lens[row] = len(ids)
                out = self.engine._fetch(self._embed_fn(
                    self.engine.params, self.engine._gr(toks),
                    self.engine._gr(lens)))
                for row, i in enumerate(idxs):
                    outs[i] = out[row]

        cp = self.control_plane
        if cp is None:
            dispatch()
        else:
            # followers replay embed() with the same texts — bucketing and
            # the jit body are deterministic, so the SPMD programs line
            # up. The dispatch lock keeps the broadcast AND the local
            # device dispatches atomic against the decode loop's mirrored
            # calls (and against unload), preserving the follower's FIFO
            # replay order on the leader's device queue.
            with cp.dispatch_lock:
                if self._unloaded:
                    raise RuntimeError("model unloaded")
                # lint: allow(lock-order): broadcast under dispatch_lock keeps FIFO replay order
                cp.broadcast(("lm_call", "embed", (list(texts),)))
                dispatch()
        return np.stack(outs)

    def unload(self):
        if self.scheduler is not None:
            self.scheduler.shutdown()   # may still mirror engine calls
            if self.control_plane is not None:
                # the ("unload",) broadcast must be FIFO-AFTER the loop's
                # last mirrored call: shutdown()'s bounded join can time
                # out mid-compile, and a call broadcast after unload
                # would hit followers with no engine while the leader
                # enters the collective alone
                t = getattr(self.scheduler, "_thread", None)
                if t is not None and t.is_alive():
                    t.join()
        if self.control_plane is not None:
            # under the dispatch lock: an embed holding it finishes its
            # dispatches first; embeds arriving after see _unloaded and
            # refuse instead of dispatching into a dead world
            with self.control_plane.dispatch_lock:
                self._unloaded = True
                # lint: allow(lock-order): unload must be FIFO-after the last mirrored call
                self.control_plane.broadcast(("unload",))
        METRICS.remove_gauge("tpu_model_active_slots")
        METRICS.remove_gauge("tpu_model_queue_depth")
        if self.engine.paged:
            METRICS.remove_gauge("tpu_model_kv_free_pages")
        if getattr(self.engine, "radix_enabled", False):
            METRICS.remove_gauge("tpu_model_radix_nodes")
            METRICS.remove_gauge("tpu_model_radix_pages")
        if getattr(self.engine, "host_cache_enabled", False):
            METRICS.remove_gauge("tpu_model_host_cache_bytes")
            METRICS.remove_gauge("tpu_model_host_cache_pages")
        for _kind in ("decode", "admit", "extend", "spec"):
            METRICS.remove_gauge("tpu_model_dispatch_ms",
                                 labels=f'{{program="{_kind}"}}')
        for _g in ("tpu_model_mfu", "tpu_model_occupancy",
                   "tpu_model_goodput_tokens_per_second",
                   "tpu_model_padding_waste_pct"):
            METRICS.remove_gauge(_g)


class _IdleScheduler:
    """Scheduler facade for embedding-only models: always quiet, never
    broken — the manager's keep-alive reaper and load-health checks read
    these fields (n_active, has_pending, qsize, finished, broken) on
    every resident model."""
    n_active = 0
    qsize = 0
    has_pending = False
    broken = False
    n_preemptions = 0
    n_restarts = 0
    finished = ()      # reaper: no completed generations to re-arm from
    # /api/ps reads these off every resident model's scheduler; an
    # encoder has no decode loop, so they are permanently "off"
    async_dispatch = False
    spec_k = 0
    spec_drafted = 0
    spec_accepted = 0
    n_throttles = 0
    draining = False
    n_replays = 0
    n_watchdog_fires = 0

    def admission_stats(self) -> dict:
        return {}   # encoders have no waiting line to police

    def lifecycle_stats(self) -> dict:
        return {}   # no decode loop: nothing to replay, drain, or watch

    def utilization_stats(self, window_s: float = 60.0) -> dict:
        return {}   # no dispatches: nothing to account

    def begin_drain(self):
        pass        # encoders hold no streams; drain is instant

    def drain(self, timeout_s=None) -> int:
        return 0

    def shutdown(self):
        pass


class EmbeddingModel:
    """A resident encoder (BERT-family) model: tokenizer + ONE jitted
    bidirectional forward, no Engine/KV-cache/decode loop. Serves
    /api/embed, /api/embeddings, and /v1/embeddings; generation routes
    reject with a clear 400 (matching how the reference's embedding
    images behave — llama.cpp refuses generation on encoder archs)."""

    def __init__(self, name: str, cfg, params, tokenizer,
                 digest: str = ""):
        import jax.numpy as jnp
        self.name = name
        self.cfg = cfg
        self.digest = digest
        self.tokenizer = tokenizer
        self.loaded_at = time.time()
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self.scheduler = _IdleScheduler()
        self.is_encoder = True
        self._lock = threading.Lock()

    def embed(self, texts) -> np.ndarray:
        from ..models import encoder as E
        ids = [self.tokenizer.encode(t) for t in texts]
        with self._lock:   # jit cache + single-chip dispatch serialization
            return E.embed_batch(self.params, self.cfg, ids)

    # -- generation surface: honest rejection --------------------------
    def _reject(self, *_a, **_kw):
        from ..server.app import ApiError
        raise ApiError(400, f"{self.name!r} is an embedding model "
                            f"(arch {self.cfg.arch}); it does not support "
                            f"generation — use /api/embed")

    generate = generate_stream = render_chat = render_prompt = _reject

    def unload(self):
        self.params = None
