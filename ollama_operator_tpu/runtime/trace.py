"""Request-lifecycle tracing and the crash flight recorder.

Zero-dependency observability core (stdlib only — no opentelemetry, no
prometheus_client; ROADMAP forbids new deps).  Two instruments:

1. **Per-request span timelines** (`RequestTrace`): every request carries a
   lock-cheap append-only event list stamping its path through the stack —
   queued → admit/stitch → each chunked-prefill piece → each decode dispatch
   (with epoch/bucket/spec-acceptance and launch-vs-materialize split) →
   detok → HTTP flush.  Appends are a single `list.append` of a tuple (
   GIL-atomic, no lock), so tracing rides the hot decode path at well under
   the 2% tok/s budget `bench.py measure_mixed` enforces.

2. **Flight recorder** (`FlightRecorder`): a global fixed-size ring buffer of
   structured scheduler/engine events (admissions, preemptions, restarts,
   quarantine transitions, async fallbacks, fault injections).  On a
   supervised restart or a chaos-drill fault the last N events are dumped as
   JSON lines to stderr, so every CI chaos job prints what happened *before*
   the injected failure — the crash-only analogue of a black box.

Multi-host note: recording is strictly host-side.  Nothing here enqueues
mirrored engine calls, so followers replay the exact same device program
stream whether the leader traces or not (`runtime/follower.py` invariant).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

# Kill switch: TPU_TRACE=0 swaps every begin() for the shared no-op trace.
# The flight recorder stays on regardless — it is the crash debugger, its
# cost is one deque append per *scheduler-level* event, not per token.
TRACE_ENABLED = os.environ.get("TPU_TRACE", "1") not in ("0", "false", "")

# How many finished request timelines the registry keeps for /debug/trace.
TRACE_KEEP = int(os.environ.get("TPU_TRACE_KEEP", "256"))

# Ring size of the flight recorder (structured events, not tokens).
FLIGHT_EVENTS = int(os.environ.get("TPU_FLIGHT_EVENTS", "512"))


class RequestTrace:
    """Span timeline for one request.

    Events are `(t_rel_s, name, fields)` tuples appended without a lock;
    `t_rel_s` is seconds since the trace began (perf_counter deltas, so
    spans subtract cleanly).  `fields` is a small dict or None.
    """

    __slots__ = ("rid", "t_wall", "_t0", "events", "cls", "tenant")

    def __init__(self, rid: str):
        self.rid = rid
        self.t_wall = time.time()
        self._t0 = time.perf_counter()
        self.events: List[tuple] = []
        # admission identity (PR 8 priority class / tenant), set by the
        # scheduler at submit() so a slow span is attributable to a class
        self.cls: Optional[str] = None
        self.tenant: Optional[str] = None

    def set_identity(self, cls: Optional[str] = None,
                     tenant: Optional[str] = None) -> None:
        if cls:
            self.cls = cls
        if tenant:
            self.tenant = tenant

    def event(self, name: str, **fields: Any) -> None:
        self.events.append(
            (time.perf_counter() - self._t0, name, fields or None))

    def event_at(self, t_abs: float, name: str, **fields: Any) -> None:
        """Record an event stamped at an earlier perf_counter() reading
        (e.g. a dispatch *launch* observed only when the handle is waited)."""
        self.events.append((t_abs - self._t0, name, fields or None))

    def to_dict(self) -> Dict[str, Any]:
        evs = []
        for t, name, fields in list(self.events):
            e = {"t_ms": round(t * 1e3, 3), "ev": name}
            if fields:
                e.update(fields)
            evs.append(e)
        out = {"id": self.rid, "t_start_unix": self.t_wall, "events": evs}
        if self.cls:
            out["class"] = self.cls
        if self.tenant:
            out["tenant"] = self.tenant
        return out

    def timings(self) -> Dict[str, Any]:
        """Condensed per-stage summary for the opt-in `timings` block in the
        final NDJSON frame (options.trace=true)."""
        first: Dict[str, float] = {}
        last: Dict[str, float] = {}
        counts: Dict[str, int] = {}
        for t, name, _ in list(self.events):
            first.setdefault(name, t)
            last[name] = t
            counts[name] = counts.get(name, 0) + 1
        out: Dict[str, Any] = {
            "spans": [{"ev": k, "first_ms": round(first[k] * 1e3, 3),
                       "last_ms": round(last[k] * 1e3, 3), "n": counts[k]}
                      for k in first],
        }
        if "admitted" in first and "queued" in first:
            out["queue_wait_ms"] = round(
                (first["admitted"] - first["queued"]) * 1e3, 3)
        return out


class _NullTrace:
    """Shared no-op stand-in when TPU_TRACE=0: call sites never branch."""

    __slots__ = ()
    rid = ""
    events: List[tuple] = []
    cls: Optional[str] = None
    tenant: Optional[str] = None

    def set_identity(self, cls: Optional[str] = None,
                     tenant: Optional[str] = None) -> None:
        pass

    def event(self, name: str, **fields: Any) -> None:
        pass

    def event_at(self, t_abs: float, name: str, **fields: Any) -> None:
        pass

    def to_dict(self) -> Dict[str, Any]:
        return {"id": "", "events": []}

    def timings(self) -> Dict[str, Any]:
        return {"spans": []}


NULL_TRACE = _NullTrace()


class Tracer:
    """Bounded registry of recent request timelines, keyed by request id.

    begin() is called by the scheduler at submit(); the trace stays
    addressable through GET /debug/trace?id= until TRACE_KEEP newer
    requests push it out."""

    def __init__(self, keep: int = TRACE_KEEP):
        self._lock = threading.Lock()
        self._keep = max(1, keep)
        self._traces: "collections.OrderedDict[str, RequestTrace]" = \
            collections.OrderedDict()

    def begin(self, rid) -> RequestTrace:
        if not TRACE_ENABLED:
            return NULL_TRACE  # type: ignore[return-value]
        tr = RequestTrace(str(rid))
        with self._lock:
            self._traces[tr.rid] = tr
            while len(self._traces) > self._keep:
                self._traces.popitem(last=False)
        return tr

    def get(self, rid) -> Optional[RequestTrace]:
        with self._lock:
            return self._traces.get(str(rid))

    def ids(self) -> List[str]:
        with self._lock:
            return list(self._traces)


class FlightRecorder:
    """Fixed-size ring buffer of structured events; survives until dumped.

    Events are plain dicts `{"seq": n, "t_unix": ..., "kind": ..., **fields}`.
    record() takes one short lock (deque.append is atomic but the seq
    counter is not); dump() snapshots under the same lock then writes JSON
    lines outside it."""

    def __init__(self, maxlen: int = FLIGHT_EVENTS):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(
            maxlen=max(16, maxlen))
        self._seq = 0
        self._dumps = 0

    def record(self, kind: str, **fields: Any) -> None:
        ev = {"seq": 0, "t_unix": round(time.time(), 6), "kind": kind}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    @property
    def seq(self) -> int:
        """Total events ever recorded (the ring keeps only the tail)."""
        return self._seq

    @property
    def dumps(self) -> int:
        return self._dumps

    def dump(self, reason: str, stream=None, last: int = 0) -> int:
        """Print the last `last` events (0 = all buffered) as JSON lines.

        Called from the supervisor restart path and chaos drills; writes to
        stderr by default so CI job logs capture it even when the process is
        about to be torn down.  Returns the number of events printed."""
        evs = self.snapshot()
        if last > 0:
            evs = evs[-last:]
        out = stream if stream is not None else sys.stderr
        with self._lock:
            self._dumps += 1
        try:
            out.write(f"--- flight recorder dump: {reason} "
                      f"({len(evs)} events) ---\n")
            for ev in evs:
                out.write(json.dumps(ev, default=str) + "\n")
            out.write(f"--- end flight recorder dump: {reason} ---\n")
            out.flush()
        except Exception:  # lint: allow(exception-hygiene): a broken stderr must never mask the original failure
            pass
        return len(evs)


TRACER = Tracer()
FLIGHT = FlightRecorder()
