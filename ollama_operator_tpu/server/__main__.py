"""CLI entry: `python -m ollama_operator_tpu.server`.

Runs either role from the reference's architecture:
- model server (per-model Deployment pods, pod.go:14): --preload <model>
- store server (image-store StatefulSet, image_store.go:126): --store-only —
  serves /api/pull into the shared store and the model-management API, no
  engine.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def main(argv=None):
    p = argparse.ArgumentParser("tpu-ollama-server")
    p.add_argument("--host", default=os.environ.get("OLLAMA_HOST_BIND",
                                                    "0.0.0.0"))
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("OLLAMA_PORT", "11434")))
    p.add_argument("--store", default=os.environ.get(
        "OLLAMA_MODELS", os.path.expanduser("~/.ollama/models")),
        help="blob store root (the shared PVC mount)")
    p.add_argument("--cache", default=os.environ.get("TPU_WEIGHT_CACHE"),
                   help="transcoded-weights cache dir")
    p.add_argument("--preload", default=os.environ.get("TPU_PRELOAD_MODEL"),
                   help="model to load at startup")
    p.add_argument("--store-only", action="store_true",
                   default=os.environ.get("TPU_STORE_ONLY") == "1",
                   help="registry/store mode: no inference engine")
    p.add_argument("--dtype", default=os.environ.get("TPU_ENGINE_DTYPE")
                   or None,
                   choices=["bfloat16", "bf16", "float32", "int8", "int4"],
                   help="weight dtype (default: resolved PER MODEL at load "
                        "on TPU — int8 ≤4B params, int4 for 7B+, bf16 for "
                        "MoE, the measured serving configs; float32 on CPU "
                        "— XLA's CPU thunk runtime has no bf16 dots; int4 "
                        "packs two nibbles per byte, ~0.63 B/weight with "
                        "group scales)")
    p.add_argument("--kv-dtype", default=os.environ.get("TPU_KV_DTYPE")
                   or None,
                   choices=["bfloat16", "float32", "int8", "int4"],
                   help="KV cache storage (default int8 on TPU — half the "
                        "decode cache traffic, double the context, the "
                        "measured serving config; float32 on CPU; int4 "
                        "nibble-packs two positions per byte — paged "
                        "cache only)")
    p.add_argument("--max-slots", type=int,
                   default=int(os.environ.get("TPU_MAX_SLOTS", "0")),
                   help="continuous-batching slots (0 = per-model default:"
                        " 32 paged, 8 dense)")
    p.add_argument("--decode-chunk", type=int,
                   default=int(os.environ.get("TPU_DECODE_CHUNK", "0")),
                   help="decode steps per device round-trip (higher = "
                        "more throughput, chunkier streaming; 0 = backend "
                        "default: 32 on TPU — the measured headline "
                        "config — 8 on CPU; 64 buys ~3% more aggregate "
                        "tok/s at 2x the streaming granularity)")
    p.add_argument("--max-seq-len", type=int,
                   default=int(os.environ.get("TPU_MAX_SEQ_LEN", "4096")))
    p.add_argument("--tp", type=int,
                   default=int(os.environ.get("TPU_TENSOR_PARALLEL", "0")),
                   help="tensor-parallel ways (0 = all local devices)")
    p.add_argument("--sp", type=int,
                   default=int(os.environ.get("TPU_SEQUENCE_PARALLEL", "1")),
                   help="sequence-parallel ways (ring attention + "
                        "sequence-sharded KV cache for long context)")
    p.add_argument("--ep", type=int,
                   default=int(os.environ.get("TPU_EXPERT_PARALLEL", "1")),
                   help="expert-parallel ways (MoE experts sharded over "
                        "the ep mesh axis; >1 only helps MoE archs)")
    p.add_argument("--dp", type=int,
                   default=int(os.environ.get("TPU_DATA_PARALLEL", "0")),
                   help="in-engine data-parallel ways: slots (and the "
                        "paged page pool) shard over dp (0 = derive from "
                        "devices left over after tp/sp/ep; note replicas "
                        "in the CRD fan out dp across PODS instead)")
    _paged_env = os.environ.get("TPU_PAGED", "")
    if _paged_env not in ("", "0", "1"):
        # 'false'/'off'/... must not silently resolve to the auto default
        # (which could page the very pod that asked for dense)
        p.error(f"TPU_PAGED={_paged_env!r}: expected 1, 0, or unset")
    p.add_argument("--paged", action="store_true",
                   default=({"1": True, "0": False}.get(_paged_env, None)),
                   help="paged KV cache: slots share a physical page pool "
                        "so HBM scales with live tokens, not max_slots × "
                        "max_seq_len. Unset = per-model default (paged "
                        "for GQA models — measured 1.90x the dense "
                        "aggregate; dense for MHA/MoE); TPU_PAGED=0 "
                        "forces dense")
    p.add_argument("--page-size", type=int,
                   default=int(os.environ.get("TPU_PAGE_SIZE", "0")),
                   help="KV pool page size in tokens (0 = backend "
                        "default: 128 paged on TPU — measured +10%% over "
                        "64 at B=32 — else 64)")
    p.add_argument("--n-pages", type=int,
                   default=int(os.environ.get("TPU_N_PAGES", "0")),
                   help="KV pool pages (0 = dense-equivalent "
                        "max_slots*max_seq_len/page_size)")
    p.add_argument("--profile-port", type=int,
                   default=int(os.environ.get("TPU_PROFILE_PORT", "0")),
                   help="jax.profiler server port (0 = off)")
    args = p.parse_args(argv)

    from ..runtime.engine import EngineConfig
    from .app import ModelManager, serve

    mesh = None
    joined = False
    if not args.store_only:
        import jax
        # honor an explicit JAX_PLATFORMS (e.g. cpu for kind/e2e pods) even
        # where a sitecustomize force-sets the platform list programmatically
        if os.environ.get("JAX_PLATFORMS"):
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        # multi-host slice? join the jax.distributed world BEFORE touching
        # the backend (operator-rendered env; no-op single-host)
        from ..parallel.distributed import maybe_initialize
        joined = maybe_initialize()
        if args.cache and os.environ.get("TPU_XLA_CACHE", "1") != "0":
            # persistent XLA compilation cache beside the weight cache: pod
            # restarts skip the multi-program warm-up compiles.
            # TPU_XLA_CACHE=0 opts out: some CPU hosts miscompile on the
            # executable-deserialization path (wrong decode tokens), the
            # same instability that keeps the test-suite cache opt-in
            xla_cache = os.path.join(args.cache, "xla-cache")
            os.makedirs(xla_cache, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", xla_cache)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
        if args.profile_port:
            jax.profiler.start_server(args.profile_port)
        devices = jax.devices()
        # a TPU pod silently falling back to CPU (tunnel/driver hiccup)
        # must crash loudly, not serve garbage at 1/100th speed: the
        # operator sets TPU_EXPECT_PLATFORM=tpu on runtime: tpu pods
        expect = os.environ.get("TPU_EXPECT_PLATFORM")
        if expect and jax.default_backend() != expect:
            p.error(f"expected JAX platform {expect!r} but initialised "
                    f"{jax.default_backend()!r} (devices: {devices})")
        sp = max(1, args.sp)
        ep = max(1, args.ep)
        dp = max(0, args.dp)
        if dp:
            tp = args.tp or max(1, len(devices) // (sp * ep * dp))
            from ..parallel import MeshPlan, make_mesh
            plan = MeshPlan(dp=dp, sp=sp, tp=tp, ep=ep)
            if plan.n_devices > len(devices):
                p.error(f"plan {plan} needs {plan.n_devices} devices; "
                        f"have {len(devices)}")
            mesh = make_mesh(plan, devices[: plan.n_devices])
        else:
            tp = args.tp or len(devices) // (sp * ep)
            if tp < 1 or len(devices) % (tp * sp * ep) != 0:
                p.error(f"parallelism plan tp={args.tp or 'auto'} sp={sp} "
                        f"ep={ep} does not fit {len(devices)} devices")
            if tp * sp * ep > 1:
                from ..parallel import MeshPlan, make_mesh
                plan = MeshPlan.for_devices(len(devices), tp=tp, sp=sp,
                                            ep=ep)
                mesh = make_mesh(plan)
                dp = plan.dp
        print(f"devices: {devices}, tensor-parallel: {tp}, "
              f"sequence-parallel: {sp}, expert-parallel: {ep}, "
              f"data-parallel: {dp or 1}",
              file=sys.stderr)

    from ..runtime.engine import resolve_cache_dtype, resolve_kv_dtype_default
    # platform-aware defaults: the zero-config CR must serve the measured
    # config (VERDICT r4 #3) — weight dtype resolves PER MODEL at load
    # (ModelManager.load → resolve_engine_dtype: int8 ≤4B / int4 7B+ /
    # bf16 MoE on TPU, f32 on CPU); KV int8 on TPU, f32 on CPU
    on_cpu = not args.store_only and all(
        d.platform == "cpu" for d in devices)
    if args.dtype is None and args.store_only:
        args.dtype = "float32"       # store pods never build an engine
    if args.kv_dtype is None:
        args.kv_dtype = resolve_kv_dtype_default("cpu" if on_cpu or
                                                 args.store_only else "tpu")
    if args.decode_chunk < 0:
        p.error(f"--decode-chunk {args.decode_chunk}: expected >= 0")
    ecfg = EngineConfig(max_slots=args.max_slots,
                        max_seq_len=args.max_seq_len,
                        decode_chunk=args.decode_chunk,
                        cache_dtype=resolve_cache_dtype(args.kv_dtype),
                        paged=args.paged, page_size=args.page_size,
                        n_pages=args.n_pages or None)
    engine_dtype = (None if args.dtype is None
                    else {"bf16": "bfloat16"}.get(args.dtype, args.dtype))

    # multi-host slice roles (runtime/follower.py): process 0 serves HTTP
    # and broadcasts every engine call; the rest replay the stream so the
    # whole jax.distributed world executes identical SPMD programs
    control_plane = None
    if not args.store_only and joined:
        import jax as _jax

        from ..runtime.follower import (ControlPlane, control_address,
                                        run_follower)
        chost, cport = control_address()
        if _jax.process_index() == 0:
            control_plane = ControlPlane(_jax.process_count() - 1, cport)
        else:
            manager = ModelManager(args.store, cache_dir=args.cache,
                                   mesh=mesh, ecfg=ecfg,
                                   engine_dtype=engine_dtype,
                                   follower=True)
            print(f"follower {_jax.process_index()}: replaying "
                  f"{chost}:{cport}", file=sys.stderr)
            run_follower(manager, chost, cport, health_port=args.port)
            return

    manager = ModelManager(args.store, cache_dir=args.cache, mesh=mesh,
                           ecfg=ecfg, engine_dtype=engine_dtype,
                           serve_models=not args.store_only,
                           control_plane=control_plane)
    if args.preload and not args.store_only:
        print(f"preloading {args.preload}...", file=sys.stderr)
        manager.load(args.preload)
        print("preload done", file=sys.stderr)

    httpd = serve(manager, args.host, args.port)
    print(f"listening on {args.host}:{args.port}", file=sys.stderr)
    # block the signals before sigwait — delivery to the default disposition
    # would otherwise race the wait and skip the graceful shutdown
    signal.pthread_sigmask(signal.SIG_BLOCK,
                           [signal.SIGINT, signal.SIGTERM])
    stop = signal.sigwait([signal.SIGINT, signal.SIGTERM])
    print(f"signal {stop}, shutting down", file=sys.stderr)
    # graceful shutdown sequence (rollouts must be zero-error):
    #   1. drain — /readyz goes 503 "draining" so the Service pulls this
    #      endpoint, new submits shed 503+Retry-After, running streams
    #      finish within TPU_DRAIN_TIMEOUT_S (stragglers get a terminal
    #      "drain" frame). The operator's preStop hook + grace period
    #      (operator/workload.py) size the kube side to match.
    #   2. stop the listener — in-flight handlers already got their
    #      terminal frames in step 1.
    #   3. unload — scheduler shutdown (fence_quiesce, queue drain) and,
    #      multi-host, the FIFO ("unload",) broadcast to followers.
    #   4. release the followers with ("shutdown",) so their replay
    #      loops return instead of dying on a closed socket.
    #   5. stop the reaper and dump the flight recorder — the black box
    #      of the shutdown itself lands in the pod's final log lines.
    # Every step is bounded and best-effort: a wedged engine must never
    # turn SIGTERM into a SIGKILL at the grace-period cliff.
    from ..runtime.trace import FLIGHT
    try:
        shed = manager.drain()
        if shed:
            print(f"drain: shed {shed} straggler(s)", file=sys.stderr)
    except Exception as e:  # noqa: BLE001
        print(f"drain failed: {e}", file=sys.stderr)
    httpd.shutdown()
    try:
        manager.unload_now()
    except Exception as e:  # noqa: BLE001
        print(f"unload failed: {e}", file=sys.stderr)
    if control_plane is not None:
        try:
            with control_plane.dispatch_lock:
                control_plane.broadcast(("shutdown",))
        except Exception:  # lint: allow(exception-hygiene): follower already gone
            pass
        control_plane.close()
    manager.shutdown()
    FLIGHT.dump("shutdown")


if __name__ == "__main__":
    main()
