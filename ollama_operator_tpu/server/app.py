"""Model manager + Ollama-compatible HTTP server (stdlib, threaded).

This is the API surface the reference's probes and clients rely on
(/root/reference/pkg/model/pod.go:41-64 probes /api/tags;
docs/pages/en/guide/getting-started.md:129-150 uses /api/generate and
/v1/chat/completions) — served by a JAX/TPU engine instead of llama.cpp:

  GET  /                      liveness banner
  GET  /api/version
  GET  /api/tags              local model list
  POST /api/pull              streaming pull progress (NDJSON)
  POST /api/generate          streaming generation (NDJSON)
  POST /api/chat              chat-templated generation (NDJSON)
  POST /api/show              modelfile/template/params/details
  POST /api/create            build a model from a Modelfile
  POST /api/copy, /api/delete, GET /api/ps
  POST /api/embeddings, /api/embed
  POST /v1/chat/completions, /v1/completions, GET /v1/models   (OpenAI)
  GET  /metrics               Prometheus (tok/s, TTFT — SURVEY.md §5 gap)
  GET  /healthz, /readyz

One model is resident at a time (each Model CR gets its own Deployment in
the operator design, mirroring the reference's per-model pods); naming a
different model swaps it in under a lock.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import math
import os
import queue
import threading
import time
from datetime import datetime, timezone
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from .. import __version__
from ..gguf.reader import GGUFFile
from ..gguf.transcode import load_model as transcode_load
from ..runtime.engine import EngineConfig, resolve_serving_defaults
from ..runtime.admission import TenantRateLimited, tenant_from_key
from ..runtime.errors import BadRequest, DeadlineExceeded, FollowerLost
from ..runtime.scheduler import SchedulerBroken, SchedulerBusy
from ..runtime.service import LoadedModel
from ..runtime.trace import FLIGHT, TRACER
from ..tokenizer import Tokenizer
from .metrics import GLOBAL as METRICS
from .modelfile import Modelfile, parse_modelfile, params_json
from .names import ModelName
from .registry import (MT_ADAPTER, MT_LICENSE, MT_MODEL, MT_PARAMS,
                       MT_PROJECTOR,
                       MT_SYSTEM, MT_TEMPLATE, ModelStore, RegistryClient,
                       RegistryError)


def _decode_images(images):
    """Ollama API images: list of base64 strings → uint8 [H, W, 3] arrays
    (PIL handles the container format). None/[] → None."""
    if not images:
        return None
    import base64
    import io
    from PIL import Image
    out = []
    for b64 in images:
        try:
            raw = base64.b64decode(b64) if isinstance(b64, str) else bytes(b64)
            im = Image.open(io.BytesIO(raw)).convert("RGB")
        except Exception as e:
            raise BadRequest(f"invalid image: {e}") from e
        out.append(np.asarray(im, np.uint8))
    return out


def _now_iso() -> str:
    return datetime.now(timezone.utc).isoformat()


# streaming-coalescing defaults: flush a frame every N tokens or T ms,
# whichever comes first (the first piece always flushes immediately — it
# carries TTFT). N=16 halves frame count at decode_chunk=8 and is a no-op
# relative to chunking at decode_chunk=32; 25 ms keeps perceived latency
# below a display refresh even when tokens trickle.
STREAM_FLUSH_TOKENS = 16
STREAM_FLUSH_MS = 25.0


def resolve_stream_flush(options: Optional[Dict]) -> Tuple[int, float]:
    """(tokens-per-frame, seconds-between-frames) for stream coalescing.

    Request options (`stream_flush_tokens`, `stream_flush_ms`) override
    the env (TPU_STREAM_FLUSH_TOKENS / TPU_STREAM_FLUSH_MS), which
    overrides the defaults. `stream_flush_tokens: 1` restores per-piece
    frames."""
    o = options or {}
    try:
        n = int(o.get("stream_flush_tokens",
                      os.environ.get("TPU_STREAM_FLUSH_TOKENS",
                                     STREAM_FLUSH_TOKENS)))
    except (TypeError, ValueError):
        n = STREAM_FLUSH_TOKENS
    try:
        ms = float(o.get("stream_flush_ms",
                         os.environ.get("TPU_STREAM_FLUSH_MS",
                                        STREAM_FLUSH_MS)))
    except (TypeError, ValueError):
        ms = STREAM_FLUSH_MS
    return max(1, n), max(0.0, ms) / 1000.0


class _StreamCoalescer:
    """Batches streamed text pieces into wire frames.

    The first piece flushes immediately (it is the TTFT token); after
    that a frame goes out every `max_tokens` tokens or `max_s` seconds,
    whichever comes first. Frames are assembled from pre-serialised
    invariant byte fragments into one reused per-request buffer, so the
    steady-state cost per frame is one strftime-free timestamp, one
    json.dumps of the text, and one socket write."""

    def __init__(self, chunk_fn, make_frame, max_tokens: int, max_s: float,
                 trace=None):
        self._chunk = chunk_fn
        self._make = make_frame
        self.max_tokens = max_tokens
        self.max_s = max_s
        self._parts = []
        self._ntok = 0
        self._t_last = None     # None → flush the first piece immediately
        self.frames = 0
        # request span timeline (runtime/trace.py) — the HTTP flush is
        # the last hop of the request's path, stamped per frame
        self._trace = trace

    def add(self, piece: str):
        self._parts.append(piece)
        self._ntok += getattr(piece, "n_tokens", 1)
        now = time.monotonic()
        if (self._t_last is None or self._ntok >= self.max_tokens
                or now - self._t_last >= self.max_s):
            self.flush(now)

    def flush(self, now: Optional[float] = None):
        if not self._parts:
            return
        text = "".join(self._parts)
        n_tok = self._ntok
        self._parts.clear()
        self._ntok = 0
        self._t_last = time.monotonic() if now is None else now
        self._chunk(self._make(text))
        self.frames += 1
        METRICS.inc("tpu_model_stream_frames_total")
        if self._trace is not None:
            self._trace.event("http_flush", n_tokens=n_tok,
                              chars=len(text))


def _fmt_params(n: int) -> str:
    if n >= 1e9:
        return f"{n / 1e9:.1f}B"
    return f"{n / 1e6:.0f}M"


class ApiError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(message)
        self.status = status


def parse_keep_alive(v) -> Optional[float]:
    """Ollama keep_alive → seconds (None = keep forever).

    Accepts numbers (seconds; negative = forever) and Go-style duration
    strings ("5m", "1h30m", "300ms", "-1"). 0 means "unload as soon as
    idle"."""
    if v is None:
        raise BadRequest("keep_alive is None")
    if isinstance(v, bool):
        raise BadRequest(f"bad keep_alive {v!r}")
    if isinstance(v, (int, float)):
        if not math.isfinite(v):
            raise BadRequest(f"bad keep_alive {v!r}")
        return None if v < 0 else float(v)
    s = str(v).strip()
    if not s:
        raise BadRequest("empty keep_alive")
    try:
        n = float(s)
        if not math.isfinite(n):
            raise ValueError
        return None if n < 0 else n
    except ValueError:
        pass
    import re
    m = re.fullmatch(r"(-?)((?:\d+(?:\.\d+)?(?:ns|us|µs|ms|s|m|h))+)", s)
    if not m:
        raise BadRequest(f"bad keep_alive {v!r}")
    if m.group(1):
        return None
    unit_s = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3,
              "s": 1.0, "m": 60.0, "h": 3600.0}
    total = 0.0
    for num, unit in re.findall(r"(\d+(?:\.\d+)?)(ns|us|µs|ms|s|m|h)", s):
        total += float(num) * unit_s[unit]
    return total


class ModelManager:
    """Owns the blob store, registry client, and the resident model."""

    def __init__(self, store_root: str, cache_dir: Optional[str] = None,
                 mesh=None, ecfg: Optional[EngineConfig] = None,
                 engine_dtype="bfloat16", serve_models: bool = True,
                 default_keep_alive=None, control_plane=None,
                 follower: bool = False):
        self.store = ModelStore(store_root)
        self.client = RegistryClient(self.store)
        self.mesh = mesh
        self.ecfg = ecfg
        self.cache_dir = cache_dir
        self.engine_dtype = engine_dtype
        self.serve_models = serve_models  # store-only mode serves pulls only
        # multi-host slice roles (runtime/follower.py): the leader's
        # control plane broadcasts load/unload + engine calls; a follower
        # manager builds bare engines (no scheduler/HTTP) and replays
        self.control_plane = control_plane
        self.follower = follower
        self.loaded: Optional[LoadedModel] = None
        self._lock = threading.Lock()
        self.start_time = time.time()
        # keep_alive: model idle-unload timer (the reference's engine keeps
        # this inside `ollama serve`; OLLAMA_KEEP_ALIVE is its env knob)
        import os
        raw_ka = (default_keep_alive if default_keep_alive is not None
                  else (os.environ.get("OLLAMA_KEEP_ALIVE") or "5m"))
        try:
            self.default_keep_alive = parse_keep_alive(raw_ka)
        except ValueError:
            # a malformed env var must not keep the pod from booting
            import sys
            print(f"warning: invalid OLLAMA_KEEP_ALIVE {raw_ka!r}; "
                  f"using 5m", file=sys.stderr)
            self.default_keep_alive = 300.0
        self.expires_at: Optional[float] = None
        self._last_ka: Optional[float] = self.default_keep_alive
        self._reaper_stop = threading.Event()
        # graceful drain (SIGTERM / preStop): /readyz flips 503 so the
        # Service pulls this endpoint, new submits shed 503+Retry-After,
        # running streams finish within TPU_DRAIN_TIMEOUT_S
        self.draining = False
        # followers unload on the leader's ("unload",) broadcast, never on
        # their own clock
        if serve_models and not follower:
            self._reaper = threading.Thread(
                target=self._reap_idle, daemon=True, name="keepalive-reaper")
            self._reaper.start()

    # ------------------------------------------------------------------
    def touch(self, keep_alive=None):
        """Reset the loaded model's idle-unload deadline (called per
        request; an explicit request keep_alive overrides the default)."""
        ka = self.default_keep_alive
        if keep_alive is not None:
            try:
                ka = parse_keep_alive(keep_alive)
            except ValueError:
                raise ApiError(400, f"invalid keep_alive "
                                    f"{keep_alive!r}") from None
        with self._lock:
            self._last_ka = ka
            self.expires_at = None if ka is None else time.monotonic() + ka

    def _reap_idle(self):
        while not self._reaper_stop.wait(1.0):
            with self._lock:
                lm = self.loaded
                exp = self.expires_at
                if (lm is None or exp is None or time.monotonic() < exp):
                    continue
                # only unload a quiet model: active slots / queued requests
                # push the actual unload past the deadline
                if lm.scheduler.has_pending:
                    continue
                # deadline is armed at request START; a generation longer
                # than keep_alive must still get its full idle window after
                # it finishes (stock server re-arms at completion)
                if self._last_ka is not None and lm.scheduler.finished:
                    last_done = lm.scheduler.finished[-1].t_done
                    if time.monotonic() < last_done + self._last_ka:
                        continue
                self.loaded = None
                self.expires_at = None
            lm.unload()  # outside the lock: shutdown joins the decode loop

    def stop(self, ref: str) -> bool:
        """keep_alive: 0 with an empty prompt — the `ollama stop` path.
        Unloads now when idle; with requests in flight it only expires the
        deadline so the reaper unloads after they drain (stock server never
        truncates other clients' generations). Returns True if ``ref`` is
        the resident model."""
        name = ModelName.parse(ref)
        with self._lock:
            lm = self.loaded
            if lm is None or lm.name != name.short:
                return False
            if lm.scheduler.has_pending:
                self._last_ka = 0.0
                self.expires_at = time.monotonic()  # reap once drained
                return True
            self.loaded = None
            self.expires_at = None
        lm.unload()
        return True

    def unload_now(self):
        """Immediate unload (follower replay of the leader's unload)."""
        with self._lock:
            lm, self.loaded = self.loaded, None
            self.expires_at = None
        if lm is not None:
            lm.unload()

    def shutdown(self):
        self._reaper_stop.set()

    def begin_drain(self):
        """Enter draining: readiness goes 503 (the operator's Service
        stops routing here), the scheduler sheds new submits, running
        streams keep generating. Idempotent. A draining replica is a
        scale-down or scale-to-zero candidate, so the AOT warm state is
        snapshotted to the shared cache volume here — the next wake
        restores it instead of recompiling the warm plan."""
        with self._lock:
            already, self.draining = self.draining, True
            lm = self.loaded
        if not already:
            FLIGHT.record("drain", phase="manager",
                          model=lm.name if lm is not None else None)
        if lm is not None:
            lm.scheduler.begin_drain()
            if not already and hasattr(lm, "save_warm_snapshot"):
                lm.save_warm_snapshot()
            # hottest KV prefixes ride along to the shared volume: the
            # next wake (any replica of this digest) imports them and
            # serves shared-prefix traffic as warm tier-2 hits
            if not already and hasattr(lm, "save_prefix_snapshot"):
                lm.save_prefix_snapshot()

    def drain(self, timeout_s: Optional[float] = None) -> int:
        """Graceful drain for SIGTERM: begin_drain(), then let the
        resident model's streams finish within ``timeout_s`` (default
        TPU_DRAIN_TIMEOUT_S) before stragglers are shed. Returns the
        straggler count."""
        self.begin_drain()
        with self._lock:
            lm = self.loaded
        if lm is None:
            return 0
        return lm.scheduler.drain(timeout_s)

    # ------------------------------------------------------------------
    def model_details(self, name: ModelName) -> Dict:
        out = {"format": "gguf", "family": "", "families": None,
               "parameter_size": "", "quantization_level": ""}
        try:
            layers = self.store.model_layers(name)
            path = layers.get(MT_MODEL)
            if path:
                with GGUFFile(path) as f:
                    out["family"] = f.arch
                    out["families"] = [f.arch]
                    cnt = f.metadata.get("general.parameter_count")
                    if cnt:
                        out["parameter_size"] = _fmt_params(int(cnt))
                    ft = f.metadata.get("general.file_type")
                    ftypes = {0: "F32", 1: "F16", 2: "Q4_0", 3: "Q4_1",
                              7: "Q8_0", 8: "Q5_0", 9: "Q5_1", 10: "Q2_K",
                              11: "Q3_K_S", 12: "Q3_K_M", 13: "Q3_K_L",
                              14: "Q4_K_S", 15: "Q4_K_M", 16: "Q5_K_S",
                              17: "Q5_K_M", 18: "Q6_K"}
                    if ft is not None:
                        out["quantization_level"] = ftypes.get(ft, str(ft))
        except (RegistryError, OSError, ValueError):
            pass
        return out

    def list_models(self):
        models = []
        for m in self.store.list_models():
            name: ModelName = m["name"]
            digest = (m["manifest"].get("config", {}) or {}).get("digest", "")
            models.append({
                "name": name.short, "model": name.short,
                "modified_at": datetime.fromtimestamp(
                    m["modified_at"], timezone.utc).isoformat(),
                "size": m["size"],
                "digest": digest.replace("sha256:", ""),
                "details": self.model_details(name),
            })
        return models

    def _read_layer_text(self, layers: Dict[str, str], mt: str
                         ) -> Optional[str]:
        path = layers.get(mt)
        if not path:
            return None
        try:
            with open(path, "r", errors="replace") as f:
                return f.read()
        except OSError:
            return None

    def load(self, ref: str) -> LoadedModel:
        if not self.serve_models:
            raise ApiError(503, "this instance is a model store; it serves "
                                "pulls, not inference")
        name = ModelName.parse(ref)
        with self._lock:
            if self.loaded is not None and self.loaded.name == name.short:
                if not self.loaded.scheduler.broken:
                    return self.loaded
                # broken scheduler (decode-loop gave up after repeated
                # failures): tear down and fall through to a fresh load so
                # a transient TPU/XLA fault doesn't wedge the pod forever
                self.loaded.unload()
                self.loaded = None
            layers = self.store.model_layers(name)  # raises if absent
            gguf_path = layers.get(MT_MODEL)
            if not gguf_path:
                raise ApiError(500, f"model {name.short} has no model layer")
            digest = self.store.model_digest(name) or ""
            import jax
            import ml_dtypes
            # ONE header open serves the arch probe, the encoder load, and
            # the auto-dtype config read (re-parsing multi-MB tokenizer
            # metadata per question would tax every model switch)
            from ..gguf.reader import GGUFFile as _GF
            from ..gguf.transcode import (config_from_gguf,
                                          encoder_config_from_gguf,
                                          is_encoder_arch,
                                          load_encoder_params)
            _enc = None
            _hcfg = None
            with _GF(gguf_path) as _hdr:
                if is_encoder_arch(_hdr.arch):
                    # embedding-only images (all-minilm & friends):
                    # BERT-family encoders load WITHOUT an Engine —
                    # tokenizer + one jitted bidirectional forward
                    # (runtime/service.EmbeddingModel); the reference
                    # serves these through llama.cpp's BERT path
                    ecfg2 = encoder_config_from_gguf(_hdr)
                    _enc = (ecfg2, load_encoder_params(_hdr, ecfg2),
                            {k: v for k, v in _hdr.metadata.items()
                             if k.startswith("tokenizer.")})
                elif self.engine_dtype is None:
                    _hcfg = config_from_gguf(_hdr)
            if _enc is not None:
                from ..runtime.service import EmbeddingModel
                ecfg2, eparams, tok_md = _enc
                if self.loaded is not None:
                    self.loaded.unload()
                    self.loaded = None
                if self.control_plane is not None:
                    self.control_plane.broadcast(("load", ref))
                self.loaded = EmbeddingModel(
                    name.short, ecfg2, eparams,
                    Tokenizer.from_gguf_metadata(tok_md), digest=digest)
                self.loaded.serving_dtype = "float32"
                self._last_ka = self.default_keep_alive
                self.expires_at = (None if self.default_keep_alive is None
                                   else time.monotonic()
                                   + self.default_keep_alive)
                return self.loaded
            engine_dtype = self.engine_dtype
            if engine_dtype is None:
                # no CR quantization / --dtype: resolve the measured
                # serving dtype PER MODEL from the GGUF header (int8 ≤4B,
                # int4 7B+, bf16 MoE on TPU; f32 on CPU) so `kubectl
                # apply` of a bare Model CR serves the config the bench
                # proves, not an unmeasured bf16 one (VERDICT r4 #3)
                from ..runtime.engine import resolve_engine_dtype
                engine_dtype = resolve_engine_dtype(
                    _hcfg, jax.default_backend())
                import sys
                print(f"serving dtype for {name.short}: {engine_dtype} "
                      f"({_hcfg.n_params/1e9:.2f}B params, auto)",
                      file=sys.stderr)
            dt = {"bfloat16": ml_dtypes.bfloat16, "int8": ml_dtypes.bfloat16,
                  "int4": ml_dtypes.bfloat16,
                  "float32": np.float32}[engine_dtype]
            if (jax.default_backend() == "cpu"
                    and dt is ml_dtypes.bfloat16):
                # this XLA CPU build cannot execute bf16 dots
                # (DotThunk UNIMPLEMENTED) — CPU serving runs f32
                dt = np.float32
            # parse/transcode the new model (host memory) BEFORE tearing the
            # old one down: a corrupt pull must not leave the server empty
            cfg, params, tok_md = transcode_load(
                gguf_path, cache_dir=self.cache_dir, dtype=dt,
                digest=digest.replace("sha256:", "")[:24] or None)
            adapter_path = layers.get(MT_ADAPTER)
            if adapter_path:
                # Modelfile ADAPTER: merge W += (alpha/r)·BA host-side so
                # serving runs unmodified fused matmuls (gguf/lora.py);
                # must happen before int8 weight quantization below
                from ..gguf.lora import apply_lora
                try:
                    params = apply_lora(params, cfg, adapter_path)
                except ValueError as e:
                    raise ApiError(400, f"adapter: {e}") from e
            tokenizer = Tokenizer.from_gguf_metadata(tok_md)
            template = self._read_layer_text(layers, MT_TEMPLATE)
            system = self._read_layer_text(layers, MT_SYSTEM)
            params_raw = self._read_layer_text(layers, MT_PARAMS)
            default_params = json.loads(params_raw) if params_raw else {}
            if self.loaded is not None:
                self.loaded.unload()
                self.loaded = None
            import jax.numpy as jnp
            # (auto resolution never picks int8/int4 for MoE — explicit
            # spec.quantization on an MoE model keeps its old behavior)
            if engine_dtype in ("int8", "int4"):
                # weight-only quantization: int8/packed-int4 weights stay
                # quantized in HBM; dequant fuses into the matmuls
                # (ops/quant.py)
                from ..ops.quant import quantize_params
                params = quantize_params(
                    params, bits=4 if engine_dtype == "int4" else 8)
                if engine_dtype == "int4":
                    from ..ops.quant import int4_mm_kernels
                    cfg = int4_mm_kernels(cfg, self.mesh)
            params = jax.tree_util.tree_map(jnp.asarray, params)
            vision = None
            proj_path = layers.get(MT_PROJECTOR)
            if proj_path:
                # llava-family mmproj layer: CLIP tower + MLP projector
                from ..gguf.reader import GGUFFile
                from ..gguf.transcode import (load_vision_params,
                                              vision_config_from_gguf)
                with GGUFFile(proj_path) as vf:
                    vcfg = vision_config_from_gguf(vf)
                    vparams = load_vision_params(vf, vcfg, dtype=dt)
                vision = (vcfg, jax.tree_util.tree_map(jnp.asarray, vparams))
            ecfg = self.ecfg or EngineConfig(
                max_seq_len=min(cfg.max_seq_len,
                                int(default_params.get("num_ctx", 4096))))
            # tri-state serving defaults, resolved per model: paged for
            # GQA on TPU (measured 2x the dense aggregate), dense for
            # MHA/MoE/CPU, pool capped at the old dense-8 HBM ceiling
            ecfg = resolve_serving_defaults(ecfg, cfg, self.mesh)
            if self.control_plane is not None:
                # followers pull the same layers from their own store and
                # replay this load; their first mirrored engine call
                # queues behind it on the FIFO control stream
                self.control_plane.broadcast(("load", ref))
            self.loaded = LoadedModel(
                name.short, cfg, params, tokenizer, template=template,
                system=system, default_params=default_params,
                mesh=self.mesh, ecfg=ecfg, digest=digest, vision=vision,
                control_plane=self.control_plane, follower=self.follower,
                warm_cache_dir=self.cache_dir)
            # effective serving config, for /api/ps observability (the
            # auto-resolved dtype is otherwise invisible to clients)
            self.loaded.serving_dtype = engine_dtype
            # fresh deadline under this same lock: a stale expiry from the
            # previous model must never reap the one we just installed
            self._last_ka = self.default_keep_alive
            self.expires_at = (None if self.default_keep_alive is None
                               else time.monotonic() + self.default_keep_alive)
            return self.loaded

    def require_loaded(self, ref: str, keep_alive=None) -> LoadedModel:
        ka = self.default_keep_alive
        if keep_alive is not None:
            try:
                ka = parse_keep_alive(keep_alive)
            except ValueError:
                raise ApiError(400, f"invalid keep_alive "
                                    f"{keep_alive!r}") from None
        for _ in range(3):
            try:
                lm = self.load(ref)
            except RegistryError as e:
                raise ApiError(404, str(e)) from e
            # arm the deadline under the same lock the reaper tests — if
            # the reaper unloaded between load() returning and here, retry
            # instead of handing out a shut-down scheduler
            with self._lock:
                if self.loaded is lm:
                    self._last_ka = ka
                    self.expires_at = (None if ka is None
                                       else time.monotonic() + ka)
                    return lm
        raise ApiError(503, f"model {ref!r} kept unloading during load "
                            f"(keep_alive too short?)")

    def ps(self):
        out = []
        with self._lock:
            lm = self.loaded
        if lm is not None:
            with self._lock:
                exp = self.expires_at
            if exp is None:
                expires = "0001-01-01T00:00:00Z"  # keep_alive < 0: forever
            else:
                wall = time.time() + (exp - time.monotonic())
                expires = datetime.fromtimestamp(
                    wall, timezone.utc).isoformat()
            out.append({
                "name": lm.name, "model": lm.name,
                "size": int(lm.cfg.n_params * 2),
                "digest": lm.digest.replace("sha256:", ""),
                "details": {"format": "gguf", "family": lm.cfg.arch,
                            "parameter_size": _fmt_params(lm.cfg.n_params),
                            "serving_dtype": getattr(lm, "serving_dtype",
                                                     None),
                            # embedding models carry no engine
                            "decode_chunk": (lm.engine.ecfg.decode_chunk
                                             if getattr(lm, "engine", None)
                                             is not None else None),
                            "paged": (bool(lm.engine.paged)
                                      if getattr(lm, "engine", None)
                                      is not None else False)},
                "expires_at": expires,
                "size_vram": 0,
                # crash-only serving status: supervised restarts on THIS
                # scheduler object plus process-lifetime failure counters
                # (the same series /metrics exports)
                "failures": {
                    "broken": bool(lm.scheduler.broken),
                    "engine_restarts": lm.scheduler.n_restarts,
                    "request_timeouts": int(METRICS.get(
                        "tpu_model_request_timeouts_total")),
                    "requests_shed": int(METRICS.get(
                        "tpu_model_requests_shed_total")),
                    "followers_lost": int(METRICS.get(
                        "tpu_model_followers_lost_total")),
                },
                # stall-free batching telemetry: last launch-to-host ms
                # per device program kind, plus process-lifetime admission
                # counters (same series /metrics exports)
                "dispatch": {
                    # whether decode double-buffers (false = forced sync:
                    # TPU_ASYNC_DISPATCH=0 or paged dp>1; the per-dispatch
                    # grammar fallback counts in
                    # tpu_model_async_fallback_total, not here — fused
                    # speculation double-buffers and never falls back)
                    "async": bool(lm.scheduler.async_dispatch),
                    "dispatch_ms": (dict(lm.engine.dispatch_ms)
                                    if getattr(lm, "engine", None)
                                    is not None else {}),
                    "prefill_chunks": int(METRICS.get(
                        "tpu_model_prefill_chunks_total")),
                    "admission_stall_ms": METRICS.get(
                        "tpu_model_admission_stall_ms_total"),
                },
                # radix prefix cache: process-lifetime hit/miss token
                # counters + live tree residency (same series /metrics
                # exports; nodes/pages are 0 when the cache is off)
                "prefix_cache": {
                    "enabled": bool(getattr(lm, "engine", None) is not None
                                    and getattr(lm.engine, "radix_enabled",
                                                False)),
                    "hit_tokens": int(METRICS.get(
                        "tpu_model_prefix_hit_tokens_total")),
                    "miss_tokens": int(METRICS.get(
                        "tpu_model_prefix_miss_tokens_total")),
                    "radix_nodes": (int(lm.engine.radix_nodes)
                                    if getattr(lm, "engine", None)
                                    is not None else 0),
                    "radix_pages": (int(lm.engine.radix_pages)
                                    if getattr(lm, "engine", None)
                                    is not None else 0),
                    # tiered residency: HBM pages (tier 0) vs spilled
                    # pages pinned in the host arena (tier 1/2), plus the
                    # arena byte occupancy against its capacity — all 0
                    # when TPU_HOST_CACHE_GB is unset
                    "tiers": {
                        "hbm_pages": (int(lm.engine.radix_pages)
                                      if getattr(lm, "engine", None)
                                      is not None else 0),
                        "host_pages": (int(lm.engine.host_cache_pages)
                                       if getattr(lm, "engine", None)
                                       is not None else 0),
                        "host_bytes": (int(lm.engine.host_cache_used_bytes)
                                       if getattr(lm, "engine", None)
                                       is not None else 0),
                        "host_capacity_bytes": (
                            int(lm.engine.host_cache_capacity_bytes)
                            if getattr(lm, "engine", None)
                            is not None else 0),
                    },
                },
                # fused prompt-lookup speculation: process-lifetime
                # drafted/accepted token counters (same series /metrics
                # exports) and the rate operators tune TPU_SPEC_DECODE
                # by — a rate holding under ~0.3 means lookup misses are
                # paying dispatch overhead for nothing, switch it off
                "spec": {
                    "enabled": lm.scheduler.spec_k > 0,
                    "k": lm.scheduler.spec_k,
                    "drafted_tokens": int(METRICS.get(
                        "tpu_model_spec_drafted_tokens_total")),
                    "accepted_tokens": int(METRICS.get(
                        "tpu_model_spec_accepted_tokens_total")),
                    "acceptance_rate": (
                        round(lm.scheduler.spec_accepted
                              / lm.scheduler.spec_drafted, 4)
                        if lm.scheduler.spec_drafted else 0.0),
                },
                # overload discipline: live admission-policy snapshot —
                # per-class queue depth / token backlog, WDRR tenant
                # state, throttles, and the knobs in force (empty for
                # encoder models, which have no waiting line)
                "admission": lm.scheduler.admission_stats(),
                # lifecycle: serving/draining/broken state, the restart-
                # replay budget in force, and hung-dispatch watchdog
                # posture (empty for encoder models)
                "lifecycle": lm.scheduler.lifecycle_stats(),
                # utilization accounting (runtime/accounting.py): 60s
                # MFU/goodput/occupancy window, dispatch-wait/host/idle
                # breakdown, and mid-serving recompile counts — the
                # block the operator mirrors into the Model CR status
                # (empty for encoder models)
                "utilization": lm.scheduler.utilization_stats(),
            })
        return out

    # -- model management ----------------------------------------------
    def show(self, ref: str) -> Dict:
        name = ModelName.parse(ref)
        manifest = self.store.read_manifest(name)
        if manifest is None:
            raise ApiError(404, f"model {name.short!r} not found")
        layers = self.store.model_layers(name)
        template = self._read_layer_text(layers, MT_TEMPLATE) or ""
        system = self._read_layer_text(layers, MT_SYSTEM) or ""
        params_raw = self._read_layer_text(layers, MT_PARAMS)
        lic = self._read_layer_text(layers, MT_LICENSE) or ""
        mf = Modelfile(from_=name.short, template=template or None,
                       system=system or None,
                       adapter=layers.get(MT_ADAPTER))
        parameters = ""
        if params_raw:
            try:
                pj = json.loads(params_raw)
                mf.parameters = pj
                parameters = "\n".join(
                    f"{k:30s} {item}" for k, v in sorted(pj.items())
                    for item in (v if isinstance(v, list) else [v]))
            except json.JSONDecodeError:
                pass
        info = {}
        path = layers.get(MT_MODEL)
        if path:
            try:
                with GGUFFile(path) as f:
                    info = {k: v for k, v in f.metadata.items()
                            if not isinstance(v, list) or len(v) < 64}
            except (OSError, ValueError):
                pass
        capabilities = ["completion"]
        if MT_PROJECTOR in layers:
            capabilities.append("vision")   # llava-family (mmproj layer)
        return {"modelfile": mf.render(), "parameters": parameters,
                "template": template, "system": system, "license": lic,
                "details": self.model_details(name), "model_info": info,
                "capabilities": capabilities}

    def copy(self, src: str, dst: str):
        sname, dname = ModelName.parse(src), ModelName.parse(dst)
        manifest = self.store.read_manifest(sname)
        if manifest is None:
            raise ApiError(404, f"model {sname.short!r} not found")
        self.store.write_manifest(dname, manifest)

    def delete(self, ref: str):
        name = ModelName.parse(ref)
        if not self.store.delete_model(name):
            raise ApiError(404, f"model {name.short!r} not found")
        with self._lock:
            if self.loaded is not None and self.loaded.name == name.short:
                self.loaded.unload()
                self.loaded = None

    def create(self, ref: str, modelfile_text: str,
               progress=None) -> None:
        mf = parse_modelfile(modelfile_text)
        if not mf.from_:
            raise ApiError(400, "Modelfile needs a FROM line")
        name = ModelName.parse(ref)
        layers = []
        base_params: Dict = {}
        if mf.from_.startswith("@"):
            # pre-uploaded blob reference: `ollama create` rewrites a
            # local-file FROM into POST /api/blobs/<digest> + FROM @digest
            import os
            digest = mf.from_[1:]
            if not self.store.has_blob(digest):
                raise ApiError(400, f"FROM {mf.from_!r}: blob not "
                                    "uploaded (POST /api/blobs/<digest>)")
            layers.append({"mediaType": MT_MODEL, "digest": digest,
                           "size": os.path.getsize(
                               self.store.blob_path(digest))})
        elif (base_manifest := self.store.read_manifest(
                ModelName.parse(mf.from_))) is not None:
            # FROM a local model name: inherit every base layer the
            # Modelfile doesn't override (ollama keeps base template/
            # system/params on create); params merge
            overridden = set()
            if mf.template:
                overridden.add(MT_TEMPLATE)
            if mf.system:
                overridden.add(MT_SYSTEM)
            if mf.license:
                overridden.add(MT_LICENSE)
            if mf.adapter:
                overridden.add(MT_ADAPTER)
            for layer in base_manifest.get("layers", []):
                mt = layer["mediaType"]
                if mt == MT_PARAMS:
                    try:
                        with open(self.store.blob_path(layer["digest"])) as f:
                            base_params = json.load(f)
                    except (OSError, json.JSONDecodeError):
                        pass
                    continue  # re-emitted (possibly merged) below
                if mt not in overridden:
                    layers.append(layer)
        else:
            # FROM a GGUF file path on the server's filesystem
            import os
            if not os.path.exists(mf.from_):
                raise ApiError(400, f"FROM {mf.from_!r}: not a local model "
                                    "or file")
            if progress:
                progress("importing model blob", 0, 0)
            entry = self.store.add_blob_file(mf.from_)
            layers.append({"mediaType": MT_MODEL, **entry})
        if mf.template:
            layers.append({"mediaType": MT_TEMPLATE,
                           **self.store.add_blob(mf.template.encode())})
        if mf.system:
            layers.append({"mediaType": MT_SYSTEM,
                           **self.store.add_blob(mf.system.encode())})
        if mf.parameters or base_params:
            merged = dict(base_params)
            merged.update(mf.parameters or {})
            mf_merged = dataclasses.replace(mf, parameters=merged)
            layers.append({"mediaType": MT_PARAMS,
                           **self.store.add_blob(
                               params_json(mf_merged).encode())})
        if mf.license:
            layers.append({"mediaType": MT_LICENSE,
                           **self.store.add_blob(mf.license.encode())})
        if mf.adapter:
            import os
            if not os.path.exists(mf.adapter):
                raise ApiError(400, f"ADAPTER {mf.adapter!r}: no such file")
            if progress:
                progress("importing adapter", 0, 0)
            layers.append({"mediaType": MT_ADAPTER,
                           **self.store.add_blob_file(mf.adapter)})
        config = self.store.add_blob(json.dumps(
            {"model_format": "gguf"}).encode())
        manifest = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.docker.distribution.manifest.v2+json",
            "config": {"mediaType": "application/vnd.docker.container.image.v1+json",
                       **config},
            "layers": layers,
        }
        self.store.write_manifest(name, manifest)
        if progress:
            progress("success", 0, 0)


# ---------------------------------------------------------------------------
# HTTP layer
# ---------------------------------------------------------------------------

class Handler(BaseHTTPRequestHandler):
    manager: ModelManager = None  # set by serve()
    protocol_version = "HTTP/1.1"
    server_version = "tpu-ollama/" + __version__
    # with a BOUNDED worker pool (_DeepStackHTTPServer), an idle
    # keep-alive connection parked on readline() must not hold a worker
    # forever — time it out and let the client reconnect
    timeout = 75

    # -- helpers --------------------------------------------------------
    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json_body(self) -> Dict:
        n = int(self.headers.get("Content-Length") or 0)
        if n == 0:
            return {}
        try:
            return json.loads(self.rfile.read(n) or b"{}")
        except json.JSONDecodeError as e:
            raise ApiError(400, f"invalid json: {e}") from e

    def _send_json(self, obj, status=200,
                   headers: Optional[Dict[str, str]] = None):
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, text: str, status=200,
                   ctype="text/plain; charset=utf-8"):
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _start_stream(self, ctype="application/x-ndjson"):
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        self._streaming = True
        self._stream_ctype = ctype

    def _chunk(self, data: bytes):
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _end_stream(self):
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()
        self._streaming = False

    def _send_error(self, message: str, status: int,
                    headers: Optional[Dict[str, str]] = None):
        """Error that is safe both before and after a stream started: once
        chunked headers are out, a second status line would corrupt the
        framing — emit the error as a final chunk instead."""
        if getattr(self, "_streaming", False):
            try:
                if getattr(self, "_stream_ctype", "") == "text/event-stream":
                    # keep SSE framing: a bare JSON line mid-stream is
                    # dropped by OpenAI SDKs and the missing [DONE] hangs them
                    self._chunk(self._sse({"error": {
                        "message": message, "type": "server_error"}}))
                    self._chunk(b"data: [DONE]\n\n")
                else:
                    self._stream_json({"error": message})
                self._end_stream()
            except (BrokenPipeError, ConnectionResetError):
                pass
        else:
            self._send_json({"error": message}, status, headers=headers)

    def _stream_json(self, obj):
        self._chunk(json.dumps(obj).encode() + b"\n")

    @staticmethod
    def _pull_first(gen):
        """Pull the FIRST (piece, final) pair before the caller commits
        200 + chunked headers. Failures that precede the first token —
        deadline shed while queued (503 + Retry-After), admission errors
        — can then surface as real HTTP status codes; once the first
        item exists the stream is committed and later failures become
        terminal frames. Returns an iterator replaying that first item."""
        it = iter(gen)
        try:
            first = next(it)
        except StopIteration:
            return iter(())
        return itertools.chain([first], it)

    def _coalescer(self, pre: bytes, mid: Optional[bytes], suf: bytes,
                   options: Optional[Dict], trace=None) -> _StreamCoalescer:
        """Frame coalescer over this response's chunked stream. A frame is
        `pre + now_iso + mid + json(text) + suf` (NDJSON; the timestamp
        is the only other varying part) or `pre + json(text) + suf` when
        ``mid`` is None (SSE chunks carry no per-frame timestamp). The
        fragments must reproduce json.dumps' default rendering of the
        full frame dict byte-for-byte — the wire format is unchanged,
        only how many tokens each frame carries."""
        n, s = resolve_stream_flush(options)
        buf = bytearray()

        def make(text: str) -> bytearray:
            buf.clear()
            buf.extend(pre)
            if mid is not None:
                # an ISO-8601 UTC timestamp is plain ASCII with no JSON
                # escapes, so splicing it raw equals json.dumps output
                buf.extend(_now_iso().encode())
                buf.extend(mid)
            buf.extend(json.dumps(text).encode())
            buf.extend(suf)
            return buf

        return _StreamCoalescer(self._chunk, make, n, s, trace=trace)

    # -- debug introspection -------------------------------------------
    def _query(self) -> Dict[str, str]:
        """Last value per key of the request's query string."""
        from urllib.parse import parse_qs
        qs = parse_qs(self.path.partition("?")[2])
        return {k: v[-1] for k, v in qs.items()}

    def _debug_trace(self):
        """Span timeline of one recent request (runtime/trace.py). With
        no id, lists the ids the tracer still holds (newest last)."""
        q = self._query()
        rid = q.get("id")
        if rid is None:
            self._send_json({"ids": TRACER.ids()})
            return
        tr = TRACER.get(rid)
        if tr is None:
            self._send_json({"error": f"no trace for id {rid!r} "
                             "(evicted, or TPU_TRACE=0)"}, 404)
            return
        self._send_json(tr.to_dict())

    def _debug_events(self):
        """The flight-recorder ring: last TPU_FLIGHT_EVENTS structured
        scheduler/engine events, oldest first. ?kind=K keeps only one
        event type (applied BEFORE the trim, so ?kind=shed&last=10 is
        the newest 10 sheds); ?last=N trims to the newest N."""
        events = FLIGHT.snapshot()
        kind = self._query().get("kind", "")
        if kind:
            events = [e for e in events if e.get("kind") == kind]
        try:
            last = int(self._query().get("last", "0"))
        except ValueError:
            last = 0
        if last > 0:
            events = events[-last:]
        self._send_json({"events": events, "dumps": FLIGHT.dumps})

    def _debug_utilization(self):
        """Per-second utilization aggregates from the loaded model's
        accounting ring (?last=N seconds, default 60) plus the windowed
        snapshot — the payload behind the /api/ps utilization block."""
        lm = self.manager.loaded
        if lm is None or getattr(lm, "scheduler", None) is None:
            self._send_json({"error": "no generative model loaded"}, 404)
            return
        acct = getattr(lm.scheduler, "acct", None)
        if acct is None or not acct.enabled:
            self._send_json(
                {"enabled": False,
                 "error": "accounting disabled (TPU_ACCOUNTING=0)"}, 200)
            return
        try:
            last = int(self._query().get("last", "60"))
        except ValueError:
            last = 60
        self._send_json({
            "model": lm.name,
            "snapshot": lm.scheduler.utilization_stats(),
            "ring": acct.ring(last=max(1, min(last, 600))),
        })

    def _debug_profile(self):
        """Capture a jax.profiler trace for ?seconds= (default 2, max
        30) into a temp dir and report its path. Opt-in via
        TPU_DEBUG_PROFILE=1 — profiling stalls the device queue, so it
        must never be reachable on an unguarded production port."""
        if os.environ.get("TPU_DEBUG_PROFILE") != "1":
            self._send_json(
                {"error": "profiling disabled (set TPU_DEBUG_PROFILE=1)"},
                403)
            return
        try:
            seconds = float(self._query().get("seconds", "2"))
        except ValueError:
            seconds = 2.0
        seconds = min(max(seconds, 0.1), 30.0)
        import tempfile

        import jax
        out_dir = tempfile.mkdtemp(prefix="tpu-profile-")
        jax.profiler.start_trace(out_dir)
        try:
            time.sleep(seconds)
        finally:
            jax.profiler.stop_trace()
        self._send_json({"seconds": seconds, "trace_dir": out_dir})

    # -- routing --------------------------------------------------------
    def do_GET(self):
        try:
            path = self.path.split("?")[0]
            if path == "/":
                self._send_text("Ollama is running")
            elif path == "/api/version":
                self._send_json({"version": __version__})
            elif path == "/api/tags":
                self._send_json({"models": self.manager.list_models()})
            elif path == "/api/ps":
                self._send_json({"models": self.manager.ps()})
            elif path == "/v1/models":
                models = [{"id": m["name"], "object": "model",
                           "created": 0, "owned_by": "library"}
                          for m in self.manager.list_models()]
                self._send_json({"object": "list", "data": models})
            elif path == "/metrics":
                self._send_text(METRICS.render(),
                                ctype="text/plain; version=0.0.4")
            elif path == "/healthz":
                self._send_text("ok")
            elif path == "/debug/trace":
                self._debug_trace()
            elif path == "/debug/events":
                self._debug_events()
            elif path == "/debug/utilization":
                self._debug_utilization()
            elif path == "/debug/profile":
                self._debug_profile()
            elif path in ("/readyz", "/livez"):
                # livez fails too: a broken scheduler self-heals on the next
                # load(), but an idle pod would otherwise stay wedged with
                # no probe ever restarting it
                lm = self.manager.loaded
                if lm is not None and lm.scheduler.broken:
                    self._send_text("engine failed", status=503)
                elif path == "/readyz" and self.manager.draining:
                    # draining: readiness fails so the Service stops
                    # routing here, but liveness stays ok — the kubelet
                    # must NOT restart a pod mid-drain (that would cut
                    # the very streams the drain is protecting)
                    self._send_text("draining", status=503)
                else:
                    self._send_text("ok")
            else:
                self._send_json({"error": "not found"}, 404)
        except ApiError as e:
            self._send_json({"error": str(e)}, e.status)
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001
            self._send_json({"error": f"internal: {e}"}, 500)

    def do_DELETE(self):
        try:
            if self.path.split("?")[0] == "/api/delete":
                body = self._json_body()
                self.manager.delete(body.get("name") or body.get("model", ""))
                self._send_json({})
            else:
                self._send_json({"error": "not found"}, 404)
        except ApiError as e:
            self._send_json({"error": str(e)}, e.status)
        except Exception as e:  # noqa: BLE001
            self._send_json({"error": f"internal: {e}"}, 500)

    def do_HEAD(self):
        path = self.path.split("?")[0]
        if path.startswith("/api/blobs/"):
            # `ollama create` probes blobs before uploading (HEAD 200 =
            # skip the POST). Reject non-hex digests before touching the
            # filesystem — blob_path() joins the digest into a path, so an
            # unvalidated one is an arbitrary-path existence oracle.
            from .registry import valid_blob_digest
            digest = path[len("/api/blobs/"):]
            ok = (valid_blob_digest(digest)
                  and self.manager.store.has_blob(digest))
            self.send_response(200 if ok else 404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        if path == "/":
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()
        else:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()

    def do_POST(self):
        path = self.path.split("?")[0]
        if path.startswith("/api/blobs/"):
            self._api_blob_upload(path[len("/api/blobs/"):])
            return
        try:
            body = self._json_body()
            route = {
                "/api/generate": self._api_generate,
                "/api/chat": self._api_chat,
                "/api/pull": self._api_pull,
                "/api/push": self._api_push,
                "/api/create": self._api_create,
                "/api/show": self._api_show,
                "/api/copy": self._api_copy,
                "/api/delete": self._api_delete,
                "/api/embeddings": self._api_embeddings,
                "/api/embed": self._api_embed,
                "/api/drain": self._api_drain,
                "/api/prefix_probe": self._api_prefix_probe,
                "/api/kv_export": self._api_kv_export,
                "/api/kv_import": self._api_kv_import,
                "/v1/chat/completions": self._oai_chat,
                "/v1/completions": self._oai_completions,
                "/v1/embeddings": self._oai_embeddings,
            }.get(path)
            if route is None:
                self._send_json({"error": "not found"}, 404)
                return
            route(body)
        except ApiError as e:
            self._send_error(str(e), e.status)
        except BadRequest as e:
            # typed request-validation failures from the service layer (bad
            # format value, prompt too long, images on a text model, …).
            # Plain ValueError deliberately falls through to the 500 branch:
            # an internal jax/numpy ValueError is a server bug, not a 400.
            self._send_error(str(e), 400)
        except DeadlineExceeded as e:
            # shed while queued: the caller got nothing and should retry
            # (503 is what load balancers key backpressure on); a
            # mid-generation expiry normally ends as a terminal stream
            # frame, so a pre-stream surface here maps to 504
            if e.while_queued:
                self._send_error(str(e), 503, headers={
                    "Retry-After": str(int(e.retry_after_s))})
            else:
                self._send_error(str(e), 504)
        except TenantRateLimited as e:
            # THIS tenant is over its share; everyone else is fine —
            # 429, so client-side backoff stays per-tenant
            self._send_error(str(e), 429, headers={
                "Retry-After": str(int(getattr(e, "retry_after_s", 1)))})
        except SchedulerBusy as e:
            # queue-full and SLO early rejects both carry a computed
            # Retry-After (queue-model drain estimate), not a flat 1s
            self._send_error(str(e), 503, headers={
                "Retry-After": str(int(getattr(e, "retry_after_s", 1)))})
        except SchedulerBroken as e:
            self._send_error(str(e), 500)
        except FollowerLost as e:
            self._send_error(f"multi-host world degraded: {e}", 500)
        except RegistryError as e:
            self._send_error(str(e), 500)
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            import traceback
            traceback.print_exc()
            self._send_error(f"internal: {e}", 500)

    # -- Ollama endpoints ----------------------------------------------
    def _model_arg(self, body) -> str:
        model = body.get("model") or body.get("name")
        if not model:
            raise ApiError(400, "missing 'model'")
        return model

    def _inject_tenant(self, options: Optional[Dict]) -> Optional[Dict]:
        """Fair-queuing tenant from transport headers when the body
        didn't name one: ``X-Tenant`` verbatim, else a stable hash of
        the API key (``X-API-Key`` / ``Authorization``) — keyed clients
        get per-key fairness without any body change. Returns the
        options dict (possibly unchanged) for generate_stream."""
        o = dict(options or {})
        if not o.get("tenant"):
            t = self.headers.get("X-Tenant")
            if not t:
                key = (self.headers.get("X-API-Key")
                       or self.headers.get("Authorization"))
                t = tenant_from_key(key) if key else None
            if t:
                o["tenant"] = t
        return o or None

    def _api_generate(self, body: Dict):
        model = self._model_arg(body)
        prompt = body.get("prompt", "")
        ka = body.get("keep_alive")
        if not prompt and not body.get("context"):
            if ka is not None and parse_keep_alive(ka) == 0.0:
                # empty prompt + keep_alive 0 = `ollama stop`
                self.manager.stop(model)
                self._send_json({"model": model, "created_at": _now_iso(),
                                 "response": "", "done": True,
                                 "done_reason": "unload"})
                return
            # empty generate is ollama's "load the model" ping
            self.manager.require_loaded(model, keep_alive=ka)
            self._send_json({"model": model, "created_at": _now_iso(),
                             "response": "", "done": True,
                             "done_reason": "load"})
            return
        lm = self.manager.require_loaded(model, keep_alive=ka)
        stream = body.get("stream", True)
        raw = bool(body.get("raw", False))
        text_prompt = prompt if raw else lm.render_prompt(
            prompt, system=body.get("system"),
            template=body.get("template"), suffix=body.get("suffix"))
        gen = lm.generate_stream(text_prompt,
                                 options=self._inject_tenant(
                                     body.get("options")),
                                 context=body.get("context"), raw=raw,
                                 images=_decode_images(body.get("images")),
                                 format=body.get("format"))
        if stream:
            trace = getattr(gen, "trace", None)
            gen = self._pull_first(gen)
            self._start_stream()
            co = self._coalescer(
                b'{"model": ' + json.dumps(model).encode()
                + b', "created_at": "',
                b'", "response": ', b', "done": false}\n',
                body.get("options"), trace=trace)
            for piece, final in gen:
                if final is None:
                    co.add(piece)
                else:
                    co.flush()
                    self._stream_json(self._final_chunk(model, final, body))
            self._end_stream()
        else:
            final = None
            for _piece, f in gen:
                if f is not None:
                    final = f
            out = self._final_chunk(model, final, body)
            out["response"] = final.text
            self._send_json(out)

    def _final_chunk(self, model: str, res, body: Dict) -> Dict:
        out = {
            "model": model, "created_at": _now_iso(), "response": "",
            "done": True, "done_reason": res.done_reason,
            "total_duration": int(res.total_s * 1e9),
            "load_duration": 0,
            "prompt_eval_count": res.prompt_tokens,
            "prompt_eval_duration": int(res.ttft_s * 1e9),
            "eval_count": res.generated_tokens,
            "eval_duration": int(max(res.total_s - res.ttft_s, 0.0) * 1e9),
        }
        if body.get("context") is not None or not body.get("raw"):
            out["context"] = res.context
        if getattr(res, "timings", None) is not None:
            # opt-in (options.trace=true): per-span first/last/count
            # summary of the request's trace, plus the id to fetch the
            # full timeline from /debug/trace
            out["timings"] = dict(res.timings,
                                  request_id=getattr(res, "request_id", 0))
        return out

    def _api_chat(self, body: Dict):
        model = self._model_arg(body)
        messages = body.get("messages", [])
        ka = body.get("keep_alive")
        if not messages and ka is not None and parse_keep_alive(ka) == 0.0:
            self.manager.stop(model)
            self._send_json({"model": model, "created_at": _now_iso(),
                             "message": {"role": "assistant", "content": ""},
                             "done": True, "done_reason": "unload"})
            return
        lm = self.manager.require_loaded(model, keep_alive=ka)
        stream = body.get("stream", True)
        tools = body.get("tools")
        prompt = lm.render_chat(messages, template=body.get("template"),
                                tools=tools)
        images = []
        for m in messages:
            images.extend(m.get("images") or [])
        gen = lm.generate_stream(prompt,
                                 options=self._inject_tenant(
                                     body.get("options")),
                                 images=_decode_images(images),
                                 format=body.get("format"))

        def chat_message(final) -> Dict:
            """Assistant message for the completed generation: JSON tool
            invocations become structured tool_calls (server/tools.py);
            prose around them stays as content."""
            msg = {"role": "assistant", "content": final.text}
            if tools:
                from .tools import split_tool_calls
                calls, prose = split_tool_calls(final.text)
                if calls:
                    msg = {"role": "assistant", "content": prose,
                           "tool_calls": calls}
            return msg

        if stream and not tools:
            trace = getattr(gen, "trace", None)
            gen = self._pull_first(gen)
            self._start_stream()
            co = self._coalescer(
                b'{"model": ' + json.dumps(model).encode()
                + b', "created_at": "',
                b'", "message": {"role": "assistant", "content": ',
                b'}, "done": false}\n',
                body.get("options"), trace=trace)
            for piece, final in gen:
                if final is None:
                    co.add(piece)
                else:
                    co.flush()
                    out = self._final_chunk(model, final, body)
                    out.pop("response", None)
                    out.pop("context", None)
                    out["message"] = {"role": "assistant", "content": ""}
                    self._stream_json(out)
            self._end_stream()
        else:
            final = None
            for _p, f in gen:
                if f is not None:
                    final = f
            out = self._final_chunk(model, final, body)
            out.pop("response", None)
            out.pop("context", None)
            out["message"] = chat_message(final)
            if stream:
                # tool responses stream as ONE message chunk + final (the
                # invocation can't be parsed until the output completes)
                self._start_stream()
                self._stream_json({"model": model, "created_at": _now_iso(),
                                   "message": out["message"],
                                   "done": False})
                out["message"] = {"role": "assistant", "content": ""}
                self._stream_json(out)
                self._end_stream()
            else:
                self._send_json(out)

    def _api_pull(self, body: Dict):
        model = self._model_arg(body)
        stream = body.get("stream", True)
        if stream:
            self._start_stream()

            def progress(status, completed, total, digest=None):
                msg = {"status": status}
                if total:
                    msg["total"] = total
                    msg["completed"] = completed
                if digest:
                    msg["digest"] = digest
                self._stream_json(msg)

            try:
                self.manager.client.pull(model, progress)
            except RegistryError as e:
                self._stream_json({"error": str(e)})
            self._end_stream()
        else:
            self.manager.client.pull(model)
            self._send_json({"status": "success"})

    def _api_push(self, body: Dict):
        model = self._model_arg(body)
        stream = body.get("stream", True)
        if stream:
            self._start_stream()

            def progress(status, completed=0, total=0, digest=None):
                msg = {"status": status}
                if total:
                    msg["total"] = total
                    msg["completed"] = completed
                if digest:
                    msg["digest"] = digest
                self._stream_json(msg)

            try:
                self.manager.client.push(model, progress)
            except RegistryError as e:
                self._stream_json({"error": str(e)})
            self._end_stream()
        else:
            self.manager.client.push(model)
            self._send_json({"status": "success"})

    def _api_blob_upload(self, digest: str):
        """POST /api/blobs/sha256:<hex> — raw body is the blob; the CLI
        uploads local GGUFs here before /api/create references them."""
        from .registry import RegistryError, valid_blob_digest
        # Any error response sent without consuming the declared body would
        # leave blob bytes on the HTTP/1.1 keep-alive socket to be parsed as
        # the next request line — close the connection on every error path.
        try:
            length = int(self.headers.get("Content-Length", "0"))
            if length <= 0:
                self.close_connection = True
                self._send_error("missing blob body", 400)
                return
            if not valid_blob_digest(digest):
                self.close_connection = True
                self._send_error(f"unsupported digest {digest!r}", 400)
                return
            self.manager.store.put_blob_stream(digest, self.rfile, length)
            self.send_response(201)
            self.send_header("Content-Length", "0")
            self.end_headers()
        except RegistryError as e:
            self.close_connection = True
            self._send_error(str(e), 400)
        except BrokenPipeError:
            pass
        except Exception as e:  # noqa: BLE001
            self.close_connection = True
            self._send_error(f"internal: {e}", 500)

    def _api_create(self, body: Dict):
        model = self._model_arg(body)
        modelfile_text = body.get("modelfile", "")
        if not modelfile_text and body.get("files"):
            # newer create API: {"files": {"x.gguf": "sha256:..."}} of
            # pre-uploaded blobs (see _api_blob_upload)
            files = body["files"]
            if len(files) != 1:
                raise ApiError(400, "multi-file create is not supported "
                                    "(one GGUF per model)")
            digest = next(iter(files.values()))
            lines = [f"FROM @{digest}"]
            if body.get("template"):
                lines.append("TEMPLATE \"\"\"" + body["template"] + "\"\"\"")
            if body.get("system"):
                lines.append("SYSTEM \"\"\"" + body["system"] + "\"\"\"")
            for k, v in (body.get("parameters") or {}).items():
                items = v if isinstance(v, list) else [v]
                lines.extend(f"PARAMETER {k} {item}" for item in items)
            modelfile_text = "\n".join(lines)
        if not modelfile_text and body.get("from"):
            modelfile_text = f"FROM {body['from']}"
        stream = body.get("stream", True)
        if stream:
            self._start_stream()

            def progress(status, *_):
                self._stream_json({"status": status})

            try:
                self.manager.create(model, modelfile_text, progress)
            except ApiError as e:
                self._stream_json({"error": str(e)})
            self._end_stream()
        else:
            self.manager.create(model, modelfile_text)
            self._send_json({"status": "success"})

    def _api_show(self, body: Dict):
        self._send_json(self.manager.show(self._model_arg(body)))

    def _api_copy(self, body: Dict):
        src, dst = body.get("source"), body.get("destination")
        if not src or not dst:
            raise ApiError(400, "need 'source' and 'destination'")
        self.manager.copy(src, dst)
        self._send_json({})

    def _api_delete(self, body: Dict):
        self.manager.delete(self._model_arg(body))
        self._send_json({})

    def _api_drain(self, body: Dict):
        """Operator-initiated graceful drain (the drain-first scale-down
        protocol): readyz flips 503, new submits shed, running streams
        finish, and the AOT warm state is snapshotted for the next wake.
        Idempotent — the operator re-POSTs on every poll until the
        replica reports zero active work via /api/ps."""
        self.manager.begin_drain()
        lm = self.manager.loaded
        sched = lm.scheduler if lm is not None else None
        self._send_json({
            "status": "draining",
            "active_streams": int(getattr(sched, "n_active", 0) or 0),
            "queued": int(getattr(sched, "qsize", 0) or 0),
        })

    def _api_prefix_probe(self, body: Dict):
        """Non-mutating radix-cache probe for the fleet gateway's
        cache-aware routing: how many leading tokens of this request's
        rendered prompt THIS replica could serve from its prefix cache
        right now. The gateway scatters the probe to healthy replicas on
        an affinity-table miss and routes to the longest match. Renders
        the prompt exactly like /api/generate so the probed ids equal
        the ids the real request would admit with."""
        model = self._model_arg(body)
        prompt = body.get("prompt", "")
        lm = self.manager.require_loaded(model,
                                         keep_alive=body.get("keep_alive"))
        raw = bool(body.get("raw", False))
        text = prompt if raw else lm.render_prompt(
            prompt, system=body.get("system"),
            template=body.get("template"), suffix=body.get("suffix"))
        tok = getattr(lm, "tokenizer", None)
        engine = getattr(lm, "engine", None)
        matched = 0
        n_ids = 0
        tier = 0
        if tok is not None and engine is not None:
            ids = tok.encode(text, add_bos=tok.add_bos)
            n_ids = len(ids)
            if n_ids > 1:
                if hasattr(engine, "prefix_probe_tier"):
                    # worst tier on the matched path: 0 = all-HBM
                    # (restitch-free), 1 = host restitch needed, 2 = the
                    # match includes imported fleet-snapshot pages — the
                    # gateway prefers lower tiers on matched-length ties
                    matched, tier = engine.prefix_probe_tier(ids)
                    matched, tier = int(matched), int(tier)
                else:
                    matched = int(engine.prefix_probe(ids))
        self._send_json({"model": model, "matched_tokens": matched,
                         "matched_tier": tier, "prompt_tokens": n_ids})

    # -- disaggregated prefill→decode KV transfer (ISSUE 20) -----------
    def _request_ids(self, lm, body: Dict):
        """Token ids exactly as /api/generate (or /api/chat, when the
        body carries ``messages``) would admit them — the KV transfer is
        keyed by the request's real admitted ids, so rendering must not
        drift from the serving paths."""
        if body.get("messages") is not None:
            text = lm.render_chat(body.get("messages") or [],
                                  template=body.get("template"),
                                  tools=body.get("tools"))
            ids = []
        else:
            prompt = body.get("prompt", "")
            text = prompt if body.get("raw") else lm.render_prompt(
                prompt, system=body.get("system"),
                template=body.get("template"), suffix=body.get("suffix"))
            ids = list(body.get("context") or [])
        tok = lm.tokenizer
        return ids + tok.encode(text, add_bos=(not ids) and tok.add_bos)

    def _api_kv_export(self, body: Dict):
        """Serve the KV pages covering this request's prompt prefix as
        one octet-stream blob (runtime/kv_wire.py format). 404 = nothing
        exportable here (dense engine, prefix not parked, multi-host) —
        the puller treats any non-200 as "re-prefill instead", so this
        endpoint never invents an error frame. Writes are paced to
        TPU_DISAGG_TRANSFER_MB_S (0 = unthrottled) so a big transfer
        cannot starve co-resident decode traffic of NIC bandwidth."""
        model = self._model_arg(body)
        lm = self.manager.require_loaded(model,
                                         keep_alive=body.get("keep_alive"))
        if not hasattr(lm, "kv_export"):
            self._send_json({"error": "kv export unsupported"}, 404)
            return
        ids = self._request_ids(lm, body)
        max_bytes = int(body.get("max_bytes") or (64 << 20))
        try:
            blob = lm.kv_export(ids, max_bytes)
        except Exception as e:  # noqa: BLE001 — incl. injected pages.export
            # faults: a failed export is a soft downgrade for the caller
            # (journal replay / cold prefill), so answer 503, not 500
            self._send_json({"error": f"kv export failed: {e}"}, 503)
            return
        if not blob:
            self._send_json({"error": "no exportable prefix"}, 404)
            return
        rate = float(os.environ.get("TPU_DISAGG_TRANSFER_MB_S", "0") or 0)
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        step = 256 << 10
        t0 = time.monotonic()
        for off in range(0, len(blob), step):
            self.wfile.write(blob[off:off + step])
            if rate > 0:
                # sleep until the bytes sent so far fit under the cap
                ahead = ((off + step) / (rate * (1 << 20))
                         - (time.monotonic() - t0))
                if ahead > 0:
                    time.sleep(min(ahead, 1.0))
        self.wfile.flush()

    def _api_kv_import(self, body: Dict):
        """Pull a request's KV blob straight from the prefill replica
        named by ``source`` and graft it into this replica's radix tree
        (direct replica-to-replica transfer; the gateway only
        orchestrates). Always answers JSON with ``imported_pages`` —
        0 with a 2xx still means "go ahead and serve, you'll just
        re-prefill", which is why import failures are 5xx only when the
        pull itself broke."""
        model = self._model_arg(body)
        lm = self.manager.require_loaded(model,
                                         keep_alive=body.get("keep_alive"))
        source = body.get("source")
        if not source:
            raise ApiError(400, "missing 'source'")
        fwd = {k: body[k] for k in
               ("model", "prompt", "system", "template", "suffix", "raw",
                "context", "messages", "tools", "keep_alive", "max_bytes")
               if body.get(k) is not None}
        timeout = float(os.environ.get("TPU_DISAGG_HANDOFF_TIMEOUT_S",
                                       "30") or 30)
        import urllib.request
        req = urllib.request.Request(
            source.rstrip("/") + "/api/kv_export",
            data=json.dumps(fwd).encode(),
            headers={"Content-Type": "application/json"})
        t0 = time.monotonic()
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                blob = resp.read()
        except Exception as e:  # noqa: BLE001 — network/HTTP/timeout
            self._send_json({"error": f"kv pull failed: {e}",
                             "imported_pages": 0}, 502)
            return
        try:
            pages = lm.kv_import(blob)
        except Exception as e:  # noqa: BLE001 — incl. injected
            # pages.import faults: page table untouched, caller serves
            # the request cold
            self._send_json({"error": f"kv import failed: {e}",
                             "imported_pages": 0}, 503)
            return
        dt = time.monotonic() - t0
        if pages:
            METRICS.inc("tpu_model_kv_transfer_pages_total", float(pages))
            METRICS.inc("tpu_model_kv_transfer_bytes_total",
                        float(len(blob)))
            METRICS.observe("tpu_model_kv_transfer_seconds", dt)
        self._send_json({"imported_pages": pages, "bytes": len(blob),
                         "seconds": dt})

    def _api_embeddings(self, body: Dict):
        lm = self.manager.require_loaded(self._model_arg(body),
                                         keep_alive=body.get("keep_alive"))
        prompt = body.get("prompt", "")
        emb = lm.embed([prompt])[0]
        self._send_json({"embedding": [float(x) for x in emb]})

    def _embed_input(self, body: Dict):
        """Shared input handling for /api/embed and /v1/embeddings."""
        lm = self.manager.require_loaded(self._model_arg(body),
                                         keep_alive=body.get("keep_alive"))
        inp = body.get("input", "")
        texts = [inp] if isinstance(inp, str) else list(inp)
        return lm.embed(texts)

    def _api_embed(self, body: Dict):
        embs = self._embed_input(body)
        self._send_json({
            "model": body.get("model"), "object": "list",
            "embeddings": [[float(x) for x in e] for e in embs]})

    # -- OpenAI compatibility ------------------------------------------
    def _oai_chat(self, body: Dict):
        model = self._model_arg(body)
        lm = self.manager.require_loaded(model)
        messages = body.get("messages", [])
        options = {}
        for src, dst in (("temperature", "temperature"), ("top_p", "top_p"),
                         ("seed", "seed"),
                         ("frequency_penalty", "frequency_penalty"),
                         ("presence_penalty", "presence_penalty")):
            if body.get(src) is not None:
                options[dst] = body[src]
        if body.get("max_tokens") is not None:
            options["num_predict"] = body["max_tokens"]
        if body.get("stop"):
            options["stop"] = body["stop"]
        tools = body.get("tools")
        prompt = lm.render_chat(messages, tools=tools)
        rid = f"chatcmpl-{int(time.time() * 1000)}"
        created = int(time.time())
        # OpenAI response_format → grammar/schema-constrained decoding:
        # json_schema carries its schema dict through to the skeleton
        # machine (ops/schema.py); json_object = generic JSON grammar
        rf = body.get("response_format") or {}
        fmt = None
        if isinstance(rf, dict):
            if rf.get("type") == "json_schema":
                js = rf.get("json_schema")
                fmt = (js.get("schema") if isinstance(js, dict)
                       else None) or "json"
            elif rf.get("type") == "json_object":
                fmt = "json"
        gen = lm.generate_stream(prompt,
                                 options=self._inject_tenant(options),
                                 format=fmt)
        if tools:
            # buffer and answer as one completion: tool invocations are
            # parsed from the full output
            final = None
            for _p, f in gen:
                if f is not None:
                    final = f
            from .tools import split_tool_calls
            calls, prose = split_tool_calls(final.text)
            if calls:
                msg = {"role": "assistant", "content": prose or None,
                       "tool_calls": [
                           {"id": f"call_{rid}_{i}", "type": "function",
                            "function": {
                                "name": c["function"]["name"],
                                "arguments": json.dumps(
                                    c["function"]["arguments"])}}
                           for i, c in enumerate(calls)]}
                finish = "tool_calls"
            else:
                msg = {"role": "assistant", "content": final.text}
                finish = final.done_reason
            if body.get("stream"):
                # tool invocations parse only once the output completes:
                # stream the finished message as one SSE delta + finish
                self._start_stream(ctype="text/event-stream")
                delta = dict(msg)
                if delta.get("tool_calls"):
                    # SSE deltas carry a per-entry index
                    delta["tool_calls"] = [dict(tc, index=i) for i, tc in
                                           enumerate(delta["tool_calls"])]
                self._chunk(self._sse({
                    "id": rid, "object": "chat.completion.chunk",
                    "created": created, "model": model,
                    "choices": [{"index": 0, "delta": delta,
                                 "finish_reason": None}]}))
                self._chunk(self._sse({
                    "id": rid, "object": "chat.completion.chunk",
                    "created": created, "model": model,
                    "choices": [{"index": 0, "delta": {},
                                 "finish_reason": finish}]}))
                self._chunk(b"data: [DONE]\n\n")
                self._end_stream()
                return
            self._send_json({
                "id": rid, "object": "chat.completion", "created": created,
                "model": model,
                "choices": [{"index": 0, "message": msg,
                             "finish_reason": finish}],
                "usage": {"prompt_tokens": final.prompt_tokens,
                          "completion_tokens": final.generated_tokens,
                          "total_tokens": final.prompt_tokens +
                          final.generated_tokens}})
            return
        if body.get("stream"):
            trace = getattr(gen, "trace", None)
            gen = self._pull_first(gen)
            self._start_stream(ctype="text/event-stream")
            self._chunk(self._sse({
                "id": rid, "object": "chat.completion.chunk",
                "created": created, "model": model,
                "choices": [{"index": 0,
                             "delta": {"role": "assistant", "content": ""},
                             "finish_reason": None}]}))
            co = self._coalescer(
                b'data: {"id": ' + json.dumps(rid).encode()
                + b', "object": "chat.completion.chunk", "created": '
                + str(created).encode() + b', "model": '
                + json.dumps(model).encode()
                + b', "choices": [{"index": 0, "delta": {"content": ',
                None, b'}, "finish_reason": null}]}\n\n', options,
                trace=trace)
            final = None
            for piece, f in gen:
                if f is None:
                    co.add(piece)
                else:
                    final = f
            co.flush()
            self._chunk(self._sse({
                "id": rid, "object": "chat.completion.chunk",
                "created": created, "model": model,
                "choices": [{"index": 0, "delta": {},
                             "finish_reason": final.done_reason}]}))
            self._chunk(b"data: [DONE]\n\n")
            self._end_stream()
        else:
            final = None
            for _p, f in gen:
                if f is not None:
                    final = f
            self._send_json({
                "id": rid, "object": "chat.completion", "created": created,
                "model": model,
                "choices": [{"index": 0,
                             "message": {"role": "assistant",
                                         "content": final.text},
                             "finish_reason": final.done_reason}],
                "usage": {"prompt_tokens": final.prompt_tokens,
                          "completion_tokens": final.generated_tokens,
                          "total_tokens": final.prompt_tokens +
                          final.generated_tokens}})

    def _oai_embeddings(self, body: Dict):
        """OpenAI-compatible embeddings (maps onto LoadedModel.embed)."""
        embs = self._embed_input(body)
        self._send_json({
            "object": "list",
            "model": body.get("model"),
            "data": [{"object": "embedding", "index": i,
                      "embedding": [float(x) for x in e]}
                     for i, e in enumerate(embs)],
            "usage": {"prompt_tokens": 0, "total_tokens": 0},
        })

    def _oai_completions(self, body: Dict):
        model = self._model_arg(body)
        lm = self.manager.require_loaded(model)
        options = {}
        if body.get("max_tokens") is not None:
            options["num_predict"] = body["max_tokens"]
        if body.get("temperature") is not None:
            options["temperature"] = body["temperature"]
        if body.get("stop"):
            options["stop"] = body["stop"]
        final = lm.generate(body.get("prompt", ""),
                            options=self._inject_tenant(options))
        self._send_json({
            "id": f"cmpl-{int(time.time() * 1000)}",
            "object": "text_completion", "created": int(time.time()),
            "model": model,
            "choices": [{"index": 0, "text": final.text,
                         "finish_reason": final.done_reason}],
            "usage": {"prompt_tokens": final.prompt_tokens,
                      "completion_tokens": final.generated_tokens,
                      "total_tokens": final.prompt_tokens +
                      final.generated_tokens}})

    @staticmethod
    def _sse(obj) -> bytes:
        return b"data: " + json.dumps(obj).encode() + b"\n\n"


class _DeepStackHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with a bounded deep-stack worker pool.

    Two departures from stock ThreadingHTTPServer:

    - Worker threads are POOLED and capped (TPU_HTTP_WORKERS, default
      64): stock spawns one thread per connection, so a load-balancer
      health-check storm or slow-reading client fleet grows threads
      without bound, and every spawn pays thread start-up on the request
      path. Workers here are spawned lazily up to the cap and then
      reused; excess connections queue until a worker frees.
    - Workers get a deep (64 MiB) stack: handler threads can run XLA
      compiles (a /api/chat that loads a model warms its buckets on the
      request thread), and LLVM recursion overflows a default stack.
      `threading.stack_size` is process-global, so the bump is scoped to
      the spawn and restored right after. (A thread spawned elsewhere in
      this narrow window also gets the deep stack; that is a virtual
      reservation, not committed memory.)"""

    daemon_threads = True

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._pool_q: queue.Queue = queue.Queue()
        self._pool_lock = threading.Lock()
        self._workers = 0
        self._idle = 0
        self._max_workers = max(
            1, int(os.environ.get("TPU_HTTP_WORKERS", "64") or "64"))

    def _worker(self):
        while True:
            item = self._pool_q.get()
            if item is None:
                return
            with self._pool_lock:
                self._idle -= 1
            request, client_address = item
            try:
                self.finish_request(request, client_address)
            except Exception:  # noqa: BLE001 — mirror ThreadingMixIn
                self.handle_error(request, client_address)
            finally:
                self.shutdown_request(request)
                with self._pool_lock:
                    self._idle += 1

    def process_request(self, request, client_address):
        with self._pool_lock:
            # spawn only when no worker will be free to take this item
            # once the backlog drains, and only below the cap
            if (self._idle - self._pool_q.qsize() <= 0
                    and self._workers < self._max_workers):
                self._workers += 1
                self._idle += 1   # counted idle until it picks up work
                try:
                    old = threading.stack_size(64 << 20)
                except (ValueError, RuntimeError):
                    old = None
                try:
                    threading.Thread(
                        target=self._worker, daemon=True,
                        name=f"http-worker-{self._workers}").start()
                finally:
                    if old is not None:
                        threading.stack_size(old)
        self._pool_q.put((request, client_address))

    def server_close(self):
        super().server_close()
        with self._pool_lock:
            n = self._workers
        for _ in range(n):
            self._pool_q.put(None)


def _hbm_bytes_in_use() -> float:
    """Live accelerator memory on local device 0, via whichever of the
    backend's memory_stats keys exists (TPU reports bytes_in_use; some
    backends report none at all — then this reads 0, and the gauge-error
    counter stays untouched because we return rather than raise)."""
    import jax
    devs = jax.local_devices()
    if not devs:
        return 0.0
    stats = devs[0].memory_stats()
    if not stats:
        return 0.0
    return float(stats.get("bytes_in_use", 0.0))


def serve(manager: ModelManager, host: str = "0.0.0.0", port: int = 11434
          ) -> ThreadingHTTPServer:
    handler = type("BoundHandler", (Handler,), {"manager": manager})
    httpd = _DeepStackHTTPServer((host, port), handler)
    METRICS.gauge_fn("tpu_model_hbm_bytes_in_use", _hbm_bytes_in_use)
    METRICS.gauge_fn("tpu_model_flight_recorder_events",
                     lambda: float(FLIGHT.seq))
    METRICS.gauge_fn("tpu_model_flight_recorder_dumps",
                     lambda: float(FLIGHT.dumps))
    t = threading.Thread(target=httpd.serve_forever, daemon=True,
                         name="http-server")
    t.start()
    return httpd
