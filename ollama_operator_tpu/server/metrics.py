"""Prometheus text-format metrics for the model server.

The reference exposes only controller-runtime metrics on the manager
(/root/reference/cmd/main.go:61,100-104) and has **no model-server metrics at
all** (SURVEY.md §5). These are the serving metrics the BASELINE target is
measured by: output tok/s and TTFT, plus queue/slot gauges. Scraped at
/metrics on the model server, optionally via a ServiceMonitor like the
reference's (deploy/monitor.yaml).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Tuple


class Histogram:
    DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                       5.0, 10.0, 30.0, 60.0)

    def __init__(self, buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)
        self.total = 0.0
        self.n = 0

    def observe(self, v: float):
        self.total += v
        self.n += 1
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def render(self, name: str, labels: str = "") -> List[str]:
        out = []
        cum = 0
        lab = labels[:-1] + "," if labels else "{"
        for b, c in zip(self.buckets, self.counts):
            cum += c
            out.append(f'{name}_bucket{lab}le="{b}"}} {cum}')
        cum += self.counts[-1]
        out.append(f'{name}_bucket{lab}le="+Inf"}} {cum}')
        out.append(f"{name}_sum{labels} {self.total}")
        out.append(f"{name}_count{labels} {self.n}")
        return out


class Metrics:
    """Tiny registry: counters, gauges (callables), histograms."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, str], float] = {}
        self._gauges: Dict[Tuple[str, str], object] = {}
        self._hists: Dict[Tuple[str, str], Histogram] = {}
        self._help: Dict[str, str] = {}

    def _key(self, name, labels):
        return (name, labels)

    def describe(self, name: str, help_: str):
        self._help[name] = help_

    def inc(self, name: str, value: float = 1.0, labels: str = ""):
        with self._lock:
            k = self._key(name, labels)
            self._counters[k] = self._counters.get(k, 0.0) + value

    def get(self, name: str, labels: str = "") -> float:
        """Current value of a counter (0.0 if never incremented)."""
        with self._lock:
            return self._counters.get(self._key(name, labels), 0.0)

    def gauge_fn(self, name: str, fn, labels: str = ""):
        with self._lock:
            self._gauges[self._key(name, labels)] = fn

    def remove_gauge(self, name: str, labels: str = ""):
        with self._lock:
            self._gauges.pop(self._key(name, labels), None)

    def observe(self, name: str, v: float, labels: str = ""):
        with self._lock:
            k = self._key(name, labels)
            if k not in self._hists:
                self._hists[k] = Histogram()
            self._hists[k].observe(v)

    def seed_histogram(self, name: str, labels: str = ""):
        """Materialise an empty histogram so its buckets scrape as 0,
        not absent — the histogram analog of the inc(name, 0.0)
        counter pre-seeds below."""
        with self._lock:
            self._hists.setdefault(self._key(name, labels), Histogram())

    def hist_buckets(self, name: str,
                     labels: str = "") -> Tuple[Tuple[float, ...],
                                                Tuple[int, ...]]:
        """(bucket upper bounds, per-bucket counts incl. the +Inf
        overflow slot) for one histogram — a snapshot callers can delta
        across a measurement window and feed to histogram_quantile-style
        interpolation (bench.py's ITL phases). Empty histogram renders
        as the default buckets with zero counts."""
        with self._lock:
            h = self._hists.get(self._key(name, labels))
            if h is None:
                return (Histogram.DEFAULT_BUCKETS,
                        (0,) * (len(Histogram.DEFAULT_BUCKETS) + 1))
            return h.buckets, tuple(h.counts)

    def hist_totals(self, name: str) -> Tuple[int, float]:
        """(observation count, value sum) aggregated across every label
        set of a histogram — e.g. total device busy-seconds across all
        tpu_model_dispatch_seconds program kinds, for the admission
        queue model's throughput estimate. (0, 0.0) when never observed."""
        with self._lock:
            n, total = 0, 0.0
            for (hname, _labels), h in self._hists.items():
                if hname == name:
                    n += h.n
                    total += h.total
            return n, total

    def render(self) -> str:
        with self._lock:
            # evaluate gauge callables FIRST: a failing one is counted in
            # tpu_model_metrics_gauge_errors_total (a silently-vanishing
            # series is how a dead weakref or a torn-down engine hides
            # from dashboards), and counters render after this pass so
            # the drop is visible in the SAME scrape. Direct dict
            # mutation, NOT self.inc(): the lock is non-reentrant.
            gauge_vals: List[Tuple[str, str, float]] = []
            for (name, labels), fn in sorted(self._gauges.items()):
                try:
                    gauge_vals.append((name, labels, float(fn())))
                except Exception:
                    k = self._key("tpu_model_metrics_gauge_errors_total",
                                  "")
                    self._counters[k] = self._counters.get(k, 0.0) + 1.0
            lines: List[str] = []
            seen = set()

            def header(name, mtype):
                if name not in seen:
                    seen.add(name)
                    if name in self._help:
                        lines.append(f"# HELP {name} {self._help[name]}")
                    lines.append(f"# TYPE {name} {mtype}")

            for (name, labels), v in sorted(self._counters.items()):
                header(name, "counter")
                lines.append(f"{name}{labels} {v}")
            for name, labels, v in gauge_vals:
                header(name, "gauge")
                lines.append(f"{name}{labels} {v}")
            for (name, labels), h in sorted(self._hists.items()):
                header(name, "histogram")
                lines.extend(h.render(name, labels))
            return "\n".join(lines) + "\n"


GLOBAL = Metrics()
GLOBAL.describe("tpu_model_generated_tokens_total",
                "Output tokens generated across all requests")
GLOBAL.describe("tpu_model_prompt_tokens_total", "Prompt tokens prefilled")
GLOBAL.describe("tpu_model_requests_total", "Completed generate requests")
GLOBAL.describe("tpu_model_ttft_seconds", "Time to first token")
GLOBAL.describe("tpu_model_decode_tokens_per_second",
                "Per-request steady-state decode rate")
GLOBAL.describe("tpu_model_active_slots", "Busy decode slots")
GLOBAL.describe("tpu_model_queue_depth", "Requests waiting for a slot")
GLOBAL.describe("tpu_model_kv_free_pages",
                "Free pages in the paged KV pool (paged mode)")
GLOBAL.describe("tpu_model_preemptions_total",
                "Requests preempted and requeued under KV-pool pressure")
GLOBAL.describe("tpu_model_stream_frames_total",
                "Streamed NDJSON/SSE frames written (after coalescing; "
                "compare to tpu_model_generated_tokens_total for the "
                "tokens-per-frame ratio)")
GLOBAL.describe("tpu_model_engine_restarts_total",
                "Supervised in-process engine restarts after decode-loop "
                "failures (no pod restart, no model reload)")
GLOBAL.describe("tpu_model_request_timeouts_total",
                "Requests cut off mid-generation by deadline_ms "
                "(terminal frame finish reason 'timeout')")
GLOBAL.describe("tpu_model_requests_shed_total",
                "Requests shed before holding a slot: deadline expired "
                "while queued, or admission queue full (HTTP 503)")
GLOBAL.describe("tpu_model_followers_lost_total",
                "Multi-host follower connections lost (send failure or "
                "missed heartbeat); the world is degraded afterwards")
GLOBAL.describe("tpu_model_dispatch_ms",
                "Last observed launch-to-tokens-on-host wall-clock per "
                "device program kind (decode chunk, one-shot admit, "
                "extend, speculative verify)")
GLOBAL.describe("tpu_model_admission_stall_ms_total",
                "Wall-clock milliseconds decode dispatches spent stalled "
                "behind admission prefill work (one-shot, batched, and "
                "per chunked-prefill piece); divide by "
                "tpu_model_prefill_chunks_total for ms/piece")
GLOBAL.describe("tpu_model_prefill_chunks_total",
                "Chunked-prefill pieces dispatched (stall-free admission "
                "of long prompts, one bucket-sized piece per scheduler "
                "step)")
GLOBAL.describe("tpu_model_prefix_hit_tokens_total",
                "Prompt tokens served from the prefix cache at admission "
                "(radix page stitch or parked-slot extend) instead of "
                "being prefilled")
GLOBAL.describe("tpu_model_prefix_miss_tokens_total",
                "Prompt tokens actually prefilled at admission; "
                "hit / (hit + miss) is the prefix-cache hit rate")
GLOBAL.describe("tpu_model_radix_nodes",
                "Radix prefix-cache tree nodes resident (one cached "
                "page_size token chunk each)")
GLOBAL.describe("tpu_model_radix_pages",
                "Physical KV pages pinned by the radix prefix cache "
                "(tier-0 nodes; spilled nodes hold host bytes instead)")
GLOBAL.describe("tpu_model_tier_hit_tokens_total",
                "Prompt tokens served from the tiered KV cache at "
                "admission, by serving tier: 0 = HBM-resident radix "
                "pages shared in place, 1 = host-arena pages restitched "
                "by async host-to-HBM copy, 2 = fleet-snapshot pages "
                "restitched after import")
GLOBAL.describe("tpu_model_tier_miss_tokens_total",
                "Prompt tokens prefilled at admission, by missed tier: "
                "0 = never cached (cold), 1/2 = spilled pages the "
                "copy-vs-recompute break-even model chose to recompute "
                "instead of restitch")
GLOBAL.describe("tpu_model_spilled_pages_total",
                "Radix KV pages spilled from HBM to the tier-1 host "
                "arena on LRU eviction (quiescent pages only; a plain "
                "eviction under fence pressure does not count)")
GLOBAL.describe("tpu_model_restitch_seconds",
                "Stitch-call latency histogram for admissions that "
                "restitched at least one host-tier page (enqueue-side: "
                "the host-to-HBM uploads themselves run async, "
                "overlapped with the tail prefill)")
GLOBAL.describe("tpu_model_host_cache_bytes",
                "Tier-1 host arena occupancy in bytes (live gauge; 0 "
                "when TPU_HOST_CACHE_GB is unset)")
GLOBAL.describe("tpu_model_host_cache_pages",
                "Spilled KV pages resident in the tier-1 host arena "
                "(live gauge)")
GLOBAL.describe("tpu_model_async_fallback_total",
                "Decode dispatches that fell back to synchronous while "
                "TPU_ASYNC_DISPATCH was on: per-dispatch for grammar "
                "(host PDA mask between dispatches), once at startup for "
                "paged_dp (dp-sharded page pools stay sync); a "
                "silently-sync deployment shows here. cause=\"spec\" is "
                "retired — fused speculation double-buffers — and kept "
                "pre-seeded at 0 to prove it stays that way")
GLOBAL.describe("tpu_model_spec_drafted_tokens_total",
                "Prompt-lookup draft tokens submitted to fused "
                "speculative verification (TPU_SPEC_DECODE=k); divide "
                "accepted by drafted for the acceptance rate")
GLOBAL.describe("tpu_model_spec_accepted_tokens_total",
                "Draft tokens accepted by speculative verification — "
                "each one is an output token that skipped a decode "
                "dispatch; accepted/drafted below ~0.3 means lookup "
                "misses are paying dispatch overhead for nothing")
GLOBAL.describe("tpu_model_prefix_reused_tokens_total",
                "Prompt tokens served from a parked prefix cache on the "
                "request's FIRST admission (per-request view of the "
                "hit/miss token counters)")
GLOBAL.describe("tpu_model_itl_seconds",
                "Inter-token latency histogram, chunk-normalized: each "
                "delivered decode chunk observes (gap since previous "
                "delivery) / (tokens in chunk) — the per-token cadence "
                "a streaming client actually experiences")
GLOBAL.describe("tpu_model_queue_wait_seconds",
                "Submit-to-first-admission wait histogram (first "
                "admission only; a preempted request's re-admission "
                "does not re-observe). Shed requests observe their "
                "submit-to-shed wait here too — a shed IS the end of "
                "that request's queue wait")
GLOBAL.describe("tpu_model_class_queue_wait_seconds",
                "Queue wait histogram by priority class "
                "(class=high|normal|best_effort): same observation "
                "points as tpu_model_queue_wait_seconds, labelled — "
                "the per-class p99 the overload SLO gates on")
GLOBAL.describe("tpu_model_shed_total",
                "Requests shed before holding a slot, by priority "
                "class and cause (cause=queue_full|deadline|"
                "slo_predict|tenant_cap); class=\"high\" staying 0 "
                "under overload is the admission policy's contract")
GLOBAL.describe("tpu_model_tenant_throttles_total",
                "Mid-stream throttle preemptions of over-rate tenants "
                "(per-tenant decode-token rate limits; best-effort "
                "class only — the request resumes on the same stream "
                "once the token bucket refills)")
GLOBAL.describe("tpu_model_tenant_decode_tokens_total",
                "Decode tokens delivered per tenant "
                "(tenant=\"default\" is the no-key bucket) — the "
                "series behind WDRR fairness dashboards")
GLOBAL.describe("tpu_model_dispatch_seconds",
                "Device dispatch latency histogram by program kind "
                "(kind=decode|admit|extend|spec): launch to tokens on "
                "host — the distribution behind the last-value "
                "tpu_model_dispatch_ms gauges")
GLOBAL.describe("tpu_model_metrics_gauge_errors_total",
                "Gauge callables that raised during /metrics render; a "
                "nonzero rate means a series is silently missing from "
                "scrapes (dead weakref, torn-down engine)")
GLOBAL.describe("tpu_model_hbm_bytes_in_use",
                "Accelerator memory in use on local device 0 "
                "(jax memory_stats; 0 when the backend reports none)")
GLOBAL.describe("tpu_model_flight_recorder_events",
                "Structured events recorded into the flight-recorder "
                "ring so far (runtime/trace.py); the ring keeps only "
                "the last TPU_FLIGHT_EVENTS of them")
GLOBAL.describe("tpu_model_flight_recorder_dumps",
                "Flight-recorder dumps written to stderr (supervised "
                "restarts and chaos-drill post-mortems)")
GLOBAL.describe("tpu_model_replayed_requests_total",
                "In-flight streams recovered across a supervised engine "
                "restart by replay (re-prefill of prompt+generated, "
                "bit-identical continuation on the same stream) instead "
                "of an error frame")
GLOBAL.describe("tpu_model_replayed_tokens_total",
                "Prompt+generated tokens re-prefilled by restart "
                "replay; bounded per restart by "
                "TPU_RESTART_REPLAY_TOKENS")
GLOBAL.describe("tpu_model_replay_fallback_total",
                "In-flight streams that could NOT be replayed across a "
                "restart and got the exactly-once error instead, by "
                "cause (cause=nondeterministic|multimodal|over_budget|"
                "faulted|broken)")
GLOBAL.describe("tpu_model_drain_started_total",
                "Graceful-drain activations (SIGTERM / preStop): new "
                "submits shed 503 while running streams finish")
GLOBAL.describe("tpu_model_drain_shed_total",
                "Requests shed by graceful drain: new submits refused "
                "while draining, plus stragglers cut at "
                "TPU_DRAIN_TIMEOUT_S")
GLOBAL.describe("tpu_model_watchdog_fires_total",
                "Hung-dispatch watchdog fires (dispatch wait exceeded "
                "TPU_DISPATCH_WATCHDOG_MS or the histogram-derived "
                "ceiling); each one forces a supervised restart + "
                "replay")
GLOBAL.describe("tpu_model_recompiles_total",
                "Mid-serving XLA compiles, by program kind (kind=decode|"
                "admit|admit_many|extend|spec): an executable-cache miss "
                "OUTSIDE warm_buckets, paid inside a timed dispatch. "
                "Nonzero after warmup means the warm plan missed a "
                "signature (the BENCH_r05 623ms spec-dispatch incident "
                "as a counter)")
GLOBAL.describe("tpu_model_useful_tokens_total",
                "Useful token positions computed per dispatch kind "
                "(kind=decode|prefill|spec): active slots' steps, real "
                "prompt positions, emitted speculative tokens — the "
                "goodput numerator (runtime/accounting.py)")
GLOBAL.describe("tpu_model_padded_tokens_total",
                "Padding-waste token positions per dispatch kind: empty "
                "batch slots x steps, prefill bucket positions past the "
                "prompt chunk, rejected speculative drafts — the waste "
                "half of the goodput split")
GLOBAL.describe("tpu_model_model_flops_total",
                "Analytic model FLOPs issued for active slots (matmul "
                "terms only, MFU convention of Chowdhery et al.); rate() "
                "over this / peak = MFU over any window")
GLOBAL.describe("tpu_model_breakdown_seconds_total",
                "Scheduler wall-clock classified by phase "
                "(phase=dispatch_wait|host|idle): where the serving "
                "thread's time goes between device programs")
GLOBAL.describe("tpu_model_mfu",
                "Achieved model-FLOPs utilization vs device peak over "
                "the last 60s (0..1; 0 when no peak is known — CPU "
                "without TPU_PEAK_FLOPS)")
GLOBAL.describe("tpu_model_occupancy",
                "Useful fraction of issued token positions over the "
                "last 60s (active slots / padded grid, Orca-style "
                "continuous-batching efficiency)")
GLOBAL.describe("tpu_model_goodput_tokens_per_second",
                "Useful tokens per second over the last 60s (decode + "
                "prefill + accepted speculative)")
GLOBAL.describe("tpu_model_padding_waste_pct",
                "Percent of issued token positions that were padding "
                "over the last 60s (100 - 100*occupancy)")
GLOBAL.describe("tpu_model_autoscale_decisions_total",
                "Autoscaler scale actions taken, by action "
                "(action=up|down|to_zero|wake): each is one damped "
                "single-step move of the desired replica count "
                "(operator/autoscale.py)")
GLOBAL.describe("tpu_model_autoscale_holds_total",
                "Autoscaler passes that held the last decision instead "
                "of scaling, by cause (cause=no_data|stale|flap|"
                "cooldown): no_data/stale are the fail-static guard — "
                "a missing or stale replica scrape must never produce "
                "a scale action")
GLOBAL.describe("tpu_model_remediation_replacements_total",
                "Broken replicas replaced by the operator, by cause "
                "(cause=unreachable|crash_loop): the pod is deleted and "
                "the ReplicaSet recreates it — the fleet never shrinks "
                "below minReplicas")
GLOBAL.describe("tpu_model_remediation_backoff_holds_total",
                "Remediation opportunities skipped because the "
                "exponential replacement backoff was still closed "
                "(doubles per replacement up to the cap; resets on a "
                "clean scrape pass)")
GLOBAL.describe("tpu_model_warm_snapshot_saves_total",
                "AOT warm-bucket executable cache snapshots persisted "
                "to the image-store PVC at drain time (scale-to-zero "
                "fast cold-start)")
GLOBAL.describe("tpu_model_scrape_failures_total",
                "Replica /api/ps scrapes the operator lost, by cause "
                "(cause=fault|http|network|parse): each one is a hole "
                "in the autoscaler's evidence — correlate with "
                "tpu_model_autoscale_holds_total{cause=\"no_data\"} to "
                "attribute fail-static holds (operator/client.py)")
GLOBAL.describe("tpu_model_gateway_routes_total",
                "Gateway routing decisions by resolution path "
                "(path=affinity|probe|least_loaded): affinity = "
                "prefix-hash table hit, probe = /api/prefix_probe "
                "scatter won, least_loaded = no cache evidence "
                "(operator/gateway.py)")
GLOBAL.describe("tpu_model_gateway_failovers_total",
                "Streams the gateway moved off a dead replica, by "
                "outcome (result=replayed|requeued|errored): replayed = "
                "mid-stream continuation on a healthy replica (zero "
                "client error frames), requeued = unstarted request "
                "re-dispatched, errored = non-replayable stream given "
                "the exactly-once error with Retry-After")
GLOBAL.describe("tpu_model_gateway_ejections_total",
                "Replica circuits opened by the gateway health state "
                "machine, by trigger (cause=failures|slow|not_ready)")
GLOBAL.describe("tpu_model_gateway_half_open_probes_total",
                "Half-open circuit probe requests admitted (exactly one "
                "per eject window), by outcome (result=ok|fail)")
GLOBAL.describe("tpu_model_gateway_replicas",
                "Replicas the gateway currently tracks in each health "
                "state (state=probe|healthy|ejected|half_open|draining) "
                "— the circuit-state view of the fleet")
GLOBAL.describe("tpu_model_warm_snapshot_restores_total",
                "Engine warm-ups served from a persisted warm snapshot "
                "instead of a from-scratch warm_buckets compile pass — "
                "a woken replica's first request must not trip "
                "tpu_model_recompiles_total")
GLOBAL.describe("tpu_model_gateway_persist_writes_total",
                "Journal/affinity snapshot records appended to the "
                "gateway's crash-recovery log (TPU_GATEWAY_PERSIST), "
                "fsync batched per flush window")
GLOBAL.describe("tpu_model_gateway_persist_restores_total",
                "Journaled streams restored from the persist log at "
                "gateway restart (each is a request a reconnecting "
                "client can splice byte-identically)")
GLOBAL.describe("tpu_model_gateway_drain_total",
                "Gateway graceful-drain activations (SIGTERM / preStop): "
                "stop accepting, finish proxied streams, persist, exit")
GLOBAL.describe("tpu_model_follower_lag_seconds",
                "Slowest follower's broadcast send lag over the control "
                "plane (bounded by TPU_CP_SEND_TIMEOUT_S — a follower "
                "that exceeds the bound is declared dead, not slow)")
GLOBAL.describe("tpu_model_leader_lost_total",
                "Follower exits after a silent leader (no control-stream "
                "traffic, heartbeats included, for longer than "
                "TPU_CP_LEADER_TIMEOUT_S): fail-static clean exit "
                "instead of hanging on the broadcast socket")
GLOBAL.describe("tpu_model_chaos_events_total",
                "Randomized chaos-campaign fault events injected, by "
                "fault point (runtime/chaos.py; the label set is the "
                "full FAULTS catalog)")
GLOBAL.describe("tpu_model_disagg_handoffs_total",
                "Disaggregated prefill->decode handoffs at the gateway, "
                "by outcome (result=transferred|replayed|"
                "unified_fallback): transferred = KV pages moved and the "
                "decode pool continued the stream, replayed = transfer "
                "failed and the journal replay path re-prefilled on "
                "decode, unified_fallback = no decode replica routable "
                "so the request served unified — every rung is "
                "bit-identical to the client (ISSUE 20)")
GLOBAL.describe("tpu_model_kv_transfer_pages_total",
                "KV pages imported over replica-to-replica transfer "
                "(/api/kv_import pull from the prefill replica)")
GLOBAL.describe("tpu_model_kv_transfer_bytes_total",
                "Wire bytes of KV page payload imported over "
                "replica-to-replica transfer (pre-decode, i.e. the "
                "kv_wire blob size; bounded per-export by "
                "TPU_DISAGG_TRANSFER_MB_S pacing)")
GLOBAL.describe("tpu_model_kv_transfer_seconds",
                "End-to-end KV transfer latency histogram per handoff "
                "(decode-side: pull from prefill + upload + radix "
                "graft); only transfers that imported >0 pages observe")
GLOBAL.describe("tpu_model_disagg_pool_replicas",
                "Replicas the gateway tracks per disagg pool "
                "(pool=unified|prefill|decode); unified fleets read "
                "everything under pool=\"unified\"")
# pre-seed the failure counters at 0: alert rules rate() over these, and
# a series that first appears AT the first failure hides that failure
# (the stall/chunk counters likewise: a mixed-load dashboard must read 0,
# not absent, on an idle server)
for _name in ("tpu_model_engine_restarts_total",
              "tpu_model_request_timeouts_total",
              "tpu_model_requests_shed_total",
              "tpu_model_followers_lost_total",
              "tpu_model_admission_stall_ms_total",
              "tpu_model_prefill_chunks_total",
              "tpu_model_prefix_hit_tokens_total",
              "tpu_model_prefix_miss_tokens_total",
              "tpu_model_spilled_pages_total",
              "tpu_model_spec_drafted_tokens_total",
              "tpu_model_spec_accepted_tokens_total",
              # traffic counters: an idle (or freshly-restarted) server
              # must scrape 0, not absent — a dashboard rate() over an
              # absent series renders "no data" exactly when someone is
              # checking whether the server serves at all
              "tpu_model_preemptions_total",
              "tpu_model_requests_total",
              "tpu_model_generated_tokens_total",
              "tpu_model_prompt_tokens_total",
              "tpu_model_stream_frames_total",
              "tpu_model_prefix_reused_tokens_total",
              # lifecycle counters (restart replay / drain / watchdog):
              # the whole point is alerting on rare events, so the
              # series must exist from the first scrape
              "tpu_model_replayed_requests_total",
              "tpu_model_replayed_tokens_total",
              "tpu_model_drain_started_total",
              "tpu_model_drain_shed_total",
              "tpu_model_watchdog_fires_total",
              # render() itself maintains this one; pre-seeded so the
              # zero-error steady state is a visible 0
              "tpu_model_metrics_gauge_errors_total"):
    GLOBAL.inc(_name, 0.0)
# replay fallbacks are labelled by cause; pre-seed every cause so a
# rate() alert on any of them reads 0, not absent, on a healthy server
for _cause in ("nondeterministic", "multimodal", "over_budget",
               "faulted", "broken"):
    GLOBAL.inc("tpu_model_replay_fallback_total", 0.0,
               f'{{cause="{_cause}"}}')
# the async-fallback counter is labelled, so pre-seed every cause — an
# alert on rate(cause="grammar") must read 0, not absent, while async
# dispatch is running clean
# tiered KV cache: the full 3-tier hit/miss matrix must read 0, not
# absent, before the first admission — the churn dashboards compute
# per-tier hit rates from these from the very first scrape
for _tier in ("0", "1", "2"):
    GLOBAL.inc("tpu_model_tier_hit_tokens_total", 0.0,
               f'{{tier="{_tier}"}}')
    GLOBAL.inc("tpu_model_tier_miss_tokens_total", 0.0,
               f'{{tier="{_tier}"}}')
# the restitch histogram likewise: a latency dashboard over a server
# that has never restitched must read empty buckets, not "no data"
GLOBAL.seed_histogram("tpu_model_restitch_seconds")
for _cause in ("grammar", "spec", "paged_dp"):
    GLOBAL.inc("tpu_model_async_fallback_total", 0.0,
               f'{{cause="{_cause}"}}')
# admission-control counters: every class × cause combination pre-seeded
# so overload alert rules (and the tpu_model_shed_total{class="high"}==0
# invariant check) read 0, not absent, on a healthy server. Label keys
# are rendered in sorted order (class before cause) — reads via
# METRICS.get must use the identical string (admission.shed_labels)
for _class in ("high", "normal", "best_effort"):
    for _cause in ("queue_full", "deadline", "slo_predict", "tenant_cap"):
        GLOBAL.inc("tpu_model_shed_total", 0.0,
                   f'{{class="{_class}",cause="{_cause}"}}')
GLOBAL.inc("tpu_model_tenant_throttles_total", 0.0,
           '{class="best_effort",tenant="default"}')
GLOBAL.inc("tpu_model_tenant_decode_tokens_total", 0.0,
           '{tenant="default"}')
# utilization accounting (runtime/accounting.py): the recompile alert and
# the goodput/waste dashboards must read 0, not absent, from the first
# scrape — a recompile series that first appears AT the first mid-serving
# compile hides exactly the event it exists to expose
for _kind in ("decode", "admit", "admit_many", "extend", "spec"):
    GLOBAL.inc("tpu_model_recompiles_total", 0.0, f'{{kind="{_kind}"}}')
for _kind in ("decode", "prefill", "spec"):
    GLOBAL.inc("tpu_model_useful_tokens_total", 0.0, f'{{kind="{_kind}"}}')
    GLOBAL.inc("tpu_model_padded_tokens_total", 0.0, f'{{kind="{_kind}"}}')
GLOBAL.inc("tpu_model_model_flops_total", 0.0)
for _phase in ("dispatch_wait", "host", "idle"):
    GLOBAL.inc("tpu_model_breakdown_seconds_total", 0.0,
               f'{{phase="{_phase}"}}')
# closed-loop fleet control (operator/autoscale.py): scale decisions,
# fail-static holds, and remediation are exactly the rare events alert
# rules watch — every labelled combination pre-seeded so rate() reads 0,
# not absent, on a fleet that has never scaled or broken
for _action in ("up", "down", "to_zero", "wake"):
    GLOBAL.inc("tpu_model_autoscale_decisions_total", 0.0,
               f'{{action="{_action}"}}')
for _cause in ("no_data", "stale", "flap", "cooldown"):
    GLOBAL.inc("tpu_model_autoscale_holds_total", 0.0,
               f'{{cause="{_cause}"}}')
for _cause in ("unreachable", "crash_loop"):
    GLOBAL.inc("tpu_model_remediation_replacements_total", 0.0,
               f'{{cause="{_cause}"}}')
GLOBAL.inc("tpu_model_remediation_backoff_holds_total", 0.0)
GLOBAL.inc("tpu_model_warm_snapshot_saves_total", 0.0)
GLOBAL.inc("tpu_model_warm_snapshot_restores_total", 0.0)
# fleet gateway (operator/gateway.py) + scrape attribution: failovers and
# circuit ejections are the rare events the fleet dashboards alert on, so
# every labelled combination must read 0, not absent, before the first
# replica ever misbehaves
for _cause in ("fault", "http", "network", "parse"):
    GLOBAL.inc("tpu_model_scrape_failures_total", 0.0,
               f'{{cause="{_cause}"}}')
for _path in ("affinity", "probe", "least_loaded"):
    GLOBAL.inc("tpu_model_gateway_routes_total", 0.0,
               f'{{path="{_path}"}}')
for _result in ("replayed", "requeued", "errored"):
    GLOBAL.inc("tpu_model_gateway_failovers_total", 0.0,
               f'{{result="{_result}"}}')
for _cause in ("failures", "slow", "not_ready"):
    GLOBAL.inc("tpu_model_gateway_ejections_total", 0.0,
               f'{{cause="{_cause}"}}')
for _result in ("ok", "fail"):
    GLOBAL.inc("tpu_model_gateway_half_open_probes_total", 0.0,
               f'{{result="{_result}"}}')
# gateway crash recovery + multi-host partition tolerance: rare-event
# counters the robustness dashboards alert on — all visible as 0 from
# the first scrape (tpu_model_follower_lag_seconds is a live gauge the
# control plane registers, not a counter)
GLOBAL.inc("tpu_model_gateway_persist_writes_total", 0.0)
GLOBAL.inc("tpu_model_gateway_persist_restores_total", 0.0)
GLOBAL.inc("tpu_model_gateway_drain_total", 0.0)
GLOBAL.inc("tpu_model_leader_lost_total", 0.0)
# disaggregated serving (ISSUE 20): every handoff rung pre-seeded — the
# acceptance dashboards alert on replayed/unified_fallback rates, and a
# fleet that has never handed off must read 0, not absent
for _result in ("transferred", "replayed", "unified_fallback"):
    GLOBAL.inc("tpu_model_disagg_handoffs_total", 0.0,
               f'{{result="{_result}"}}')
GLOBAL.inc("tpu_model_kv_transfer_pages_total", 0.0)
GLOBAL.inc("tpu_model_kv_transfer_bytes_total", 0.0)
GLOBAL.seed_histogram("tpu_model_kv_transfer_seconds")
# chaos-campaign event counter: one series per registered fault point
# (this literal list mirrors runtime/faults.py CATALOG; test_faults
# asserts the two stay in sync)
for _point in ("admission.predict", "detok.feed", "engine.admit",
               "engine.step", "engine.watchdog", "follower.send",
               "gateway.handoff", "gateway.route", "gateway.stream",
               "kube.request", "operator.scrape", "pages.alloc",
               "pages.export", "pages.import", "pages.restitch",
               "pages.spill", "scheduler.replay"):
    GLOBAL.inc("tpu_model_chaos_events_total", 0.0,
               f'{{point="{_point}"}}')


class Stopwatch:
    def __init__(self):
        self.t0 = time.monotonic()

    def elapsed(self):
        return time.monotonic() - self.t0
