"""Modelfile parsing (the ollama model-definition DSL).

The reference consumes Modelfiles implicitly via model images
(/root/reference/README.md model table; SURVEY.md §2.2). Model images carry
the rendered layers (template/system/params); this parser also accepts the
textual Modelfile for /api/create. Supported commands: FROM, PARAMETER,
TEMPLATE, SYSTEM, LICENSE, ADAPTER, MESSAGE — values may be single-line or
triple-quoted blocks.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class Modelfile:
    from_: str = ""
    parameters: Dict[str, object] = dataclasses.field(default_factory=dict)
    template: Optional[str] = None
    system: Optional[str] = None
    license: Optional[str] = None
    adapter: Optional[str] = None
    messages: List[Tuple[str, str]] = dataclasses.field(default_factory=list)

    def render(self) -> str:
        out = [f"FROM {self.from_}"]
        for k, v in self.parameters.items():
            vs = v if not isinstance(v, list) else v
            if isinstance(vs, list):
                for item in vs:
                    out.append(f"PARAMETER {k} {item}")
            else:
                out.append(f"PARAMETER {k} {vs}")
        if self.template:
            out.append(f'TEMPLATE """{self.template}"""')
        if self.system:
            out.append(f'SYSTEM """{self.system}"""')
        if self.adapter:
            out.append(f"ADAPTER {self.adapter}")
        if self.license:
            out.append(f'LICENSE """{self.license}"""')
        return "\n".join(out) + "\n"


# parameter name → parser; repeatable params accumulate into lists
_NUM_PARAMS = {
    "temperature": float, "top_p": float, "min_p": float,
    "repeat_penalty": float, "presence_penalty": float,
    "frequency_penalty": float, "top_k": int, "seed": int,
    "num_ctx": int, "num_predict": int, "repeat_last_n": int,
    "num_keep": int, "num_gpu": int, "num_thread": int,
    "mirostat": int, "mirostat_eta": float, "mirostat_tau": float,
    "tfs_z": float, "typical_p": float,
}
_REPEATABLE = {"stop"}


def parse_parameter(key: str, raw: str):
    key = key.lower()
    if key in _NUM_PARAMS:
        return key, _NUM_PARAMS[key](raw)
    if raw.lower() in ("true", "false"):
        return key, raw.lower() == "true"
    if len(raw) >= 2 and raw[0] == raw[-1] and raw[0] in "\"'":
        raw = raw[1:-1]
    return key, raw


def parse_modelfile(text: str) -> Modelfile:
    mf = Modelfile()
    lines = text.splitlines()
    i = 0

    def read_value(first: str) -> str:
        nonlocal i
        v = first.strip()
        for quote in ('"""', "'''"):
            if v.startswith(quote):
                rest = v[len(quote):]
                if rest.endswith(quote) and len(rest) >= len(quote):
                    return rest[:-len(quote)]
                parts = [rest] if rest else []
                while i < len(lines):
                    ln = lines[i]
                    i += 1
                    if ln.rstrip().endswith(quote):
                        parts.append(ln.rstrip()[:-len(quote)])
                        return "\n".join(parts)
                    parts.append(ln)
                return "\n".join(parts)
        if len(v) >= 2 and v[0] == v[-1] and v[0] in "\"'":
            return v[1:-1]
        return v

    while i < len(lines):
        line = lines[i]
        i += 1
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        cmd, _, rest = stripped.partition(" ")
        cmd = cmd.upper()
        if cmd == "FROM":
            mf.from_ = rest.strip()
        elif cmd == "PARAMETER":
            key, _, raw = rest.strip().partition(" ")
            k, v = parse_parameter(key, raw.strip())
            if k in _REPEATABLE:
                mf.parameters.setdefault(k, [])
                mf.parameters[k].append(v)
            else:
                mf.parameters[k] = v
        elif cmd == "TEMPLATE":
            mf.template = read_value(rest)
        elif cmd == "SYSTEM":
            mf.system = read_value(rest)
        elif cmd == "LICENSE":
            mf.license = read_value(rest)
        elif cmd == "ADAPTER":
            mf.adapter = rest.strip()
        elif cmd == "MESSAGE":
            role, _, content = rest.strip().partition(" ")
            mf.messages.append((role, read_value(content)))
        # unknown commands are ignored (forward compatibility)
    return mf


def params_json(mf: Modelfile) -> str:
    """The params layer content (application/vnd.ollama.image.params)."""
    return json.dumps(mf.parameters, sort_keys=True)
