"""Model name parsing: [registry/][namespace/]name[:tag].

Same resolution rules the ollama CLI applies to the reference's
`spec.image` field (/root/reference/api/v1/model_types.go:47-53, README
model table): bare names default to registry.ollama.ai/library/<name>:latest.
"""

from __future__ import annotations

import dataclasses

DEFAULT_REGISTRY = "registry.ollama.ai"
DEFAULT_NAMESPACE = "library"
DEFAULT_TAG = "latest"


@dataclasses.dataclass(frozen=True)
class ModelName:
    registry: str = DEFAULT_REGISTRY
    namespace: str = DEFAULT_NAMESPACE
    name: str = ""
    tag: str = DEFAULT_TAG

    @staticmethod
    def parse(s: str) -> "ModelName":
        s = s.strip()
        scheme = ""
        if s.startswith("http://") or s.startswith("https://"):
            scheme, s = s.split("://", 1)
        tag = DEFAULT_TAG
        if ":" in s.rsplit("/", 1)[-1]:
            s, tag = s.rsplit(":", 1)
        parts = s.split("/")
        if len(parts) == 1:
            reg, ns, name = DEFAULT_REGISTRY, DEFAULT_NAMESPACE, parts[0]
        elif len(parts) == 2:
            reg, ns, name = DEFAULT_REGISTRY, parts[0], parts[1]
        else:
            reg, ns, name = parts[0], "/".join(parts[1:-1]), parts[-1]
        if scheme:
            reg = f"{scheme}://{reg}"
        return ModelName(reg, ns, name, tag)

    @property
    def short(self) -> str:
        """Display form: drops default registry/namespace."""
        base = self.name
        if self.namespace != DEFAULT_NAMESPACE:
            base = f"{self.namespace}/{base}"
        if self.registry != DEFAULT_REGISTRY:
            base = f"{self.registry}/{base}"
        return f"{base}:{self.tag}"

    @property
    def registry_host(self) -> str:
        return self.registry.split("://", 1)[-1]

    @property
    def base_url(self) -> str:
        if "://" in self.registry:
            return self.registry
        return f"https://{self.registry}"

    def manifest_url(self) -> str:
        return (f"{self.base_url}/v2/{self.namespace}/{self.name}"
                f"/manifests/{self.tag}")

    def blob_url(self, digest: str) -> str:
        return f"{self.base_url}/v2/{self.namespace}/{self.name}/blobs/{digest}"
