"""`python -m ollama_operator_tpu.server.pull <model>` — init-container pull.

The reference's puller init container runs `ollama pull <image>` with
OLLAMA_HOST pointed at the shared store Service
(/root/reference/pkg/model/pod.go:68-83), so the *store* server downloads
into the shared PVC and the model pod starts only once the blobs exist.
This is the same client: POST /api/pull to $OLLAMA_HOST, stream NDJSON
progress to stdout, exit non-zero on error so the init container restarts.
"""

from __future__ import annotations

import json
import os
import sys
import time
import urllib.error
import urllib.request


def resolve_host(raw: str) -> str:
    raw = raw or "127.0.0.1:11434"
    if "://" not in raw:
        raw = "http://" + raw
    if raw.count(":") < 2:  # no explicit port after scheme
        raw = raw + ":11434"
    return raw.rstrip("/")


def pull(model: str, host: str, retries: int = 1080,
         retry_delay: float = 5.0) -> int:
    """Pull with retry-until-store-up: the init container may start before
    the store StatefulSet is Ready (the reference tolerates this the same
    way — `ollama pull` fails and the init container restarts; we retry
    in-process to keep restart counts clean)."""
    url = f"{resolve_host(host)}/api/pull"
    body = json.dumps({"model": model, "stream": True}).encode()
    attempt = 0
    while True:
        attempt += 1
        try:
            req = urllib.request.Request(
                url, data=body, headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=24 * 3600) as resp:
                ok = False
                for line in resp:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        evt = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    print(json.dumps(evt), flush=True)
                    if evt.get("error"):
                        print(f"pull failed: {evt['error']}", file=sys.stderr)
                        return 1
                    if evt.get("status") == "success":
                        ok = True
                return 0 if ok else 1
        except urllib.error.HTTPError as e:
            # a definitive HTTP response is not "store unreachable": 4xx is
            # a permanent error (bad model ref) — exit so the failure shows
            # up in pod status; 5xx may be store startup/backpressure
            if e.code < 500:
                print(f"pull failed: HTTP {e.code}: "
                      f"{e.read().decode(errors='replace')[:500]}",
                      file=sys.stderr)
                return 1
            if attempt >= retries:
                print(f"pull: giving up after {attempt} attempts: {e}",
                      file=sys.stderr)
                return 1
            print(f"pull: store returned {e.code}; retry {attempt} in "
                  f"{retry_delay:.0f}s", file=sys.stderr)
            time.sleep(retry_delay)
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as e:
            if attempt >= retries:
                print(f"pull: giving up after {attempt} attempts: {e}",
                      file=sys.stderr)
                return 1
            print(f"pull: store not reachable ({e}); retry {attempt} in "
                  f"{retry_delay:.0f}s", file=sys.stderr)
            time.sleep(retry_delay)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m ollama_operator_tpu.server.pull <model>",
              file=sys.stderr)
        return 2
    return pull(argv[0], os.environ.get("OLLAMA_HOST", ""))


if __name__ == "__main__":
    sys.exit(main())
