"""Ollama registry client + local model store.

Re-provides what the reference delegates to `ollama pull` run against the
shared store server (/root/reference/pkg/model/pod.go:68-83 — the puller
init-container; docs/pages/en/references/architectural-design.md explains
the store exists because model images are OCI manifests with non-runnable
contentTypes). This client speaks that protocol natively:

  GET  /v2/<ns>/<name>/manifests/<tag>   (docker manifest v2 JSON)
  GET  /v2/<ns>/<name>/blobs/<digest>    (content-addressed layers)

Layer mediaTypes: application/vnd.ollama.image.{model,template,system,
params,license,adapter} — the model layer is the GGUF file.

On-disk layout mirrors ollama's so the cache semantics match the reference's
shared PVC (pull once, every replica mmap-shares):

  <root>/blobs/sha256-<hex>
  <root>/manifests/<registry>/<ns>/<name>/<tag>

Downloads stream to a unique .partial file and are verified against the
digest before being atomically published; interrupted pulls resume via HTTP
Range.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from .names import ModelName

MT_MODEL = "application/vnd.ollama.image.model"
MT_TEMPLATE = "application/vnd.ollama.image.template"
MT_SYSTEM = "application/vnd.ollama.image.system"
MT_PARAMS = "application/vnd.ollama.image.params"
MT_LICENSE = "application/vnd.ollama.image.license"
MT_ADAPTER = "application/vnd.ollama.image.adapter"
MT_PROJECTOR = "application/vnd.ollama.image.projector"
MANIFEST_MT = "application/vnd.docker.distribution.manifest.v2+json"
MANIFEST_ACCEPT = ("application/vnd.docker.distribution.manifest.v2+json, "
                   "application/vnd.oci.image.manifest.v1+json")

# (status, completed, total, digest=None) — digest set on blob progress so
# clients (the ollama CLI keys per-layer progress bars on it) can track layers
ProgressCb = Callable[..., None]


class RegistryError(RuntimeError):
    pass


_HEX64 = re.compile(r"[0-9a-f]{64}\Z")


def valid_blob_digest(digest: str) -> bool:
    """True iff ``digest`` is ``sha256:`` + 64 lowercase hex chars.

    Must be checked before any filesystem access derived from a
    client-supplied digest: `blob_path` joins the digest into a path, so a
    64-char digest containing ``/../`` would otherwise escape the blobs
    dir (upstream ollama enforces the same pattern)."""
    algo, _, hexd = digest.partition(":")
    return algo == "sha256" and _HEX64.match(hexd) is not None


class ModelStore:
    """Local content-addressed store of model blobs + manifests."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(os.path.join(root, "blobs"), exist_ok=True)
        os.makedirs(os.path.join(root, "manifests"), exist_ok=True)

    # -- paths ------------------------------------------------------------
    def blob_path(self, digest: str) -> str:
        return os.path.join(self.root, "blobs", digest.replace(":", "-"))

    def manifest_path(self, name: ModelName) -> str:
        return os.path.join(self.root, "manifests", name.registry_host,
                            name.namespace, name.name, name.tag)

    def has_blob(self, digest: str) -> bool:
        return os.path.exists(self.blob_path(digest))

    # -- manifests --------------------------------------------------------
    def write_manifest(self, name: ModelName, manifest: dict):
        path = self.manifest_path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, path)

    def read_manifest(self, name: ModelName) -> Optional[dict]:
        try:
            with open(self.manifest_path(name)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def delete_model(self, name: ModelName) -> bool:
        path = self.manifest_path(name)
        if not os.path.exists(path):
            return False
        os.remove(path)
        self.gc()
        return True

    def list_models(self) -> List[dict]:
        out = []
        mroot = os.path.join(self.root, "manifests")
        for dirpath, _dirs, files in os.walk(mroot):
            for tag in files:
                if tag.startswith("."):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, tag), mroot)
                parts = rel.split(os.sep)
                if len(parts) < 4:
                    continue
                # registry / <namespace…> / name / tag — the namespace may
                # span several path segments
                reg, ns, nm, tg = (parts[0], "/".join(parts[1:-2]),
                                   parts[-2], parts[-1])
                name = ModelName(reg, ns, nm, tg)
                try:
                    with open(os.path.join(dirpath, tag)) as f:
                        manifest = json.load(f)
                except (OSError, json.JSONDecodeError):
                    continue
                size = sum(l.get("size", 0)
                           for l in manifest.get("layers", []))
                out.append({"name": name, "manifest": manifest,
                            "size": size,
                            "modified_at": os.path.getmtime(
                                os.path.join(dirpath, tag))})
        return out

    def gc(self):
        """Delete blobs referenced by no manifest (ollama's prune)."""
        referenced = set()
        for m in self.list_models():
            cfg = m["manifest"].get("config", {})
            if cfg.get("digest"):
                referenced.add(cfg["digest"].replace(":", "-"))
            for layer in m["manifest"].get("layers", []):
                referenced.add(layer["digest"].replace(":", "-"))
        bdir = os.path.join(self.root, "blobs")
        now = time.time()
        for b in os.listdir(bdir):
            p = os.path.join(bdir, b)
            if ".partial" in b:
                # abandoned downloads (live writers keep mtime fresh)
                try:
                    if now - os.path.getmtime(p) >= 3600:
                        os.remove(p)
                except OSError:
                    pass
            elif b not in referenced:
                os.remove(p)

    # -- model assembly ---------------------------------------------------
    def model_layers(self, name: ModelName) -> Dict[str, str]:
        """mediaType → blob path for a pulled model."""
        manifest = self.read_manifest(name)
        if manifest is None:
            raise RegistryError(f"model {name.short} not found locally")
        out = {}
        for layer in manifest.get("layers", []):
            out[layer["mediaType"]] = self.blob_path(layer["digest"])
        return out

    def model_digest(self, name: ModelName, media_type: str = MT_MODEL
                     ) -> Optional[str]:
        manifest = self.read_manifest(name)
        if manifest is None:
            return None
        for layer in manifest.get("layers", []):
            if layer["mediaType"] == media_type:
                return layer["digest"]
        return None

    # -- local create (for /api/create without a registry) ---------------
    def add_blob(self, data: bytes) -> dict:
        digest = "sha256:" + hashlib.sha256(data).hexdigest()
        path = self.blob_path(digest)
        if not os.path.exists(path):
            tmp = path + f".partial.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return {"digest": digest, "size": len(data)}

    def put_blob_stream(self, digest: str, fileobj, length: int) -> dict:
        """Client blob upload (POST /api/blobs/<digest>): stream ``length``
        bytes to the content-addressed path, verifying the declared sha256
        on the way — a mismatch leaves no partial file behind. Matches the
        upload half of `ollama create`'s CLI flow (the reference serves it
        via the stock ollama image, /root/reference/pkg/model/pod.go:11)."""
        if not valid_blob_digest(digest):
            raise RegistryError(f"unsupported digest {digest!r}")
        hexd = digest.partition(":")[2]
        path = self.blob_path(digest)
        if os.path.exists(path):
            # content-addressed: identical bytes already present — drain
            # the body so the connection stays usable
            remaining = length
            while remaining > 0:
                chunk = fileobj.read(min(1 << 20, remaining))
                if not chunk:
                    raise RegistryError("short blob body")
                remaining -= len(chunk)
            return {"digest": digest, "size": length}
        h = hashlib.sha256()
        size = 0
        # unique per upload: the server is threaded, so two concurrent
        # uploads of the same digest must not share one tmp inode
        tmp = path + f".partial.{os.getpid()}.{threading.get_ident()}"
        try:
            with open(tmp, "wb") as f:
                remaining = length
                while remaining > 0:
                    chunk = fileobj.read(min(1 << 20, remaining))
                    if not chunk:
                        raise RegistryError("short blob body")
                    h.update(chunk)
                    f.write(chunk)
                    size += len(chunk)
                    remaining -= len(chunk)
            got = h.hexdigest()
            if got != hexd:
                raise RegistryError(
                    f"digest mismatch: body is sha256:{got}")
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
        return {"digest": digest, "size": size}

    def add_blob_file(self, src: str) -> dict:
        h = hashlib.sha256()
        size = 0
        with open(src, "rb") as f:
            while chunk := f.read(1 << 20):
                h.update(chunk)
                size += len(chunk)
        digest = "sha256:" + h.hexdigest()
        path = self.blob_path(digest)
        if not os.path.exists(path):
            tmp = path + f".partial.{os.getpid()}.{threading.get_ident()}"
            shutil.copyfile(src, tmp)
            os.replace(tmp, path)
        return {"digest": digest, "size": size}


# An in-flight writer may legitimately go quiet for a full network read
# timeout (RegistryClient timeout=60s) without touching its .partial, so the
# abandoned-partial threshold must exceed that with wide margin — claiming or
# deleting a LIVE partial splits one inode between two writers and corrupts
# the blob.
PARTIAL_STALE_S = 600.0


class RegistryClient:
    def __init__(self, store: ModelStore, timeout: float = 60.0):
        self.store = store
        self.timeout = timeout
        # serialise same-digest downloads within this process; the .partial
        # claim-by-rename below only guards against *other* processes
        self._blob_locks: Dict[str, threading.Lock] = {}
        self._blob_locks_guard = threading.Lock()

    def _blob_lock(self, digest: str) -> threading.Lock:
        with self._blob_locks_guard:
            return self._blob_locks.setdefault(digest, threading.Lock())

    def _open(self, url: str, headers: Dict[str, str]):
        req = urllib.request.Request(url, headers=headers)
        return urllib.request.urlopen(req, timeout=self.timeout)

    def fetch_manifest(self, name: ModelName) -> dict:
        try:
            with self._open(name.manifest_url(),
                            {"Accept": MANIFEST_ACCEPT}) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise RegistryError(
                    f"model {name.short!r} not found in registry") from e
            raise RegistryError(f"manifest fetch failed: {e}") from e
        except urllib.error.URLError as e:
            raise RegistryError(f"registry unreachable: {e}") from e

    def _pull_blob(self, name: ModelName, digest: str, size: int,
                   progress: Optional[ProgressCb], status: str):
        with self._blob_lock(digest):
            self._pull_blob_locked(name, digest, size, progress, status)

    @staticmethod
    def _cleanup_stale_partials(path: str):
        """Remove abandoned .partial files once the blob is installed.

        Only stale ones (mtime older than PARTIAL_STALE_S): a fresh partial
        may belong to a live writer in another process, whose in-flight fd
        must not be yanked."""
        import glob as _glob
        now = time.time()
        for cand in _glob.glob(path + ".partial*"):
            try:
                if now - os.path.getmtime(cand) >= PARTIAL_STALE_S:
                    os.remove(cand)
            except OSError:
                continue

    def _pull_blob_locked(self, name: ModelName, digest: str, size: int,
                          progress: Optional[ProgressCb], status: str):
        path = self.store.blob_path(digest)
        if os.path.exists(path):
            self._cleanup_stale_partials(path)
            if progress:
                progress(status, size, size, digest=digest)
            return
        # each attempt writes its own .partial.<suffix>; to resume, claim an
        # abandoned partial by atomic rename. Only partials whose mtime is
        # stale are claimed: an active writer (another process; same-process
        # writers are excluded by _blob_lock) touches its file continuously,
        # and renaming a live partial would not stop the writer's open fd —
        # both would append to one inode and corrupt the blob.
        partial = path + f".partial.{os.getpid()}.{os.urandom(3).hex()}"
        have = 0
        import glob as _glob
        now = time.time()
        for cand in _glob.glob(path + ".partial*"):
            try:
                if now - os.path.getmtime(cand) < PARTIAL_STALE_S:
                    continue
                os.replace(cand, partial)
                have = os.path.getsize(partial)
                break
            except OSError:
                continue
        headers: Dict[str, str] = {}
        mode = "wb"
        if 0 < have < size:
            headers["Range"] = f"bytes={have}-"
            mode = "ab"
        h = hashlib.sha256()
        try:
            with self._open(name.blob_url(digest), headers) as r:
                if mode == "ab" and r.status != 206:
                    mode, have = "wb", 0  # server ignored Range
                with open(partial, mode) as f:
                    done = have
                    while chunk := r.read(1 << 20):
                        f.write(chunk)
                        done += len(chunk)
                        if progress:
                            progress(status, done, size, digest=digest)
        except urllib.error.URLError as e:
            raise RegistryError(f"blob pull failed: {e}") from e
        # verify the whole file (including any resumed prefix)
        with open(partial, "rb") as f:
            while chunk := f.read(1 << 20):
                h.update(chunk)
        actual = "sha256:" + h.hexdigest()
        if actual != digest:
            os.remove(partial)
            raise RegistryError(
                f"digest mismatch for {digest}: got {actual}")
        os.replace(partial, path)
        self._cleanup_stale_partials(path)

    def pull(self, ref: str, progress: Optional[ProgressCb] = None) -> ModelName:
        """Pull a model by name into the store. Idempotent; resumes."""
        name = ModelName.parse(ref)
        if progress:
            progress("pulling manifest", 0, 0)
        manifest = self.fetch_manifest(name)
        layers = list(manifest.get("layers", []))
        cfg = manifest.get("config")
        if cfg:
            layers.append(cfg)
        for layer in layers:
            self._pull_blob(name, layer["digest"], layer.get("size", 0),
                            progress, f"pulling {layer['digest'][7:19]}")
        if progress:
            progress("writing manifest", 0, 0)
        self.store.write_manifest(name, manifest)
        if progress:
            progress("success", 0, 0)
        return name

    def _request(self, method: str, url: str, data: Optional[bytes] = None,
                 headers: Optional[Dict[str, str]] = None):
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=headers or {})
        return urllib.request.urlopen(req, timeout=self.timeout)

    def _blob_exists(self, name: ModelName, digest: str) -> bool:
        try:
            with self._request("HEAD", name.blob_url(digest)):
                return True
        except urllib.error.HTTPError as e:
            if e.code in (404, 405):
                return False
            raise RegistryError(f"blob HEAD failed: {e}") from e
        except urllib.error.URLError as e:
            raise RegistryError(f"registry unreachable: {e}") from e

    def _push_blob(self, name: ModelName, digest: str, path: str,
                   size: int, progress, label: str):
        """Docker registry v2 two-step upload: POST an upload session,
        PUT the bytes at the returned Location with ?digest=. The blob
        streams from disk (model layers are multi-GB; never buffered
        whole) with per-chunk progress, mirroring pull."""
        start_url = (f"{name.base_url}/v2/{name.namespace}/{name.name}"
                     f"/blobs/uploads/")
        try:
            with self._request("POST", start_url, data=b"") as r:
                loc = r.headers.get("Location", "")
        except urllib.error.HTTPError as e:
            raise RegistryError(f"upload start failed: {e}") from e
        except urllib.error.URLError as e:
            raise RegistryError(f"registry unreachable: {e}") from e
        if loc.startswith("/"):
            loc = name.base_url + loc
        sep = "&" if "?" in loc else "?"
        put_url = f"{loc}{sep}digest={digest}"

        client = self

        class _Reader:
            """File-like body: urllib streams it; read() reports progress."""

            def __init__(self, f):
                self.f = f
                self.sent = 0

            def read(self, n=-1):
                chunk = self.f.read(n if n and n > 0 else 1 << 20)
                self.sent += len(chunk)
                if progress and chunk:
                    progress(label, min(self.sent, size), size, digest)
                return chunk

            def __len__(self):  # Content-Length for urllib
                return size

        try:
            with open(path, "rb") as f:
                with client._request("PUT", put_url, data=_Reader(f),
                                     headers={
                        "Content-Type": "application/octet-stream",
                        "Content-Length": str(size)}):
                    pass
        except urllib.error.HTTPError as e:
            raise RegistryError(f"blob upload failed: {e}") from e
        except urllib.error.URLError as e:
            raise RegistryError(f"registry unreachable: {e}") from e
        except OSError as e:
            raise RegistryError(f"local blob {digest} missing: {e}") from e

    def push(self, ref: str, progress: Optional[ProgressCb] = None):
        """Push a local model to its registry (docker registry v2 flow:
        existence HEAD per blob, chunked-session upload, then manifest PUT)
        — the inverse of ``pull``, same protocol the ollama CLI's
        `ollama push` speaks against registry.ollama.ai."""
        name = ModelName.parse(ref)
        manifest = self.store.read_manifest(name)
        if manifest is None:
            raise RegistryError(f"model {name.short!r} not found locally")
        blobs = list(manifest.get("layers", []))
        if manifest.get("config"):
            blobs.append(manifest["config"])
        for layer in blobs:
            digest = layer["digest"]
            size = layer.get("size", 0)
            label = f"pushing {digest[7:19]}"
            if progress:
                progress(label, 0, size, digest)
            if self._blob_exists(name, digest):
                if progress:
                    progress(label, size, size, digest)
                continue
            self._push_blob(name, digest, self.store.blob_path(digest),
                            size, progress, label)
            if progress:
                progress(label, size, size, digest)
        if progress:
            progress("pushing manifest", 0, 0)
        body = json.dumps(manifest).encode()
        try:
            with self._request("PUT", name.manifest_url(), data=body,
                               headers={"Content-Type": manifest.get(
                                   "mediaType", MANIFEST_MT)}):
                pass
        except urllib.error.HTTPError as e:
            raise RegistryError(f"manifest push failed: {e}") from e
        except urllib.error.URLError as e:
            raise RegistryError(f"registry unreachable: {e}") from e
        if progress:
            progress("success", 0, 0)
        return name
