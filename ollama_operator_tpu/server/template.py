"""Go text/template subset interpreter for Ollama prompt templates.

Ollama model images carry a TEMPLATE layer written in Go's text/template
syntax; the reference inherits its rendering from the delegated ollama server
(SURVEY.md §2.2 "Modelfile semantics"). This implements the subset real
model templates use:

  {{ .Field }} {{ .A.B }}           field paths (dict lookup)
  {{- ... -}}                       whitespace trim markers
  {{ if EXPR }} … {{ else }} … {{ end }}
  {{ range EXPR }} … {{ end }}      (dot rebinds to the element)
  eq/ne/and/or/not, string literals "…", $last-style iteration helpers are
  NOT needed by the shipped templates we target (llama2, chatml, gemma,
  phi, mistral) — unsupported constructs raise TemplateError.
"""

from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple


class TemplateError(ValueError):
    pass


_TOKEN_RE = re.compile(r"\{\{-?\s*(.*?)\s*-?\}\}", re.DOTALL)


def _lex(src: str) -> List[Tuple[str, str]]:
    """→ [("text", s) | ("action", expr)], with whitespace trims applied."""
    out: List[Tuple[str, str]] = []
    pos = 0
    for m in _TOKEN_RE.finditer(src):
        text = src[pos:m.start()]
        if m.group(0).startswith("{{-"):
            text = text.rstrip()
        if out and out[-1][0] == "trim_next":
            out.pop()
            text = text.lstrip()
        if text:
            out.append(("text", text))
        out.append(("action", m.group(1)))
        if m.group(0).endswith("-}}"):
            out.append(("trim_next", ""))
        pos = m.end()
    tail = src[pos:]
    if out and out[-1][0] == "trim_next":
        out.pop()
        tail = tail.lstrip()
    if tail:
        out.append(("text", tail))
    return out


# --- expression evaluation -------------------------------------------------

_STR_RE = re.compile(r'"((?:[^"\\]|\\.)*)"')


def _split_args(expr: str) -> List[str]:
    out, cur, depth, in_str = [], "", 0, False
    i = 0
    while i < len(expr):
        c = expr[i]
        if in_str:
            cur += c
            if c == "\\":
                cur += expr[i + 1]
                i += 2
                continue
            if c == '"':
                in_str = False
        elif c == '"':
            in_str = True
            cur += c
        elif c == "(":
            depth += 1
            cur += c
        elif c == ")":
            depth -= 1
            cur += c
        elif c.isspace() and depth == 0:
            if cur:
                out.append(cur)
                cur = ""
        else:
            cur += c
        i += 1
    if cur:
        out.append(cur)
    return out


def _truthy(v: Any) -> bool:
    return bool(v)


def _eval(expr: str, dot: Any) -> Any:
    expr = expr.strip()
    if expr.startswith("(") and expr.endswith(")"):
        return _eval(expr[1:-1], dot)
    m = _STR_RE.fullmatch(expr)
    if m:
        return m.group(1).replace('\\"', '"').replace("\\n", "\n")
    if expr == ".":
        return dot
    if expr.startswith("."):
        cur = dot
        for part in expr[1:].split("."):
            if not part:
                continue
            if isinstance(cur, dict):
                cur = cur.get(part, cur.get(part[0].lower() + part[1:], ""))
            else:
                cur = getattr(cur, part, "")
        return cur
    args = _split_args(expr)
    if len(args) > 1:
        fn, rest = args[0], [_eval(a, dot) for a in args[1:]]
        if fn == "eq":
            return all(r == rest[0] for r in rest[1:])
        if fn == "ne":
            return rest[0] != rest[1]
        if fn == "and":
            for r in rest:
                if not _truthy(r):
                    return r
            return rest[-1]
        if fn == "or":
            for r in rest:
                if _truthy(r):
                    return r
            return rest[-1]
        if fn == "not":
            return not _truthy(rest[0])
        if fn == "json":
            import json as _json
            return _json.dumps(rest[0])
        raise TemplateError(f"unsupported template function {fn!r}")
    if expr in ("true", "false"):
        return expr == "true"
    raise TemplateError(f"unsupported template expression {expr!r}")


# --- parse + render --------------------------------------------------------

class _Node:
    pass


class _Text(_Node):
    def __init__(self, s):
        self.s = s


class _Emit(_Node):
    def __init__(self, expr):
        self.expr = expr


class _If(_Node):
    def __init__(self, expr, body, orelse):
        self.expr, self.body, self.orelse = expr, body, orelse


class _Range(_Node):
    def __init__(self, expr, body):
        self.expr, self.body = expr, body


def _parse(tokens: List[Tuple[str, str]], i: int = 0,
           until: Optional[set] = None) -> Tuple[List[_Node], int, str]:
    nodes: List[_Node] = []
    while i < len(tokens):
        kind, val = tokens[i]
        if kind == "text":
            nodes.append(_Text(val))
            i += 1
            continue
        word = val.split(None, 1)[0] if val else ""
        if until and word in until:
            return nodes, i, word
        if word == "if":
            node, i = _parse_if(val.split(None, 1)[1], tokens, i + 1)
            nodes.append(node)
            i += 1  # past the matching end
        elif word == "range":
            body, i, _ = _parse(tokens, i + 1, {"end"})
            nodes.append(_Range(val.split(None, 1)[1], body))
            i += 1
        elif word in ("end", "else"):
            raise TemplateError(f"unexpected {{{{ {word} }}}}")
        else:
            nodes.append(_Emit(val))
            i += 1
    return nodes, i, ""


def _parse_if(expr: str, tokens: List[Tuple[str, str]], i: int
              ) -> Tuple[_If, int]:
    """Parse an if-chain starting just after its `if EXPR` action. Returns
    the node and the index of the matching `end` token (chained else-ifs
    share one `end`)."""
    body, i, stop = _parse(tokens, i, {"else", "end"})
    orelse: List[_Node] = []
    if stop == "else":
        rest = tokens[i][1].split(None, 1)
        if len(rest) > 1 and rest[1].lstrip().startswith("if"):
            sub_expr = rest[1].lstrip()[2:].strip()
            inner, i = _parse_if(sub_expr, tokens, i + 1)
            orelse = [inner]
        else:
            orelse, i, _ = _parse(tokens, i + 1, {"end"})
    return _If(expr, body, orelse), i


def _render(nodes: List[_Node], dot: Any, out: List[str]):
    for n in nodes:
        if isinstance(n, _Text):
            out.append(n.s)
        elif isinstance(n, _Emit):
            v = _eval(n.expr, dot)
            if isinstance(v, (dict, list)):
                # Go renders structs with fmt verbs; models are trained on
                # JSON tool specs, so emit maps/lists as JSON (tool use)
                import json as _json
                out.append(_json.dumps(v))
            else:
                out.append("" if v is None else str(v))
        elif isinstance(n, _If):
            if _truthy(_eval(n.expr, dot)):
                _render(n.body, dot, out)
            else:
                _render(n.orelse, dot, out)
        elif isinstance(n, _Range):
            seq = _eval(n.expr, dot) or []
            for item in seq:
                _render(n.body, item, out)


class Template:
    def __init__(self, src: str):
        self.src = src
        tokens = [t for t in _lex(src) if t[0] != "trim_next"]
        self.nodes, _, _ = _parse(tokens)

    def render(self, **ctx: Any) -> str:
        # Go templates address fields capitalised; accept both spellings
        dot = dict(ctx)
        for k in list(dot):
            dot[k[0].upper() + k[1:]] = dot[k]
        out: List[str] = []
        _render(self.nodes, dot, out)
        return "".join(out)


# default template when a model image carries none (matches ollama's
# behaviour of passing the prompt through)
DEFAULT_TEMPLATE = "{{ if .System }}{{ .System }}\n\n{{ end }}{{ .Prompt }}"
