"""Tool calling (function calling) for /api/chat and /v1/chat/completions.

The reference delegates tool support to the ollama server inside the
container image (/root/reference/pkg/model/pod.go:11); the contract is:
requests carry OpenAI-shaped ``tools``, the model's Go template renders
them into the prompt (templates access capitalized fields — ``.Tools``,
``.Function.Name`` …), and the model's textual output is parsed back into
structured ``tool_calls`` when it emits a JSON invocation.

This module owns the two data transformations:
- ``to_template_tools`` / ``to_template_tool_calls``: OpenAI wire shape →
  Go-template shape (capitalized keys) for server/template.py.
- ``parse_tool_calls``: model output text → [{"function": {"name", "arguments"}}]
  (handles a bare object, a list of objects, ollama's "parameters" alias,
  and JSON embedded after leading prose).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


def to_template_tools(tools: List[Dict]) -> List[Dict]:
    """Normalised LOWERCASE keys: the template engine's field lookup falls
    back from ``.Function.Name`` to ``function``/``name``, and ``json``
    emission must produce the wire-shaped JSON models were trained on."""
    out = []
    for t in tools or []:
        fn = t.get("function") or {}
        out.append({
            "type": t.get("type", "function"),
            "function": {
                "name": fn.get("name", ""),
                "description": fn.get("description", ""),
                "parameters": fn.get("parameters") or {},
            },
        })
    return out


def to_template_tool_calls(calls: List[Dict]) -> List[Dict]:
    out = []
    for c in calls or []:
        fn = c.get("function") or {}
        args = fn.get("arguments")
        if isinstance(args, str):
            try:
                args = json.loads(args)
            except json.JSONDecodeError:
                pass
        out.append({"function": {"name": fn.get("name", ""),
                                 "arguments": args or {}}})
    return out


def _as_call(obj: Any) -> Optional[Dict]:
    """One parsed JSON value → a tool call dict, or None."""
    if not isinstance(obj, dict):
        return None
    name = obj.get("name")
    args = obj.get("arguments", obj.get("parameters"))
    if not isinstance(name, str) or not name:
        return None
    if args is None or not isinstance(args, dict):
        return None
    return {"function": {"name": name, "arguments": args}}


def _json_candidates(text: str):
    """Yield (value, start, end) for every decodable JSON span in
    ``text``: the whole string first, then brace/bracket-delimited spans
    between prose."""
    dec = json.JSONDecoder()
    s = text
    try:
        yield json.loads(s), 0, len(s)
        return
    except json.JSONDecodeError:
        pass
    i = 0
    while i < len(s):
        if s[i] in "[{":
            try:
                val, end = dec.raw_decode(s, i)
                yield val, i, end
                i = end
                continue
            except json.JSONDecodeError:
                pass
        i += 1


def split_tool_calls(text: str):
    """Model output → (tool calls, remaining prose).

    EVERY JSON span that decodes to tool invocations contributes calls
    (models emit parallel calls as separate objects); spans that aren't
    invocations, and all non-JSON text, stay in the prose remainder."""
    calls: List[Dict] = []
    keep: List[str] = []
    pos = 0
    for val, start, end in _json_candidates(text):
        items = val if isinstance(val, list) else [val]
        found = [c for c in (_as_call(x) for x in items) if c]
        if found and len(found) == len(items):
            calls.extend(found)
            keep.append(text[pos:start])
            pos = end
    keep.append(text[pos:])
    return calls, "".join(keep).strip()


def parse_tool_calls(text: str) -> List[Dict]:
    """Model output → tool calls ([] when the output is ordinary text)."""
    return split_tool_calls(text)[0]
