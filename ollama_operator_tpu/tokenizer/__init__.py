from .tokenizer import Tokenizer, StreamDecoder  # noqa: F401
