"""Tokenizers built purely from GGUF metadata (no sentencepiece/tiktoken).

The reference's tokenization happens inside the delegated llama.cpp runtime
(SURVEY.md §2.2); here it is re-implemented natively:

- ``model == "llama"`` → SentencePiece-style BPE: pieces + scores, greedy
  highest-score bigram merging, ``▁`` whitespace convention, ``<0xXX>`` byte
  fallback.
- ``model == "gpt2"`` → byte-level BPE: byte→unicode table + ranked merges
  (llama3, phi-2, qwen2, gemma-style vocabularies).

Both support streaming-safe incremental decoding (StreamDecoder) — bytes are
only emitted once they form complete UTF-8, which the server relies on for
chunked responses.
"""

from __future__ import annotations

import heapq
import re
from typing import Dict, Iterable, List, Optional, Sequence

# llama.cpp token-type enum
TT_UNDEFINED, TT_NORMAL, TT_UNKNOWN, TT_CONTROL, TT_USER_DEFINED, \
    TT_UNUSED, TT_BYTE = range(7)

_SPM_SPACE = "▁"  # ▁


def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's invertible byte→printable-unicode mapping."""
    bs = (list(range(ord("!"), ord("~") + 1)) +
          list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {b: chr(c) for b, c in zip(bs, cs)}


_BYTE_ENC = _bytes_to_unicode()
_BYTE_DEC = {v: k for k, v in _BYTE_ENC.items()}

# GPT-2 pre-tokenizer, approximated for stdlib `re` (no \p classes):
# [^\W\d_] ≈ \p{L}; \d ≈ \p{N}; punctuation bucket catches the rest incl. _
_GPT2_PAT = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+"
    r"|\s+(?!\S)|\s+", re.UNICODE)


class Tokenizer:
    def __init__(self, model: str, tokens: Sequence[str],
                 scores: Optional[Sequence[float]] = None,
                 token_types: Optional[Sequence[int]] = None,
                 merges: Optional[Sequence[str]] = None,
                 bos_id: int = -1, eos_id: int = -1,
                 add_bos: bool = True, add_eos: bool = False,
                 add_space_prefix: bool = True,
                 extra_eog: Iterable[int] = ()):
        self.model = model
        self.tokens = list(tokens)
        self.scores = list(scores) if scores is not None else [0.0] * len(tokens)
        self.token_types = (list(token_types) if token_types is not None
                            else [TT_NORMAL] * len(tokens))
        self.vocab = {t: i for i, t in enumerate(self.tokens)}
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.add_bos = add_bos
        self.add_eos = add_eos
        self.add_space_prefix = add_space_prefix
        self.eog_ids = {eos_id} | set(extra_eog)
        self.eog_ids.discard(-1)
        # control/user-defined pieces must match before normal text
        self._specials = sorted(
            (t for i, t in enumerate(self.tokens)
             if self.token_types[i] in (TT_CONTROL, TT_USER_DEFINED)
             and t),
            key=len, reverse=True)
        self._special_re = (re.compile(
            "|".join(re.escape(s) for s in self._specials))
            if self._specials else None)
        if model == "gpt2":
            merges = merges or []
            self._ranks = {tuple(m.split(" ", 1)): r
                           for r, m in enumerate(merges)}
        if model == "bert":
            # WordPiece (embedding models: all-minilm & friends). Uncased
            # checkpoints ship all-lowercase vocabs — detect once so
            # encode() lowercases to match (llama.cpp reads the same
            # signal from the vocab rather than a metadata flag)
            self._wp_lower = not any(
                any(ch.isalpha() and ch.isupper() for ch in t)
                for t in self.tokens
                if not (t.startswith("[") and t.endswith("]")))
            self._unk_id = next(
                (i for i, t in enumerate(self.tokens) if t == "[UNK]"), 0)
        self._byte_ids = {}
        for i, t in enumerate(self.tokens):
            if self.token_types[i] == TT_BYTE and len(t) == 6:  # <0xXX>
                try:
                    self._byte_ids[int(t[3:5], 16)] = i
                except ValueError:
                    pass

    # -----------------------------------------------------------------
    @classmethod
    def from_gguf_metadata(cls, md: dict) -> "Tokenizer":
        model = md.get("tokenizer.ggml.model", "llama")
        tokens = md["tokenizer.ggml.tokens"]
        bos = md.get("tokenizer.ggml.bos_token_id", -1)
        eos = md.get("tokenizer.ggml.eos_token_id", -1)
        if model == "bert":
            # BERT frames sequences as [CLS] … [SEP]; conversions carry
            # cls/seperator ids (llama.cpp's spelling) instead of bos/eos
            bos = md.get("tokenizer.ggml.cls_token_id", bos)
            eos = md.get("tokenizer.ggml.seperator_token_id",
                         md.get("tokenizer.ggml.separator_token_id", eos))
            return cls(model=model, tokens=tokens,
                       token_types=md.get("tokenizer.ggml.token_type"),
                       bos_id=bos, eos_id=eos,
                       add_bos=md.get("tokenizer.ggml.add_bos_token", True),
                       add_eos=md.get("tokenizer.ggml.add_eos_token", True))
        extra = set()
        for key in ("tokenizer.ggml.eot_token_id",
                    "tokenizer.ggml.eom_token_id"):
            if key in md:
                extra.add(md[key])
        return cls(
            model=model,
            tokens=tokens,
            scores=md.get("tokenizer.ggml.scores"),
            token_types=md.get("tokenizer.ggml.token_type"),
            merges=md.get("tokenizer.ggml.merges"),
            bos_id=bos, eos_id=eos,
            add_bos=md.get("tokenizer.ggml.add_bos_token", model == "llama"),
            add_eos=md.get("tokenizer.ggml.add_eos_token", False),
            add_space_prefix=md.get("tokenizer.ggml.add_space_prefix", True),
            extra_eog=extra)

    @property
    def n_vocab(self) -> int:
        return len(self.tokens)

    def is_eog(self, tid: int) -> bool:
        return tid in self.eog_ids

    # -----------------------------------------------------------------
    # encoding
    # -----------------------------------------------------------------
    def encode(self, text: str, add_bos: Optional[bool] = None,
               parse_special: bool = True) -> List[int]:
        ids: List[int] = []
        if add_bos is None:
            add_bos = self.add_bos
        if add_bos and self.bos_id >= 0:
            ids.append(self.bos_id)
        # split out special tokens first, tokenize the text in between
        chunks: List = []
        if parse_special and self._special_re is not None:
            pos = 0
            for m in self._special_re.finditer(text):
                if m.start() > pos:
                    chunks.append(text[pos:m.start()])
                chunks.append(self.vocab[m.group()])
                pos = m.end()
            if pos < len(text):
                chunks.append(text[pos:])
        else:
            chunks.append(text)
        first_text = True
        for c in chunks:
            if isinstance(c, int):
                ids.append(c)
                continue
            if self.model == "gpt2":
                ids.extend(self._encode_bpe(c))
            elif self.model == "bert":
                ids.extend(self._encode_wpm(c))
            else:
                ids.extend(self._encode_spm(c, first_text))
            first_text = False
        if self.add_eos and self.eos_id >= 0:
            ids.append(self.eos_id)
        return ids

    # -- WordPiece (bert embedding models) -----------------------------
    def _encode_wpm(self, text: str) -> List[int]:
        """BERT WordPiece: basic-clean + (uncased) lowercase/strip-accents
        normalization, whitespace + punctuation pre-split, then greedy
        longest-prefix matching with ##-continuations; a word with no
        full cover collapses to [UNK] (canonical WordPiece semantics)."""
        import unicodedata
        if getattr(self, "_wp_lower", False):
            text = text.lower()
            text = "".join(ch for ch in unicodedata.normalize("NFD", text)
                           if unicodedata.category(ch) != "Mn")

        def is_punct(ch):
            return (unicodedata.category(ch).startswith("P")
                    or (33 <= ord(ch) <= 47) or (58 <= ord(ch) <= 64)
                    or (91 <= ord(ch) <= 96) or (123 <= ord(ch) <= 126))

        words: List[str] = []
        buf = []
        for ch in text:
            if ch.isspace():
                if buf:
                    words.append("".join(buf))
                    buf = []
            elif is_punct(ch) or 0x4E00 <= ord(ch) <= 0x9FFF:
                # punctuation and CJK split to single-char words
                if buf:
                    words.append("".join(buf))
                    buf = []
                words.append(ch)
            else:
                buf.append(ch)
        if buf:
            words.append("".join(buf))

        ids: List[int] = []
        for word in words:
            if len(word) > 100:
                ids.append(self._unk_id)
                continue
            out, start, ok = [], 0, True
            while start < len(word):
                end = len(word)
                piece_id = None
                while end > start:
                    sub = word[start:end]
                    if start > 0:
                        sub = "##" + sub
                    if sub in self.vocab:
                        piece_id = self.vocab[sub]
                        break
                    end -= 1
                if piece_id is None:
                    ok = False
                    break
                out.append(piece_id)
                start = end
            ids.extend(out if ok else [self._unk_id])
        return ids

    # -- SPM (llama) ---------------------------------------------------
    def _encode_spm(self, text: str, is_first: bool) -> List[int]:
        if not text:
            return []
        if self.add_space_prefix and is_first:
            text = " " + text
        text = text.replace(" ", _SPM_SPACE)
        symbols: List[str] = list(text)

        # greedy highest-score bigram merge (scores are log-probs)
        nxt = list(range(1, len(symbols) + 1))
        prv = list(range(-1, len(symbols) - 1))
        alive = [True] * len(symbols)

        def try_pair(i):
            j = nxt[i]
            if j >= len(symbols):
                return None
            merged = symbols[i] + symbols[j]
            tid = self.vocab.get(merged)
            if tid is None:
                return None
            return (-self.scores[tid], i, merged)

        heap = []
        for i in range(len(symbols) - 1):
            p = try_pair(i)
            if p:
                heapq.heappush(heap, p)
        while heap:
            negs, i, merged = heapq.heappop(heap)
            j = nxt[i] if i < len(nxt) else None
            if (not alive[i] or j is None or j >= len(symbols)
                    or not alive[j] or symbols[i] + symbols[j] != merged):
                continue
            symbols[i] = merged
            alive[j] = False
            nxt[i] = nxt[j]
            if nxt[i] < len(symbols):
                prv[nxt[i]] = i
            for cand in (try_pair(prv[i]) if prv[i] >= 0 and alive[prv[i]]
                         else None, try_pair(i)):
                if cand:
                    heapq.heappush(heap, cand)

        out: List[int] = []
        for i, s in enumerate(symbols):
            if not alive[i]:
                continue
            tid = self.vocab.get(s)
            if tid is not None:
                out.append(tid)
            else:  # byte fallback
                for b in s.encode("utf-8"):
                    if b in self._byte_ids:
                        out.append(self._byte_ids[b])
                    elif self.vocab.get("<unk>") is not None:
                        out.append(self.vocab["<unk>"])
        return out

    # -- byte-level BPE (gpt2) -----------------------------------------
    def _bpe_merge(self, word: List[str]) -> List[str]:
        while len(word) > 1:
            best, best_rank = None, None
            for k in range(len(word) - 1):
                r = self._ranks.get((word[k], word[k + 1]))
                if r is not None and (best_rank is None or r < best_rank):
                    best, best_rank = k, r
            if best is None:
                break
            word[best:best + 2] = [word[best] + word[best + 1]]
        return word

    def _encode_bpe(self, text: str) -> List[int]:
        out: List[int] = []
        for m in _GPT2_PAT.finditer(text):
            chunk = m.group()
            mapped = "".join(_BYTE_ENC[b] for b in chunk.encode("utf-8"))
            for piece in self._bpe_merge(list(mapped)):
                tid = self.vocab.get(piece)
                if tid is not None:
                    out.append(tid)
                else:
                    for ch in piece:
                        tid = self.vocab.get(ch)
                        if tid is not None:
                            out.append(tid)
        return out

    # -----------------------------------------------------------------
    # decoding
    # -----------------------------------------------------------------
    def piece_bytes(self, tid: int) -> bytes:
        """Raw bytes of one token (may be partial UTF-8)."""
        if tid < 0 or tid >= len(self.tokens):
            return b""
        t = self.tokens[tid]
        tt = self.token_types[tid]
        if tt == TT_BYTE:
            try:
                return bytes([int(t[3:5], 16)])
            except (ValueError, IndexError):
                return b""
        if tt in (TT_CONTROL, TT_UNKNOWN, TT_UNUSED):
            return b""
        if self.model == "gpt2":
            return bytes(_BYTE_DEC.get(c, ord(" ") & 0xFF) for c in t)
        return t.replace(_SPM_SPACE, " ").encode("utf-8")

    def decode(self, ids: Sequence[int]) -> str:
        return b"".join(self.piece_bytes(i) for i in ids).decode(
            "utf-8", errors="replace")


class StreamDecoder:
    """Incremental detokeniser that never emits partial UTF-8 sequences."""

    def __init__(self, tok: Tokenizer):
        self.tok = tok
        self._buf = b""

    def feed(self, tid: int) -> str:
        self._buf += self.tok.piece_bytes(tid)
        # emit the longest prefix that is valid UTF-8
        for cut in range(len(self._buf), max(len(self._buf) - 4, -1), -1):
            try:
                s = self._buf[:cut].decode("utf-8")
                self._buf = self._buf[cut:]
                return s
            except UnicodeDecodeError:
                continue
        return ""

    def feed_many(self, tids) -> str:
        """Batch form of feed(): join a whole decode chunk's piece bytes
        and run ONE valid-prefix scan over the result, instead of one
        buffer append + scan per token."""
        self._buf += b"".join(self.tok.piece_bytes(t) for t in tids)
        for cut in range(len(self._buf), max(len(self._buf) - 4, -1), -1):
            try:
                s = self._buf[:cut].decode("utf-8")
                self._buf = self._buf[cut:]
                return s
            except UnicodeDecodeError:
                continue
        return ""

    def flush(self) -> str:
        s = self._buf.decode("utf-8", errors="replace")
        self._buf = b""
        return s
