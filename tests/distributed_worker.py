"""Worker process for tests/test_distributed.py's two-process world.

Launched twice (process 0 and 1) with the operator's StatefulSet env
contract (operator/pod.py:multihost_env); joins a jax.distributed world
over the CPU backend (2 local devices each → 4 global), runs a
tensor-parallel sharded forward over the GLOBAL mesh, and dumps the
replicated logits (process 0) for the parent to compare against a
single-process reference.
"""

import json
import os
import sys

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
port, pid, outdir = sys.argv[1], int(sys.argv[2]), sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
# the operator's env contract (rendered by operator/pod.py:multihost_env)
os.environ["TPU_DIST_HOSTS"] = "2"
os.environ["TPU_DIST_CHIPS_PER_HOST"] = "2"
os.environ["TPU_DIST_COORDINATOR"] = f"127.0.0.1:{port}"
os.environ["TPU_DIST_POD_NAME"] = f"ollama-model-llama2-{pid}"
sys.path.insert(0, repo)

import numpy as np  # noqa: E402
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from ollama_operator_tpu.parallel import distributed  # noqa: E402

assert distributed.maybe_initialize(), "expected to join a 2-process world"
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == pid, (jax.process_index(), pid)
assert len(jax.devices()) == 4, jax.devices()
assert len(jax.local_devices()) == 2

import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from ollama_operator_tpu.models import config as cfglib, decoder  # noqa: E402
from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh  # noqa: E402
from ollama_operator_tpu.parallel.sharding import params_pspec_tree  # noqa: E402

cfg = cfglib.PRESETS["tiny"]
params = decoder.init_params(cfg, jax.random.key(0), jnp.float32)
tokens = np.arange(1, 17, dtype=np.int32).reshape(2, 8) % cfg.vocab_size

mesh = make_mesh(MeshPlan(dp=1, tp=4))   # spans BOTH processes
pspecs = params_pspec_tree(params, cfg, mesh)


def to_global(x, spec):
    sh = NamedSharding(mesh, spec)
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])


gparams = jax.tree.map(to_global, params, pspecs,
                       is_leaf=lambda s: isinstance(s, P))
gtokens = to_global(tokens, P(None, None))

rep = NamedSharding(mesh, P())
fn = jax.jit(lambda p, t: decoder.prefill_chunk(p, cfg, t)[0],
             out_shardings=rep)
logits = fn(gparams, gtokens)
jax.block_until_ready(logits)
local = np.asarray(logits.addressable_data(0))   # replicated → full array

if pid == 0:
    np.save(os.path.join(outdir, "logits.npy"), local)
with open(os.path.join(outdir, f"ok{pid}.json"), "w") as f:
    json.dump({"processes": jax.process_count(),
               "devices": len(jax.devices())}, f)
print(f"worker {pid} done", flush=True)
