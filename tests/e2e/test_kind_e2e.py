"""Asserted kind e2e: image build → kind load → install.yaml → Model CR
→ Available → /api/generate answers.

The reference's e2e (test/e2e/e2e_test.go:32-122) stops at "manager pod is
Running"; this one drives the whole product promise — `kubectl apply` of a
Model CR serves tokens — against a kind cluster with zero registry egress
(an in-cluster fixture registry serves the deterministic tiny model; see
hack/fake_registry_entry.py).

Runs when docker+kind+kubectl are on PATH (CI job `kind-e2e` in
.github/workflows/tests.yml) or when RUN_KIND_E2E=1; skipped otherwise
so the CPU-mesh unit tiers stay hermetic. One command:

    python -m pytest tests/e2e/ -q
"""

import json
import os
import shutil
import subprocess
import time
import urllib.request

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
IMG = os.environ.get("E2E_IMG", "ollama-operator-tpu-e2e:dev")
CLUSTER = os.environ.get("E2E_CLUSTER", "tpu-operator-e2e")
NS = "ollama-operator-system"

_have_tools = all(shutil.which(t) for t in ("docker", "kind", "kubectl"))
pytestmark = pytest.mark.skipif(
    os.environ.get("RUN_KIND_E2E") != "1" or not _have_tools,
    reason="opt-in: RUN_KIND_E2E=1 + docker/kind/kubectl on PATH "
           "(the CI kind-e2e job sets it; unit tiers stay hermetic)")


def run(*cmd, timeout=900, **kw):
    print("+", " ".join(cmd), flush=True)
    return subprocess.run(cmd, check=True, cwd=ROOT, timeout=timeout, **kw)


def out(*cmd, timeout=120):
    return subprocess.run(cmd, check=True, cwd=ROOT, timeout=timeout,
                          capture_output=True, text=True).stdout


REGISTRY_MANIFEST = """
apiVersion: apps/v1
kind: Deployment
metadata:
  name: fake-registry
  namespace: default
spec:
  replicas: 1
  selector: {matchLabels: {app: fake-registry}}
  template:
    metadata: {labels: {app: fake-registry}}
    spec:
      containers:
        - name: registry
          image: %(img)s
          imagePullPolicy: Never
          command: ["python", "/app/hack/fake_registry_entry.py"]
          ports: [{containerPort: 5000}]
---
apiVersion: v1
kind: Service
metadata:
  name: fake-registry
  namespace: default
spec:
  selector: {app: fake-registry}
  ports: [{port: 5000, targetPort: 5000}]
"""

MODEL_CR = """
apiVersion: ollama.ayaka.io/v1
kind: Model
metadata:
  name: tiny
  namespace: default
spec:
  image: http://fake-registry.default.svc.cluster.local:5000/library/tiny:latest
  runtime: cpu
"""


@pytest.fixture(scope="module")
def cluster():
    # idempotent: a stale cluster (E2E_KEEP=1 or a killed run) must not
    # error the fixture
    subprocess.run(["kind", "delete", "cluster", "--name", CLUSTER],
                   cwd=ROOT, timeout=300)
    run("kind", "create", "cluster", "--name", CLUSTER,
        "--config", "hack/kind-config.yaml")
    try:
        yield CLUSTER
    finally:
        if os.environ.get("E2E_KEEP") != "1":
            subprocess.run(["kind", "delete", "cluster", "--name", CLUSTER],
                           cwd=ROOT, timeout=300)


def _wait(pred, what, timeout_s):
    deadline = time.time() + timeout_s
    last = None
    while time.time() < deadline:
        try:
            last = pred()
            if last:
                return last
        except Exception as e:  # noqa: BLE001 — cluster still converging
            last = e
        time.sleep(5)
    pytest.fail(f"timed out waiting for {what}; last={last}")


def test_apply_model_cr_serves_tokens(cluster, tmp_path):
    # 1. CPU image → kind
    run("docker", "build", "--build-arg", "BACKEND=cpu", "-t", IMG, ".")
    run("kind", "load", "docker-image", IMG, "--name", cluster)

    # 2. operator via the single-file installer, model pods on our image
    inst = tmp_path / "install.yaml"
    run("python", "hack/build_installer.py", "--image", IMG,
        "-o", str(inst))
    run("kubectl", "apply", "-f", str(inst))
    run("kubectl", "-n", NS, "set", "env",
        "deployment/ollama-operator-controller-manager",
        f"TPU_SERVER_IMAGE={IMG}", "JAX_PLATFORMS=cpu")
    # local image only exists in kind — never try to pull it
    run("kubectl", "-n", NS, "patch",
        "deployment/ollama-operator-controller-manager", "--type", "json",
        "-p", json.dumps([{
            "op": "replace",
            "path": "/spec/template/spec/containers/0/imagePullPolicy",
            "value": "Never"}]))
    _wait(lambda: "True" in out(
        "kubectl", "-n", NS, "get", "deploy",
        "ollama-operator-controller-manager",
        "-o", "jsonpath={.status.conditions[?(@.type=='Available')].status}"),
        "manager Available", 300)

    # 3. in-cluster fixture registry
    (tmp_path / "registry.yaml").write_text(REGISTRY_MANIFEST % {"img": IMG})
    run("kubectl", "apply", "-f", str(tmp_path / "registry.yaml"))
    _wait(lambda: "True" in out(
        "kubectl", "get", "deploy", "fake-registry",
        "-o", "jsonpath={.status.conditions[?(@.type=='Available')].status}"),
        "fake registry Available", 300)

    # 4. the product promise: apply a Model CR …
    (tmp_path / "model.yaml").write_text(MODEL_CR)
    run("kubectl", "apply", "-f", str(tmp_path / "model.yaml"))
    _wait(lambda: "True" in out(
        "kubectl", "get", "model", "tiny", "-o",
        "jsonpath={.status.conditions[?(@.type=='Available')].status}"),
        "Model Available=True", 900)

    # 5. … and the service answers the Ollama API
    pf = subprocess.Popen(
        ["kubectl", "port-forward", "svc/ollama-model-tiny",
         "18434:11434"], cwd=ROOT,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        def gen():
            req = urllib.request.Request(
                "http://127.0.0.1:18434/api/generate",
                data=json.dumps({"model": "http://fake-registry.default"
                                          ".svc.cluster.local:5000/library"
                                          "/tiny:latest",
                                 "prompt": "hello", "stream": False,
                                 "options": {"num_predict": 4}}).encode(),
                headers={"Content-Type": "application/json"})
            return json.loads(urllib.request.urlopen(req, timeout=120)
                              .read())
        res = _wait(lambda: gen(), "generate response", 300)
        assert res.get("done") is True
        assert "response" in res
    finally:
        pf.kill()
