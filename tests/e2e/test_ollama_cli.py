"""Stock-`ollama`-CLI conformance: the UNMODIFIED upstream client must
work against this server.

The reference's contract is exactly this — its getting-started doc points
the stock ollama CLI at the operator-exposed endpoint
(ref docs/pages/en/guide/getting-started.md:129-150) and its probes
assume the `ollama serve` surface (ref pkg/model/pod.go:41-64). Rounds
1-2 tested our own HTTP clients; this tier drives the real release
binary: list / pull (through the server's pull-through store, from a
local fixture registry) / show / run / ps / stop.

Runs when an `ollama` binary is available (OLLAMA_BIN or PATH) and
RUN_OLLAMA_CLI=1 — the CI job `ollama-cli-conformance` downloads the
release binary; local unit tiers stay hermetic.
"""

import json
import os
import shutil
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
OLLAMA = os.environ.get("OLLAMA_BIN") or shutil.which("ollama")

pytestmark = pytest.mark.skipif(
    os.environ.get("RUN_OLLAMA_CLI") != "1" or not OLLAMA,
    reason="opt-in: RUN_OLLAMA_CLI=1 + stock ollama binary (OLLAMA_BIN "
           "or PATH); the CI ollama-cli-conformance job provides both")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """Fixture registry (tiny model) + our server on CPU + OLLAMA_HOST."""
    sys.path.insert(0, os.path.join(ROOT, "tests"))
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")
    from fake_registry import FakeRegistry, add_tiny_model

    tmp = tmp_path_factory.mktemp("ollama-cli")
    reg = FakeRegistry()
    url = reg.start()
    short = add_tiny_model(reg, gguf_path=str(tmp / "tiny.gguf"))
    # host-prefixed (schemeless) ref — the form the stock CLI accepts
    ref = f"{url.split('://', 1)[1]}/{short}"

    port = _free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", TPU_WARM_BUCKETS="0",
               PYTHONPATH=ROOT)
    srv = subprocess.Popen(
        [sys.executable, "-m", "ollama_operator_tpu.server",
         "--store", str(tmp / "store"), "--port", str(port),
         "--max-seq-len", "128", "--max-slots", "2"],
        cwd=ROOT, env=env, stderr=open(str(tmp / "srv.log"), "wb"))
    base = f"http://127.0.0.1:{port}"
    for _ in range(120):
        try:
            urllib.request.urlopen(base + "/api/version", timeout=2)
            break
        except Exception:
            time.sleep(1)
    else:
        srv.kill()
        raise RuntimeError("server never came up")
    yield {"ref": ref, "host": f"127.0.0.1:{port}", "srv": srv,
           "log": str(tmp / "srv.log")}
    srv.kill()
    reg.stop()


def cli(stack, *args, timeout=600):
    env = dict(os.environ, OLLAMA_HOST=stack["host"])
    r = subprocess.run([OLLAMA, *args], env=env, capture_output=True,
                       text=True, timeout=timeout)
    print(f"+ ollama {' '.join(args)} -> rc={r.returncode}\n"
          f"{r.stdout}\n{r.stderr}", flush=True)
    return r


def test_cli_version_connects(stack):
    r = cli(stack, "-v")
    assert r.returncode == 0


def test_cli_pull_list_show_run(stack):
    ref = stack["ref"]
    r = cli(stack, "pull", ref)
    assert r.returncode == 0, r.stderr

    r = cli(stack, "list")
    assert r.returncode == 0, r.stderr
    assert "tiny" in r.stdout

    r = cli(stack, "show", ref)
    assert r.returncode == 0, r.stderr

    r = cli(stack, "run", ref, "hello", "--keepalive", "1m")
    assert r.returncode == 0, r.stderr

    r = cli(stack, "ps")
    assert r.returncode == 0, r.stderr

    r = cli(stack, "stop", ref)
    assert r.returncode == 0, r.stderr
