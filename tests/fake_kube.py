"""In-process fake Kubernetes apiserver — the envtest stand-in.

The reference's integration tier boots a real apiserver+etcd via envtest
(/root/reference/internal/controller/suite_test.go:52-84): real object
CRUD, no kubelet, so nothing ever becomes Ready on its own. Same model
here: `FakeKube` implements the KubeClient interface over a dict store
with resourceVersion bumping, status-subresource separation, label
selectors and watch streams; tests flip workload readiness by writing
status, exactly the role kubelet plays in a real cluster.

`serve_http(fake)` additionally exposes it over real HTTP speaking the
apiserver's REST/watch wire format so the stdlib KubeClient itself is
under test (URL construction, error mapping, watch framing).
"""

from __future__ import annotations

import copy
import itertools
import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ollama_operator_tpu.operator.client import (PLURALS, ApiError, Conflict,
                                                 NotFound)


def _key(api_version: str, kind: str, namespace: Optional[str], name: str
         ) -> Tuple[str, str, str, str]:
    return (api_version, kind, namespace or "", name)


class FakeKube:
    """Duck-typed KubeClient: same methods, in-memory store."""

    def __init__(self):
        self._lock = threading.RLock()
        self._store: Dict[Tuple, Dict[str, Any]] = {}
        self._rv = itertools.count(1)
        self._watchers: List[Tuple[Tuple[str, str, str], queue.Queue]] = []
        self.create_log: List[Tuple[str, str]] = []  # (kind, name) order

    # --- internals ------------------------------------------------------
    def _bump(self, obj: Dict[str, Any]) -> None:
        obj.setdefault("metadata", {})["resourceVersion"] = str(next(self._rv))

    def _notify(self, type_: str, obj: Dict[str, Any]) -> None:
        meta = obj.get("metadata") or {}
        topic = (obj.get("apiVersion", ""), obj.get("kind", ""),
                 meta.get("namespace", ""))
        for (t, q) in list(self._watchers):
            if t[0] == topic[0] and t[1] == topic[1] and \
                    (not t[2] or t[2] == topic[2]):
                q.put({"type": type_, "object": copy.deepcopy(obj)})

    # --- KubeClient interface -------------------------------------------
    def get(self, api_version, kind, namespace, name):
        with self._lock:
            obj = self._store.get(_key(api_version, kind, namespace, name))
            return copy.deepcopy(obj) if obj else None

    def create(self, obj):
        obj = copy.deepcopy(obj)
        meta = obj.setdefault("metadata", {})
        k = _key(obj["apiVersion"], obj["kind"], meta.get("namespace"),
                 meta["name"])
        with self._lock:
            if k in self._store:
                raise Conflict(409, f"{obj['kind']} {meta['name']} exists")
            meta.setdefault("uid", f"uid-{next(self._rv)}")
            self._bump(obj)
            obj.setdefault("status", {})
            self._store[k] = copy.deepcopy(obj)
            self.create_log.append((obj["kind"], meta["name"]))
            self._notify("ADDED", obj)
            return copy.deepcopy(obj)

    def update(self, obj):
        obj = copy.deepcopy(obj)
        meta = obj.get("metadata") or {}
        k = _key(obj["apiVersion"], obj["kind"], meta.get("namespace"),
                 meta["name"])
        with self._lock:
            cur = self._store.get(k)
            if cur is None:
                raise NotFound(404, f"{obj['kind']} {meta['name']}")
            sent = meta.get("resourceVersion")
            if sent and sent != cur["metadata"].get("resourceVersion"):
                raise Conflict(409, "resourceVersion mismatch")
            obj["status"] = cur.get("status", {})  # spec update only
            self._bump(obj)
            self._store[k] = copy.deepcopy(obj)
            self._notify("MODIFIED", obj)
            return copy.deepcopy(obj)

    def update_status(self, obj):
        obj = copy.deepcopy(obj)
        meta = obj.get("metadata") or {}
        k = _key(obj["apiVersion"], obj["kind"], meta.get("namespace"),
                 meta["name"])
        with self._lock:
            cur = self._store.get(k)
            if cur is None:
                raise NotFound(404, f"{obj['kind']} {meta['name']}")
            sent = meta.get("resourceVersion")
            if sent and sent != cur["metadata"].get("resourceVersion"):
                raise Conflict(409, "resourceVersion mismatch")
            cur["status"] = copy.deepcopy(obj.get("status", {}))
            self._bump(cur)
            self._notify("MODIFIED", cur)
            return copy.deepcopy(cur)

    def set_status(self, api_version, kind, namespace, name, status):
        """Test hook: play kubelet (mark workloads ready, etc.)."""
        with self._lock:
            cur = self._store[_key(api_version, kind, namespace, name)]
            cur.setdefault("status", {}).update(status)
            self._bump(cur)
            self._notify("MODIFIED", cur)

    def delete(self, api_version, kind, namespace, name):
        with self._lock:
            obj = self._store.pop(_key(api_version, kind, namespace, name),
                                  None)
            if obj is not None:
                self._notify("DELETED", obj)

    def list(self, api_version, kind, namespace=None, label_selector=None):
        sel = {}
        if label_selector:
            for part in label_selector.split(","):
                k, _, v = part.partition("=")
                sel[k] = v
        with self._lock:
            out = []
            for (av, kd, ns, _), obj in self._store.items():
                if av != api_version or kd != kind:
                    continue
                if namespace and ns != namespace:
                    continue
                labels = (obj.get("metadata") or {}).get("labels") or {}
                if any(labels.get(k) != v for k, v in sel.items()):
                    continue
                out.append(copy.deepcopy(obj))
            return out

    def watch(self, api_version, kind, namespace=None, resource_version=None,
              timeout_seconds=300, stop=None):
        q: queue.Queue = queue.Queue()
        topic = (api_version, kind, namespace or "")
        with self._lock:
            self._watchers.append((topic, q))
        try:
            while stop is None or not stop.is_set():
                try:
                    yield q.get(timeout=0.2)
                except queue.Empty:
                    if stop is None:
                        return
        finally:
            with self._lock:
                try:
                    self._watchers.remove((topic, q))
                except ValueError:
                    pass


# ---------------------------------------------------------------------------
# HTTP facade: the apiserver wire format over the fake store
# ---------------------------------------------------------------------------

def _parse_path(path: str):
    """/api/v1/... or /apis/<group>/<version>/... →
    (api_version, plural, namespace, name, subresource)"""
    parts = [p for p in path.split("/") if p]
    if not parts:
        raise NotFound(404, path)
    if parts[0] == "api":
        api_version, rest = parts[1], parts[2:]
    elif parts[0] == "apis":
        api_version, rest = f"{parts[1]}/{parts[2]}", parts[3:]
    else:
        raise NotFound(404, path)
    namespace = None
    if rest and rest[0] == "namespaces" and len(rest) > 1:
        namespace, rest = rest[1], rest[2:]
    plural = rest[0] if rest else ""
    name = rest[1] if len(rest) > 1 else None
    sub = rest[2] if len(rest) > 2 else None
    return api_version, plural, namespace, name, sub


_KIND_BY_PLURAL = {v: k for k, v in PLURALS.items()}


def serve_http(fake: FakeKube) -> ThreadingHTTPServer:
    """Expose the fake over HTTP on an ephemeral localhost port."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):
            pass

        def _send(self, code: int, body: Dict[str, Any]) -> None:
            data = json.dumps(body).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _error(self, e: ApiError) -> None:
            self._send(e.status, {"kind": "Status", "code": e.status,
                                  "message": e.message})

        def _body(self) -> Dict[str, Any]:
            n = int(self.headers.get("Content-Length") or 0)
            return json.loads(self.rfile.read(n)) if n else {}

        def do_GET(self):
            url = urlparse(self.path)
            q = parse_qs(url.query)
            try:
                api_version, plural, ns, name, _ = _parse_path(url.path)
                kind = _KIND_BY_PLURAL.get(plural, plural.rstrip("s").title())
                if q.get("watch") == ["true"]:
                    return self._watch(api_version, kind, ns)
                if name:
                    obj = fake.get(api_version, kind, ns, name)
                    if obj is None:
                        raise NotFound(404, f"{kind} {name}")
                    return self._send(200, obj)
                sel = (q.get("labelSelector") or [None])[0]
                items = fake.list(api_version, kind, ns, sel)
                return self._send(200, {"kind": f"{kind}List",
                                        "items": items})
            except ApiError as e:
                return self._error(e)

        def _watch(self, api_version, kind, ns):
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            stop = threading.Event()
            try:
                for evt in fake.watch(api_version, kind, ns, stop=stop):
                    data = (json.dumps(evt) + "\n").encode()
                    self.wfile.write(f"{len(data):x}\r\n".encode() + data
                                     + b"\r\n")
                    self.wfile.flush()
            except (BrokenPipeError, ConnectionResetError):
                pass
            finally:
                stop.set()
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

        def do_POST(self):
            try:
                obj = self._body()
                return self._send(201, fake.create(obj))
            except ApiError as e:
                return self._error(e)

        def do_PUT(self):
            url = urlparse(self.path)
            try:
                _, _, _, _, sub = _parse_path(url.path)
                obj = self._body()
                if sub == "status":
                    return self._send(200, fake.update_status(obj))
                return self._send(200, fake.update(obj))
            except ApiError as e:
                return self._error(e)

        def do_DELETE(self):
            url = urlparse(self.path)
            try:
                api_version, plural, ns, name, _ = _parse_path(url.path)
                kind = _KIND_BY_PLURAL.get(plural, plural.rstrip("s").title())
                fake.delete(api_version, kind, ns, name)
                return self._send(200, {"kind": "Status", "status": "Success"})
            except ApiError as e:
                return self._error(e)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd
