"""In-process fake ollama registry for tests — the analog of the reference's
envtest trick (real protocol, no external service)."""

import hashlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ollama_operator_tpu.server.registry import (
    MT_MODEL, MT_PARAMS, MT_SYSTEM, MT_TEMPLATE)


class FakeRegistry:
    def __init__(self):
        self.blobs = {}        # digest -> bytes
        self.manifests = {}    # (ns, name, tag) -> manifest dict
        self.requests = []     # log of (method, path, headers)
        self.httpd = None
        self.port = None

    def add_blob(self, data: bytes) -> dict:
        digest = "sha256:" + hashlib.sha256(data).hexdigest()
        self.blobs[digest] = data
        return {"digest": digest, "size": len(data)}

    def add_model(self, ns: str, name: str, tag: str, gguf_bytes: bytes,
                  template: str = None, params: dict = None,
                  system: str = None, projector_bytes: bytes = None):
        layers = [{"mediaType": MT_MODEL, **self.add_blob(gguf_bytes)}]
        if projector_bytes:
            from ollama_operator_tpu.server.registry import MT_PROJECTOR
            layers.append({"mediaType": MT_PROJECTOR,
                           **self.add_blob(projector_bytes)})
        if template:
            layers.append({"mediaType": MT_TEMPLATE,
                           **self.add_blob(template.encode())})
        if system:
            layers.append({"mediaType": MT_SYSTEM,
                           **self.add_blob(system.encode())})
        if params:
            layers.append({"mediaType": MT_PARAMS,
                           **self.add_blob(json.dumps(params).encode())})
        config = self.add_blob(json.dumps({"model_format": "gguf"}).encode())
        self.manifests[(ns, name, tag)] = {
            "schemaVersion": 2,
            "mediaType": "application/vnd.docker.distribution.manifest.v2+json",
            "config": {"mediaType":
                       "application/vnd.docker.container.image.v1+json",
                       **config},
            "layers": layers,
        }

    def start(self, host: str = "127.0.0.1", port: int = 0):
        reg = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                reg.requests.append(("GET", self.path,
                                     dict(self.headers)))
                parts = self.path.strip("/").split("/")
                # /v2/<ns>/<name>/manifests/<tag>
                if len(parts) >= 5 and parts[0] == "v2" and \
                        parts[-2] == "manifests":
                    key = ("/".join(parts[1:-2]), )  # ns may contain /
                    ns = "/".join(parts[1:-3])
                    name, tag = parts[-3], parts[-1]
                    m = reg.manifests.get((ns, name, tag))
                    if m is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    body = json.dumps(m).encode()
                    self.send_response(200)
                    self.send_header("Content-Type", m["mediaType"])
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if len(parts) >= 5 and parts[0] == "v2" and \
                        parts[-2] == "blobs":
                    digest = parts[-1]
                    data = reg.blobs.get(digest)
                    if data is None:
                        self.send_response(404)
                        self.end_headers()
                        return
                    rng = self.headers.get("Range")
                    if rng and rng.startswith("bytes="):
                        start = int(rng[6:].split("-")[0])
                        chunk = data[start:]
                        self.send_response(206)
                    else:
                        chunk = data
                        self.send_response(200)
                    self.send_header("Content-Length", str(len(chunk)))
                    self.end_headers()
                    self.wfile.write(chunk)
                    return
                self.send_response(404)
                self.end_headers()

            # --- push support (docker registry v2 upload flow) --------
            def do_HEAD(self):
                reg.requests.append(("HEAD", self.path, dict(self.headers)))
                parts = self.path.strip("/").split("/")
                if len(parts) >= 5 and parts[0] == "v2" and \
                        parts[-2] == "blobs":
                    if parts[-1] in reg.blobs:
                        self.send_response(200)
                        self.send_header("Content-Length",
                                         str(len(reg.blobs[parts[-1]])))
                        self.end_headers()
                        return
                self.send_response(404)
                self.end_headers()

            def do_POST(self):
                reg.requests.append(("POST", self.path, dict(self.headers)))
                parts = self.path.strip("/").split("/")
                # /v2/<ns>/<name>/blobs/uploads/
                if len(parts) >= 5 and parts[0] == "v2" and \
                        parts[-2] == "blobs" or (parts and
                                                 parts[-1] == "uploads"):
                    import uuid
                    loc = self.path.rstrip("/") + "/" + uuid.uuid4().hex
                    self.send_response(202)
                    self.send_header("Location", loc)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(404)
                self.end_headers()

            def do_PUT(self):
                reg.requests.append(("PUT", self.path, dict(self.headers)))
                n = int(self.headers.get("Content-Length") or 0)
                data = self.rfile.read(n) if n else b""
                parts = self.path.split("?")[0].strip("/").split("/")
                query = self.path.split("?", 1)[1] if "?" in self.path else ""
                if "uploads" in parts and "digest=" in query:
                    digest = [q[7:] for q in query.split("&")
                              if q.startswith("digest=")][0]
                    actual = "sha256:" + hashlib.sha256(data).hexdigest()
                    if digest != actual:
                        self.send_response(400)
                        self.end_headers()
                        return
                    reg.blobs[digest] = data
                    self.send_response(201)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                if len(parts) >= 5 and parts[0] == "v2" and \
                        parts[-2] == "manifests":
                    ns = "/".join(parts[1:-3])
                    name, tag = parts[-3], parts[-1]
                    reg.manifests[(ns, name, tag)] = json.loads(data)
                    self.send_response(201)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(404)
                self.end_headers()

        self.httpd = ThreadingHTTPServer((host, port), H)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        # loopback URL for in-process callers; 0.0.0.0 binds are reached
        # by cluster DNS, not this return value
        url_host = "127.0.0.1" if host in ("0.0.0.0", "") else host
        return f"http://{url_host}:{self.port}"

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()


def add_tiny_model(reg, *, template="{{ .Prompt }}", params=None,
                   gguf_path=None):
    """Deterministic tiny-llama fixture shared by the compose e2e and the
    in-cluster kind-e2e registry (hack/fake_registry_entry.py) — one
    recipe, so the two e2e tiers can never diverge."""
    import os
    import tempfile

    import jax
    import jax.numpy as jnp

    from ollama_operator_tpu.models import config as cfglib, decoder
    from test_transcode import write_tiny_llama_gguf

    cfg = cfglib.PRESETS["tiny"]
    model_params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                       dtype=jnp.float32)
    path = gguf_path or os.path.join(tempfile.mkdtemp(), "tiny.gguf")
    write_tiny_llama_gguf(path, cfg, model_params)
    with open(path, "rb") as f:
        reg.add_model(
            "library", "tiny", "latest", f.read(), template=template,
            params=params if params is not None
            else {"temperature": 0.0, "num_predict": 16})
    return "library/tiny:latest"
