"""determinism fixture: wall clock, process entropy, unsorted set
iteration — plus the allowed forms."""

import random
import time

PAGES = set([3, 1, 2])


def replayed():
    t = time.time()
    r = random.random()
    for x in {1, 2}:
        pass
    for y in PAGES:
        pass
    for z in sorted(PAGES):
        pass
    ok = time.monotonic()
    return t, r, ok
