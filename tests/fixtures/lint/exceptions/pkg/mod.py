"""exception-hygiene fixture: bare except, silent swallow, justified
suppression, reasonless suppression, and a legal narrow handler."""


def g():
    raise ValueError("boom")


def f():
    try:
        g()
    except:
        pass
    try:
        g()
    except Exception:
        pass
    try:
        g()
    except Exception:  # lint: allow(exception-hygiene): fixture-justified teardown
        pass
    try:
        g()
    except Exception:  # lint: allow(exception-hygiene)
        pass
    try:
        g()
    except ValueError:
        pass
