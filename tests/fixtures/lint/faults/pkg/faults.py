"""Fixture fault-point catalog: one healthy point, one stale, one
undocumented in the zh tree."""


def point(name, site, doc):
    return (name, site, doc)


point("fix.ok", "pkg/mod.py", "checked and documented everywhere")
point("fix.stale", "pkg/mod.py", "registered but never checked")
point("fix.nodoc", "pkg/mod.py", "checked but missing from docs/zh")
