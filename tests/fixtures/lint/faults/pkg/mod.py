"""Fixture check sites: a healthy one, an uncatalogued one, a computed
one, and a suppressed uncatalogued one."""

FAULTS = object()


def healthy():
    FAULTS.check("fix.ok")
    FAULTS.check("fix.nodoc")


def ghost():
    FAULTS.check("fix.ghost")


def computed(name):
    FAULTS.check(name)


def tolerated():
    # lint: allow(fault-catalog): fixture exercises suppression
    FAULTS.check("fix.tolerated")
