"""follower-purity fixture: a handler reaching a forbidden singleton
through a helper."""

FLIGHT = None


def run_follower(sock):
    while True:
        handle_op(sock)


def handle_op(sock):
    FLIGHT.record("replay_error")


def unrelated():
    FLIGHT.record("fine here — not reachable from the handler")
