"""host-sync fixture: violations in the hot call graph, a sanctioned
wait boundary, a suppressed site, and a cold function that must NOT be
flagged."""

import numpy as np


class Engine:
    def decode_n_launch(self):
        self._helper()
        return Handle()

    def _helper(self):
        a = self.scalar.item()
        b = np.asarray(self.buf)
        c = int(self.lengths[0])
        d = self.arr.block_until_ready()  # lint: allow(host-sync-hot-path): fixture exercises suppression
        return a, b, c, d

    def cold(self):
        return self.scalar.item()


class Handle:
    def wait(self):
        return self.fut.item()
