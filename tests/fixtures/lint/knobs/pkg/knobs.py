"""Fixture knob registry: one live declaration, one stale."""


def declare(name, type, default, subsystem, doc):
    return name


declare("TPU_FIX_A", "bool", 1, "fixture", "declared and read")
declare("TPU_FIX_STALE", "int", 0, "fixture", "declared, never mentioned")
