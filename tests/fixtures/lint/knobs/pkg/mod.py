"""knob-registry fixture: one declared read, one undeclared read, one
suppressed undeclared read."""

import os

DECLARED = os.environ.get("TPU_FIX_A", "1")

UNDECLARED = os.environ["TPU_FIX_B"]

SUPPRESSED = os.getenv("TPU_FIX_SUPP", "")  # lint: allow(knob-registry): fixture exercises suppression
