"""lock-order fixture: an ABBA cycle, direct and transitive blocking
calls under a lock, and a legal RLock re-entry."""

import threading
import time


class A:
    def __init__(self):
        self._la = threading.Lock()
        self.sock = None

    def one(self, b):
        with self._la:
            with b._lb:
                pass

    def sleepy(self):
        with self._la:
            time.sleep(1)

    def indirect(self):
        with self._la:
            self._push()

    def _push(self):
        self.sock.sendall(b"x")


class B:
    def __init__(self):
        self._lb = threading.Lock()

    def two(self, a):
        with self._lb:
            with a._la:
                pass


class R:
    def __init__(self):
        self._lr = threading.RLock()

    def reenter(self):
        with self._lr:
            with self._lr:
                pass
