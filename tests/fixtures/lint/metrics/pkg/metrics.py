"""Fixture metric registry: describes + pre-seeds two families."""


class Metrics:
    def describe(self, name, text):
        pass

    def inc(self, name, value=1.0, labels=""):
        pass


GLOBAL = Metrics()

GLOBAL.describe("tpu_model_fix_ok_total", "plain counter")
GLOBAL.describe("tpu_model_fix_labeled_total", "labeled counter")

for _n in ("tpu_model_fix_ok_total",):
    GLOBAL.inc(_n, 0.0)

for _cause in ("a", "b"):
    GLOBAL.inc("tpu_model_fix_labeled_total", 0.0, f'{{cause="{_cause}"}}')
