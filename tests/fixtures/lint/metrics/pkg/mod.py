"""metrics-discipline fixture: good increments, an undescribed+unseeded
family, and a label-key-set mismatch."""

from .metrics import GLOBAL


def record(cause):
    GLOBAL.inc("tpu_model_fix_ok_total")
    GLOBAL.inc("tpu_model_fix_labeled_total", 1.0, f'{{cause="{cause}"}}')
    GLOBAL.inc("tpu_model_fix_missing_total")
    GLOBAL.inc("tpu_model_fix_labeled_total", 1.0, f'{{other="{cause}"}}')
