"""The 70B north star stops being a paper claim (VERDICT r2 weak #8).

BASELINE.md config 4 / SURVEY §7 hard part 3: llama2:70b tensor-sharded
across a v5e-16 slice. Real multi-chip hardware isn't reachable here, so
the checkable halves are proven on CPU: the REAL-dimension program (80
layers, dim 8192, GQA 8:1) compiles over a virtual 16-device mesh, and the
per-device byte budget (int8 params + KV) fits a v5e chip's HBM.

Runs hack/prog_70b.py in a subprocess — the proof needs 16 virtual devices
while the suite's conftest pins this process to 8.
"""

import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "hack", "prog_70b.py")


@pytest.fixture(scope="module")
def proof():
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=16")
    r = subprocess.run([sys.executable, WORKER], env=env, cwd=ROOT,
                       capture_output=True, text=True, timeout=1500)
    assert r.returncode == 0, f"worker failed:\n{r.stderr[-4000:]}"
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_real_dims(proof):
    assert proof["model"] == "llama2:70b"
    assert proof["n_devices"] == 16
    # ~70B weights at int8 + scales; a shape-reduced config would be far
    # smaller and void the proof
    assert proof["global_param_gb"] > 60


def test_programs_compile_and_fit(proof):
    plans = {p["plan"]: p for p in proof["programs"]}
    assert set(plans) == {"tp8xsp2", "tp8xdp2"}
    for p in plans.values():
        assert p["compiled"]
        assert p["fits_v5e"]
        # exact shard accounting: tp8 splits the int8 params 8 ways
        assert p["per_device_param_gb"] == pytest.approx(
            proof["global_param_gb"] / 8, rel=0.02)
        assert p["per_device_total_gb"] < 14.5


def test_paged_pool_compiles_and_fits(proof):
    pool = proof["paged_pool"]
    assert pool["compiled"]          # real-dims paged decode program
    assert pool["slots"] == 32 and pool["fits_v5e"]
    assert pool["per_device_total_gb"] < 14.5


def test_collectives_priced(proof):
    """The 1000-tok/s projection must price tp8 communication (VERDICT r4
    #6): the partitioned HLO's collective sites corroborate the analytic
    model hack/roofline_70b.py charges — 2 all-reduces per layer (o-proj,
    down-proj psums, reduced at **f32**) riding the layer loop. The
    check is BYTES, not op count (GSPMD may fuse/split sites): got must
    land in [1.0x, 1.5x] of the 2·L·B_local·dim·f32 analytic (the slack
    covers the small s32/s8 index all-gathers, ~12% observed). If this
    trips after a JAX/XLA upgrade, check hack/prog_70b.collective_stats'
    HLO parsing FIRST (async -start forms, outlined computations) before
    suspecting the partitioner."""
    plans = {p["plan"]: p for p in proof["programs"]}
    coll = plans["tp8xdp2"]["collectives"]
    assert coll["n_in_layer_loop"] >= 2, "no collectives in the layer loop"
    # analytic logical bytes: 2 ARs/layer x [B_local=8, dim] f32 (the
    # compiled HLO reduces at f32); index all-gathers for the dp-sharded
    # cache scatter add ~12%
    analytic = 80 * 2 * 8 * 8192 * 4
    got = coll["logical_bytes_per_step"]
    assert analytic <= got <= analytic * 1.5, (got, analytic)


def test_int4_quarter_slice(proof):
    """llama2:70b int4 on a v5e-4 — a QUARTER of the north-star slice:
    packed nibbles + f32 scales ≈ 0.63 B/weight, and the real-dimension
    tp4 decode program compiles with collectives present."""
    q = proof["int4_quarter_slice"]
    assert q["compiled"] and q["fits_v5e"]
    # ~0.63 B/weight on ~69B params
    assert 40 < q["global_param_gb"] < 48
    assert q["per_device_param_gb"] == pytest.approx(
        q["global_param_gb"] / 4, rel=0.02)
    assert q["per_device_total_gb"] < 14.5
