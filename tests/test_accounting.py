"""Utilization & goodput accounting (runtime/accounting.py).

The FLOPs formulas are checked against hand-computed values for two
model configs (tiny and llama2) plus the MoE and sliding-window
variants; the goodput/occupancy split is checked across padded buckets
including spec k>0 and chunked prefill; the engine's recompile detector
must fire exactly once per unwarmed executable signature and never for
AOT-warmed ones.
"""

import dataclasses
import time

import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.runtime import accounting
from ollama_operator_tpu.runtime.accounting import (NULL_ACCOUNTING,
                                                    UtilizationAccounting,
                                                    attn_span_flops,
                                                    decode_flops,
                                                    detect_peak_flops,
                                                    make_accounting,
                                                    per_token_flops,
                                                    prefill_flops,
                                                    spec_verify_flops,
                                                    _ctx_sum)
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

TINY = cfglib.PRESETS["tiny"]
LLAMA2 = cfglib.PRESETS["llama2"]
TINY_MOE = cfglib.PRESETS["tiny-moe"]


# -- per-position FLOPs vs hand-computed values ------------------------

def test_per_token_flops_tiny_hand_computed():
    # tiny: d=64 q=64 kv=32 L=2 ffn=128 vocab=256, gated MLP
    # proj = 2*(64*64 + 2*64*32 + 64*64) = 24576
    # mlp  = 6*64*128                    = 49152
    # head = 2*64*256                    = 32768
    assert per_token_flops(TINY) == 2 * (24576 + 49152) + 32768 == 180224


def test_per_token_flops_llama2_hand_computed():
    # llama2 7B: d=4096 q=kv=4096 L=32 ffn=11008 vocab=32000
    # proj = 2*4*4096^2        = 134217728
    # mlp  = 6*4096*11008      = 270532608
    # head = 2*4096*32000      = 262144000
    expect = 32 * (134217728 + 270532608) + 262144000
    assert per_token_flops(LLAMA2) == expect == 13214154752
    # sanity: ~2 FLOPs per weight per token for a 7B-class model
    assert 1.8 * LLAMA2.n_params < expect < 2.5 * LLAMA2.n_params


def test_per_token_flops_moe_counts_topk_plus_router():
    # tiny-moe: 4 experts top-2 → mlp = 2*(6*64*128) + router 2*64*4
    expect = 2 * (24576 + (2 * 49152 + 512)) + 32768
    assert per_token_flops(TINY_MOE) == expect == 279552


def test_ctx_sum_closed_forms():
    # pure arithmetic series
    assert _ctx_sum(0, 4) == 1 + 2 + 3 + 4
    assert _ctx_sum(9, 2) == 10 + 11
    # window caps: linear head then flat tail
    assert _ctx_sum(0, 16, window=8) == sum(min(p + 1, 8) for p in range(16))
    # fully capped span
    assert _ctx_sum(10, 4, window=8) == 4 * 8
    assert _ctx_sum(5, 0) == 0.0


def test_attn_span_and_prefill_tiny_hand_computed():
    # tiny is full attention on both layers: span [0,4) attends 1+2+3+4
    # keys per layer, 4*q_dim FLOPs per key
    assert attn_span_flops(TINY, 0, 4) == 4 * 64 * (2 * 10) == 5120
    assert prefill_flops(TINY, 0, 4) == 4 * 180224 + 5120


def test_decode_flops_continues_the_series():
    # 2 steps from 10 attended keys: steps attend 10 then 11
    assert decode_flops(TINY, 10, 2) == 2 * 180224 + 4 * 64 * (2 * 21)
    # decode IS a width-n prefill starting one position back
    assert decode_flops(TINY, 10, 2) == prefill_flops(TINY, 9, 2)


def test_spec_verify_is_a_k_plus_1_prefill():
    assert spec_verify_flops(TINY, 10, 3) == prefill_flops(TINY, 9, 4)
    assert spec_verify_flops(LLAMA2, 100, 4) == prefill_flops(LLAMA2, 99, 5)


def test_sliding_window_layers_split_and_cap():
    sw = dataclasses.replace(TINY, sliding_window=8)
    # all layers sliding: span past the window costs window keys/step
    assert attn_span_flops(sw, 100, 2) == 4 * 64 * (2 * 2 * 8)
    # gemma-style alternation: layer i%3==2 is full, rest sliding
    alt = dataclasses.replace(TINY, n_layers=6, sliding_window=8,
                              altern_sliding=True, sliding_pattern=3)
    full_keys = _ctx_sum(100, 2)
    assert attn_span_flops(alt, 100, 2) == \
        4 * 64 * (2 * full_keys + 4 * 2 * 8)


# -- peak detection ----------------------------------------------------

def test_peak_flops_env_override(monkeypatch):
    monkeypatch.setenv("TPU_PEAK_FLOPS", "197e12")
    peak, kind = detect_peak_flops()
    assert peak == 197e12 and kind == "override"


def test_peak_flops_bad_override_falls_through(monkeypatch):
    monkeypatch.setenv("TPU_PEAK_FLOPS", "not-a-number")
    peak, kind = detect_peak_flops()
    assert kind != "override"


# -- goodput / occupancy accumulator -----------------------------------

def make_acct(cfg=TINY, peak=1e12):
    return UtilizationAccounting(cfg, peak_flops=peak, device_kind="unit")


def _rebucket(acct, ago=2):
    """Move everything in the ring a couple of seconds into the past so
    snapshot()'s in-progress-second exclusion doesn't hide it."""
    with acct._lock:
        cells = list(acct._ring.values())
        acct._ring.clear()
        merged = [sum(c[i] for c in cells) for i in range(4)]
        acct._ring[int(time.monotonic()) - ago] = merged


def test_decode_goodput_counts_padded_slots():
    acct = make_acct()
    acct.on_decode(0.01, ctxs=[5, 9], n_steps=4, capacity=4)
    assert acct.useful_tokens["decode"] == 8      # 2 active x 4 steps
    assert acct.padded_tokens["decode"] == 8      # 2 empty slots x 4
    expect = (4 * per_token_flops(TINY) + attn_span_flops(TINY, 4, 4)
              + 4 * per_token_flops(TINY) + attn_span_flops(TINY, 8, 4))
    assert acct.model_flops == pytest.approx(expect)


def test_spec_goodput_counts_rejected_drafts_as_waste():
    acct = make_acct()
    # 2-slot bucket, k=3 → 8 issued positions; only 3 tokens advanced
    acct.on_spec(0.01, ctxs=[10, 12], k=3, emitted=3.0, capacity=2)
    assert acct.useful_tokens["spec"] == 3
    assert acct.padded_tokens["spec"] == 5
    expect = spec_verify_flops(TINY, 10, 3) + spec_verify_flops(TINY, 12, 3)
    assert acct.model_flops == pytest.approx(expect)


def test_prefill_goodput_counts_bucket_padding():
    acct = make_acct()
    acct.on_prefill(0.01, start=0, n_new=10, bucket=16)
    assert acct.useful_tokens["prefill"] == 10
    assert acct.padded_tokens["prefill"] == 6
    # chunked prefill: the second piece starts where the first ended and
    # fills its bucket exactly → no extra padding
    acct.on_prefill(0.01, start=10, n_new=16, bucket=16)
    assert acct.useful_tokens["prefill"] == 26
    assert acct.padded_tokens["prefill"] == 6
    expect = prefill_flops(TINY, 0, 10) + prefill_flops(TINY, 10, 16)
    assert acct.model_flops == pytest.approx(expect)


def test_snapshot_occupancy_waste_and_mfu():
    acct = make_acct(peak=1e9)
    acct.on_decode(0.02, ctxs=[5, 9, 11], n_steps=4, capacity=4)
    _rebucket(acct)
    snap = acct.snapshot(window_s=60)
    assert snap["enabled"] is True
    assert snap["occupancy"] == pytest.approx(12 / 16)
    assert snap["waste_pct"] == pytest.approx(25.0)
    assert snap["mfu"] is not None and snap["mfu"] > 0
    assert snap["totals"]["useful_tokens"]["decode"] == 12
    assert snap["totals"]["dispatches"]["decode"] == 1
    assert snap["busy_s"] == pytest.approx(0.02)


def test_snapshot_without_peak_reads_null_mfu():
    acct = make_acct(peak=0.0)
    acct.on_decode(0.01, ctxs=[5], n_steps=1, capacity=1)
    _rebucket(acct)
    snap = acct.snapshot()
    assert snap["mfu"] is None and snap["peak_flops"] is None
    assert snap["occupancy"] == 1.0 and snap["waste_pct"] == 0.0


def test_breakdown_classifies_wait_idle_host():
    acct = make_acct()
    acct.on_wait(0.5)
    acct.on_idle(0.25)
    bd = acct.breakdown()
    assert bd["dispatch_wait_s"] == pytest.approx(0.5)
    assert bd["idle_s"] == pytest.approx(0.25)
    assert bd["wall_s"] >= 0 and bd["host_s"] >= 0


def test_ring_is_bounded_and_ordered():
    acct = make_acct()
    base = int(time.monotonic())
    with acct._lock:
        # backfill strictly-past seconds; the next dispatch opens the
        # current second's cell, which is what triggers the prune
        for i in range(1, accounting.RING_SECONDS + 41):
            acct._ring[base - i] = [1.0, 1.0, 0.0, 0.0]
    acct.on_decode(0.001, ctxs=[5], n_steps=1, capacity=1)  # prunes
    assert len(acct._ring) <= accounting.RING_SECONDS + 9
    rows = acct.ring(last=10)
    assert len(rows) == 10
    assert [r["t_rel_s"] for r in rows] == \
        sorted(r["t_rel_s"] for r in rows)


def test_counters_mirror_totals():
    before = METRICS.get("tpu_model_useful_tokens_total",
                         '{kind="decode"}')
    flops0 = METRICS.get("tpu_model_model_flops_total")
    acct = make_acct()
    acct.on_decode(0.01, ctxs=[5, 6], n_steps=3, capacity=4)
    assert METRICS.get("tpu_model_useful_tokens_total",
                       '{kind="decode"}') == before + 6
    assert METRICS.get("tpu_model_model_flops_total") > flops0


def test_kill_switch_returns_shared_null(monkeypatch):
    monkeypatch.setattr(accounting, "ACCOUNTING_ENABLED", False)
    acct = make_accounting(TINY)
    assert acct is NULL_ACCOUNTING and acct.enabled is False
    acct.on_decode(0.01, ctxs=[5], n_steps=1, capacity=1)   # inert
    assert acct.snapshot() == {"enabled": False}
    assert acct.ring() == []
    monkeypatch.setattr(accounting, "ACCOUNTING_ENABLED", True)
    assert make_accounting(TINY).enabled is True


def test_accounting_without_cfg_is_safe():
    acct = UtilizationAccounting(None, peak_flops=1e12)
    acct.on_decode(0.01, ctxs=[5], n_steps=1, capacity=1)
    acct.on_prefill(0.01, 0, 4, 16)
    acct.on_spec(0.01, ctxs=[5], k=2, emitted=1, capacity=1)
    assert acct.model_flops == 0.0


# -- recompile detector (engine-level) ---------------------------------

def test_recompile_detector_fires_once_per_unwarmed_signature():
    from ollama_operator_tpu.runtime.trace import FLIGHT

    from test_scheduler import GREEDY, make_stack
    cfg, params, eng, sched = make_stack(slots=2)
    sched.shutdown()
    rc_metric0 = METRICS.get("tpu_model_recompiles_total",
                             '{kind="decode"}')
    seq0 = FLIGHT.seq
    prompt = np.array([1, 2, 3], np.int32)
    assert sum(eng.recompiles.values()) == 0
    eng.admit(0, prompt)
    assert eng.recompiles["admit"] == 1
    eng.release(0)
    eng.admit(0, prompt)                 # same bucket → cached executable
    assert eng.recompiles["admit"] == 1
    n_dec0 = eng.recompiles["decode"]
    eng.decode_n()
    assert eng.recompiles["decode"] == n_dec0 + 1
    assert METRICS.get("tpu_model_recompiles_total",
                       '{kind="decode"}') == rc_metric0 + n_dec0 + 1
    evs = [e for e in FLIGHT.snapshot()
           if e["seq"] > seq0 and e["kind"] == "recompile"]
    assert any(e["program"] == "admit" for e in evs)
    assert any(e["program"] == "decode" for e in evs)
    eng.release(0)


def test_recompile_detector_silent_after_aot_warm():
    from test_scheduler import make_stack
    cfg, params, eng, sched = make_stack(slots=2)
    sched.shutdown()
    eng.warm_buckets()
    assert sum(eng.recompiles.values()) == 0, \
        "AOT warm must register signatures, not count them"
    eng.admit(0, np.array([1, 2, 3], np.int32))
    eng.decode_n()
    assert sum(eng.recompiles.values()) == 0, \
        "warmed signatures must not count as mid-serving recompiles"
    eng.release(0)


def test_scheduler_surfaces_utilization_stats():
    from test_scheduler import GREEDY, make_stack
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        r = sched.submit(np.array([1, 2, 3], np.int32), GREEDY,
                         max_tokens=5)
        assert len(list(r.tokens())) == 5
        out = sched.utilization_stats()
        assert out["enabled"] is True
        assert out["totals"]["useful_tokens"]["decode"] >= 5
        assert out["totals"]["useful_tokens"]["prefill"] >= 3
        assert "recompiles" in out and isinstance(out["recompiles"], dict)
        assert out["breakdown"]["wall_s"] > 0
    finally:
        sched.shutdown()
