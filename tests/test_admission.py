"""Admission control: priority classes, WDRR tenant fairness, SLO-aware
early rejection, and per-tenant rate limiting (runtime/admission.py).

The invariants under test (ISSUE 8 acceptance):
- strict-priority dequeue (high before normal before best_effort) and
  shed-lowest-first displacement when the queue is full;
- weighted deficit round-robin serves tenants by TOKEN budget, not
  request count — 3 equal tenants each get 33±10% of the served tokens
  even when their per-request costs differ, and 2:1:1 weights track a
  2:1:1 token split;
- early-reject Retry-After is finite, clamped to [1, 120], and monotone
  in the backlog it is computed from;
- a best-effort stream throttled mid-generation by a tenant rate limit
  resumes on the same output queue with BIT-IDENTICAL greedy tokens;
- a queued request whose deadline expires at the admission boundary is
  shed with 503 + Retry-After, never admitted into a doomed prefill;
- chaos: an engine failure mid-overload restarts supervised, queued
  requests keep their class ordering, and the in-flight request errors
  exactly once.
"""

import itertools
import threading
import time
import types

import numpy as np
import pytest

from ollama_operator_tpu.runtime.admission import (
    PRIORITIES, PRIORITY_RANK, AdmissionQueue, TenantRateLimiter,
    resolve_priority, resolve_tenant, resolve_ttft_slo_s, retry_after_s,
    shed_labels, tenant_from_key)
from ollama_operator_tpu.runtime.errors import BadRequest, DeadlineExceeded
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.scheduler import (SchedulerBusy,
                                                   SchedulerOverloaded)
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

from test_scheduler import GREEDY, make_stack
from test_stall_free import manual


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# -- fake requests for queue-only unit tests ---------------------------

_seq = itertools.count()


def fake(priority="normal", tenant="default", cost=32.0):
    """The slice of Request the AdmissionQueue actually touches."""
    r = types.SimpleNamespace(
        priority=priority, rank=PRIORITY_RANK[priority], tenant=tenant,
        cost=float(cost),
        stats=types.SimpleNamespace(t_submit=float(next(_seq))))
    return r


# -- option resolution -------------------------------------------------

def test_resolve_priority_precedence(monkeypatch):
    assert resolve_priority(None, None) == "normal"
    monkeypatch.setenv("TPU_DEFAULT_PRIORITY", "best_effort")
    assert resolve_priority(None, None) == "best_effort"
    # Modelfile default beats env; request beats Modelfile
    assert resolve_priority({"priority": "normal"}, None) == "normal"
    assert resolve_priority({"priority": "normal"},
                            {"priority": "HIGH"}) == "high"
    with pytest.raises(BadRequest):
        resolve_priority(None, {"priority": "urgent"})


def test_resolve_tenant_sanitises():
    assert resolve_tenant(None) == "default"
    assert resolve_tenant({"tenant": "team-a"}) == "team-a"
    hashed = resolve_tenant({"tenant": "spaces and \n junk"})
    assert hashed.startswith("t-") and len(hashed) == 14
    # hashing is stable — the same ugly name lands in the same bucket
    assert hashed == resolve_tenant({"tenant": "spaces and \n junk"})


def test_tenant_from_key_never_leaks_the_key():
    t = tenant_from_key("Bearer super-secret-key")
    assert "super-secret-key" not in t
    assert t.startswith("key-")
    assert t == tenant_from_key("super-secret-key")  # prefix-insensitive
    assert tenant_from_key("   ") == "default"


def test_resolve_ttft_slo(monkeypatch):
    assert resolve_ttft_slo_s(None, None) is None
    assert resolve_ttft_slo_s(None, {"ttft_slo_ms": 250}) == 0.25
    assert resolve_ttft_slo_s(None, {"ttft_slo_ms": 0}) is None
    monkeypatch.setenv("TPU_TTFT_SLO_MS", "500")
    assert resolve_ttft_slo_s(None, None) == 0.5
    with pytest.raises(BadRequest):
        resolve_ttft_slo_s(None, {"ttft_slo_ms": "soon"})


# -- strict-priority dequeue and displacement --------------------------

def test_priority_dequeue_ordering():
    q = AdmissionQueue(max_queue=16, weights={}, quantum=64)
    # arrival order deliberately inverted vs priority
    order_in = ["best_effort", "normal", "high", "best_effort", "high",
                "normal"]
    for p in order_in:
        q.offer(fake(p))
    out = []
    while True:
        r = q.pop()
        if r is None:
            break
        out.append(r.priority)
    assert out == ["high", "high", "normal", "normal",
                   "best_effort", "best_effort"]


def test_offer_displaces_newest_lowest_class():
    q = AdmissionQueue(max_queue=3, weights={}, quantum=64)
    be_old = fake("best_effort")
    nm = fake("normal")
    be_new = fake("best_effort")
    for r in (be_old, nm, be_new):
        assert q.offer(r) == (True, None)
    # full: a high arrival displaces the NEWEST best_effort, not the old
    accepted, victim = q.offer(fake("high"))
    assert accepted and victim is be_new
    # full of equal-or-higher classes: the lowest incoming is rejected
    accepted, victim = q.offer(fake("best_effort"))
    assert (accepted, victim) == (False, None)
    # ...and rank counts: a normal cannot displace another normal
    q2 = AdmissionQueue(max_queue=1, weights={}, quantum=64)
    q2.offer(fake("normal"))
    assert q2.offer(fake("normal")) == (False, None)


def test_backlog_tokens_counts_equal_or_higher_priority():
    q = AdmissionQueue(max_queue=16, weights={}, quantum=64)
    q.offer(fake("high", cost=100))
    q.offer(fake("normal", cost=10))
    q.offer(fake("best_effort", cost=1))
    assert q.backlog_tokens(PRIORITY_RANK["high"]) == 100
    assert q.backlog_tokens(PRIORITY_RANK["normal"]) == 110
    assert q.backlog_tokens(PRIORITY_RANK["best_effort"]) == 111


# -- WDRR token-budget fairness ----------------------------------------

def _served_shares(q, tenants, n_pops):
    served = {t: 0.0 for t in tenants}
    for _ in range(n_pops):
        r = q.pop()
        assert r is not None
        served[r.tenant] += r.cost
    total = sum(served.values())
    return {t: served[t] / total for t in tenants}


def test_wdrr_equal_weights_equal_token_shares():
    """Equal weights, UNEQUAL request costs: tenant a sends 64-token
    requests, b and c send 32-token ones — token shares still equalise
    (a is served half as many requests). Request-count round-robin
    would give a a 50% token share here."""
    q = AdmissionQueue(max_queue=10_000, weights={}, quantum=32)
    for _ in range(40):
        q.offer(fake("normal", "a", cost=64))
        q.offer(fake("normal", "b", cost=32))
        q.offer(fake("normal", "c", cost=32))
    # measure inside the backlogged window only (all tenants nonempty)
    shares = _served_shares(q, "abc", 60)
    for t in "abc":
        assert abs(shares[t] - 1 / 3) <= 0.05, \
            f"tenant {t} token share {shares[t]:.3f} not ~1/3"


def test_wdrr_weighted_2_1_1():
    q = AdmissionQueue(max_queue=10_000,
                       weights={"a": 2.0, "b": 1.0, "c": 1.0}, quantum=32)
    for _ in range(60):
        for t in "abc":
            q.offer(fake("normal", t, cost=32))
    shares = _served_shares(q, "abc", 80)
    assert abs(shares["a"] - 0.50) <= 0.05, shares
    assert abs(shares["b"] - 0.25) <= 0.05, shares
    assert abs(shares["c"] - 0.25) <= 0.05, shares


def test_wdrr_idle_tenant_accrues_no_credit():
    """Classic DRR: a tenant that drains and re-enters starts from a
    clean deficit — idling must not bank a burst allowance."""
    q = AdmissionQueue(max_queue=10_000, weights={}, quantum=32)
    q.offer(fake("normal", "a", cost=32))
    assert q.pop().tenant == "a"          # a drains and goes idle
    for _ in range(10):
        q.offer(fake("normal", "b", cost=32))
    q.offer(fake("normal", "a", cost=32))  # a re-enters
    got = [q.pop().tenant for _ in range(6)]
    # a gets its fair alternating share, not a catch-up burst
    assert got.count("a") == 1


# -- Retry-After: clamped, monotone ------------------------------------

def test_retry_after_unit_monotone_and_clamped():
    waits = [0.0, 0.5, 2.0, 10.0, 50.0, 1e9]
    vals = [retry_after_s(w, 1.0, 100.0) for w in waits]
    assert vals == sorted(vals)
    assert vals[0] == 1                    # floor
    assert vals[-1] == 120                 # ceiling
    assert all(1 <= v <= 120 for v in vals)


def test_early_reject_retry_after_monotone_in_backlog(monkeypatch):
    """Scheduler-level: with throughput pinned, a growing backlog must
    produce non-decreasing (and eventually growing) Retry-After values
    on consecutive early rejections."""
    monkeypatch.setenv("TPU_ADMIT_THROUGHPUT_TPS", "50")
    sched = manual(make_stack(slots=1)[3])
    try:
        retries = []
        for _ in range(3):
            for _ in range(5):   # grow the backlog by ~5 requests
                sched.submit(np.arange(1, 9, dtype=np.int32), GREEDY,
                             max_tokens=32)
            with pytest.raises(SchedulerOverloaded) as ei:
                sched.submit(np.arange(1, 9, dtype=np.int32), GREEDY,
                             max_tokens=32, ttft_slo_s=0.001)
            retries.append(ei.value.retry_after_s)
        assert retries == sorted(retries)
        assert retries[-1] > retries[0]
        assert all(1 <= r <= 120 for r in retries)
    finally:
        sched.shutdown()


def test_slo_predictor_fails_open(monkeypatch):
    """An armed admission.predict fault must ADMIT the request (the
    predictor is an optimisation), never 500 it."""
    monkeypatch.setenv("TPU_ADMIT_THROUGHPUT_TPS", "50")
    sched = manual(make_stack(slots=1)[3])
    try:
        sched.submit(np.arange(1, 9, dtype=np.int32), GREEDY,
                     max_tokens=32)  # backlog > 0
        FAULTS.arm("admission.predict", "fail")
        r = sched.submit(np.arange(1, 9, dtype=np.int32), GREEDY,
                         max_tokens=32, ttft_slo_s=0.001)
        assert sched.qsize == 2 and r.error is None
    finally:
        FAULTS.reset()
        sched.shutdown()


# -- satellite 1: queue-full shed carries Retry-After + observes wait --

def test_queue_full_rejection_retry_after_and_wait_observed():
    sched = manual(make_stack(slots=1)[3])
    sched._admission.max_queue = 2
    try:
        for i in range(2):
            sched.submit(np.array([i + 1], np.int32), GREEDY,
                         max_tokens=8, priority="best_effort")
        h0 = METRICS._hists.get(("tpu_model_queue_wait_seconds", ""))
        n0 = h0.n if h0 else 0
        c0 = METRICS.get("tpu_model_shed_total",
                         shed_labels("best_effort", "queue_full"))
        with pytest.raises(SchedulerBusy) as ei:
            sched.submit(np.array([9], np.int32), GREEDY, max_tokens=8,
                         priority="best_effort")
        assert 1 <= ei.value.retry_after_s <= 120
        h1 = METRICS._hists.get(("tpu_model_queue_wait_seconds", ""))
        assert h1 is not None and h1.n == n0 + 1
        assert METRICS.get("tpu_model_shed_total",
                           shed_labels("best_effort",
                                       "queue_full")) == c0 + 1
    finally:
        sched.shutdown()


def test_queue_full_displacement_sheds_victim_with_retry_after():
    sched = manual(make_stack(slots=1)[3])
    sched._admission.max_queue = 2
    try:
        sched.submit(np.array([1], np.int32), GREEDY, max_tokens=8,
                     priority="normal")
        victim = sched.submit(np.array([2], np.int32), GREEDY,
                              max_tokens=8, priority="best_effort")
        high = sched.submit(np.array([3], np.int32), GREEDY, max_tokens=8,
                            priority="high")
        # the displaced best_effort request sees a 503-shaped shed
        with pytest.raises(DeadlineExceeded) as ei:
            list(victim.chunks())
        assert ei.value.while_queued
        assert 1 <= ei.value.retry_after_s <= 120
        # ...and the high request took its place in the line
        assert sched._admission.queued_for("default") == 2
        assert high.error is None
    finally:
        sched.shutdown()


# -- satellite 2: deadline re-checked at the admission boundary --------

def test_deadline_expiry_swept_while_queued_is_shed_503():
    sched = manual(make_stack(slots=1)[3])
    try:
        r = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=8,
                         deadline_s=0.01)
        time.sleep(0.03)
        sched._shed_expired()
        with pytest.raises(DeadlineExceeded) as ei:
            list(r.chunks())
        assert ei.value.while_queued
        assert ei.value.retry_after_s >= 1
    finally:
        sched.shutdown()


def test_deadline_recheck_at_admission_boundary():
    """A request can expire BETWEEN the queue pop and the engine touch
    (earlier admissions in the same pass block on prefill dispatches).
    The boundary re-check must shed it — a fresh request never burns a
    prefill on a guaranteed timeout."""
    sched = manual(make_stack(slots=1)[3])
    try:
        r = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=8,
                         deadline_s=0.01)
        popped = sched._admission.pop()
        assert popped is r
        time.sleep(0.03)                      # expires post-pop
        assert sched._expired_at_admission(r) is True
        with pytest.raises(DeadlineExceeded) as ei:
            list(r.chunks())
        assert ei.value.while_queued
        # a RESUMED request already streamed tokens: its expiry is a
        # terminal timeout frame, not a shed
        r2 = sched.submit(np.array([3, 4], np.int32), GREEDY, max_tokens=8,
                          deadline_s=0.01)
        sched._admission.pop()
        r2.resume_ids = np.array([3, 4, 5], np.int32)
        time.sleep(0.03)
        assert sched._expired_at_admission(r2) is True
        chunks = list(r2.chunks())
        assert chunks == [] and r2.done_reason == "timeout"
    finally:
        sched.shutdown()


# -- tenant caps and rate limiting -------------------------------------

def test_tenant_queued_cap_is_429_not_503(monkeypatch):
    from ollama_operator_tpu.runtime.admission import TenantRateLimited
    monkeypatch.setenv("TPU_TENANT_MAX_QUEUED", "2")
    sched = manual(make_stack(slots=1)[3])
    try:
        for i in range(2):
            sched.submit(np.array([i + 1], np.int32), GREEDY, max_tokens=8,
                         tenant="greedy-team")
        with pytest.raises(TenantRateLimited) as ei:
            sched.submit(np.array([9], np.int32), GREEDY, max_tokens=8,
                         tenant="greedy-team")
        assert not isinstance(ei.value, SchedulerBusy)  # 429, not 503
        assert ei.value.retry_after_s >= 1
        # OTHER tenants are unaffected — that is the whole point of 429
        sched.submit(np.array([7], np.int32), GREEDY, max_tokens=8,
                     tenant="polite-team")
    finally:
        sched.shutdown()


def test_rate_limiter_debt_delay():
    lim = TenantRateLimiter(rate_tps=10.0, burst_s=1.0)
    assert lim.enabled
    assert lim.debt_delay("t") == 0.0
    lim.debit("t", 30)                     # 10-token bucket, 30 spent
    d = lim.debt_delay("t")
    assert 1.5 <= d <= 2.1                 # ~20 tokens of debt at 10 tps
    assert lim.debt_delay("other") == 0.0  # per-tenant buckets
    off = TenantRateLimiter(rate_tps=0.0)
    off.debit("t", 1000)
    assert not off.enabled and off.debt_delay("t") == 0.0


def test_throttle_resume_bit_parity(monkeypatch):
    """A best-effort stream throttled mid-generation (tenant over its
    decode-token rate) must resume on the same output queue and deliver
    the EXACT tokens of an unthrottled run."""
    ids = np.array([3, 1, 4, 1, 5], np.int32)
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        base = list(sched.submit(ids, GREEDY, max_tokens=10,
                                 priority="best_effort",
                                 tenant="tt").tokens())
        assert len(base) == 10
    finally:
        sched.shutdown()
    monkeypatch.setenv("TPU_TENANT_TOKEN_RATE", "8")
    monkeypatch.setenv("TPU_TENANT_BURST_S", "0.25")
    cfg, params, eng, sched = make_stack(slots=1)
    try:
        r = sched.submit(ids, GREEDY, max_tokens=10,
                         priority="best_effort", tenant="tt")
        throttled = list(r.tokens())
        assert throttled == base
        assert r.done_reason in ("stop", "length")
        assert sched.n_throttles >= 1, \
            "rate limit never engaged — the parity check proved nothing"
        assert METRICS.get(
            "tpu_model_tenant_throttles_total",
            '{class="best_effort",tenant="tt"}') >= 1
    finally:
        sched.shutdown()


# -- chaos: engine failure mid-overload --------------------------------

@pytest.mark.chaos
def test_restart_mid_overload_preserves_class_order_errors_once(monkeypatch):
    """Engine dies mid-decode with a multi-class, multi-tenant backlog
    queued behind it: the supervised restart must (a) error the
    in-flight request EXACTLY once, (b) keep every queued request —
    class and tenant intact — and (c) admit the survivors in strict
    class order."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    cfg, params, eng, sched = make_stack(slots=1, restart_backoff=0.001)
    try:
        # the in-flight request is high-class: the queued "hi" request
        # must not priority-preempt it out of the slot before the fault
        # fires (preemption only evicts strictly lower classes)
        victim = sched.submit(np.array([9, 9], np.int32), GREEDY,
                              max_tokens=10_000, priority="high")
        it = victim.chunks()
        next(it)                           # decoding for sure
        queued = {
            "be_a": sched.submit(np.array([1], np.int32), GREEDY,
                                 max_tokens=4, priority="best_effort",
                                 tenant="a"),
            "be_b": sched.submit(np.array([2], np.int32), GREEDY,
                                 max_tokens=4, priority="best_effort",
                                 tenant="b"),
            "nm": sched.submit(np.array([3], np.int32), GREEDY,
                               max_tokens=4, priority="normal"),
            "hi": sched.submit(np.array([4], np.int32), GREEDY,
                               max_tokens=4, priority="high"),
        }
        FAULTS.arm("engine.step", "fail:once")
        frames = []
        with pytest.raises(RuntimeError, match="injected fault"):
            for chunk in it:
                frames.append(chunk)
        # exactly once: the stream is terminal after the error frame
        assert victim.out.qsize() == 0

        outs = {}
        def drain(name, r):
            outs[name] = list(r.tokens())
        threads = [threading.Thread(target=drain, args=(n, r))
                   for n, r in queued.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert set(outs) == set(queued)
        assert all(len(v) == 4 for v in outs.values()), outs
        # class metadata survived the restart...
        assert queued["hi"].priority == "high"
        assert queued["be_a"].tenant == "a"
        # ...and admission order after recovery is strict priority
        t_hi = queued["hi"].stats.t_admitted
        t_nm = queued["nm"].stats.t_admitted
        t_be = min(queued["be_a"].stats.t_admitted,
                   queued["be_b"].stats.t_admitted)
        assert t_hi <= t_nm <= t_be, (t_hi, t_nm, t_be)
        assert sched.n_restarts == 1 and not sched.broken
    finally:
        FAULTS.reset()
        sched.shutdown()


# -- /api/ps admission block -------------------------------------------

def test_admission_stats_snapshot():
    sched = manual(make_stack(slots=1)[3])
    try:
        sched.submit(np.array([1], np.int32), GREEDY, max_tokens=8,
                     priority="high", tenant="a")
        sched.submit(np.array([2], np.int32), GREEDY, max_tokens=8,
                     priority="best_effort", tenant="b")
        st = sched.admission_stats()
        assert st["queued_by_class"]["high"] == 1
        assert st["queued_by_class"]["best_effort"] == 1
        assert st["tenants_queued"] == 2
        assert st["backlog_tokens_by_class"]["high"] > 0
        assert set(st["shed_by_class"]) == set(PRIORITIES)
    finally:
        sched.shutdown()
