"""Closed-loop fleet control: autoscaler law, reconciler actuation,
remediation, and the scale-event chaos drills.

The FleetHarness plays every cluster actor the reconciler doesn't own —
ReplicaSet (pods converge on Deployment spec.replicas), kubelet
(readiness; a hung server keeps its lagging Ready condition, mirroring
the 2500-failure probe tolerance in operator/pod.py), the model servers
(/api/ps bodies, /api/drain), and the gateway (routing, wake annotation,
PR 9 stream replay on replica death). Error-frame accounting is the
contract under test: a stream killed on a live, non-draining replica is
a client-visible error; drained and replayed streams are not.
"""

import copy
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from ollama_operator_tpu.operator import autoscale, workload
from ollama_operator_tpu.operator.autoscale import (Autoscaler, Observation,
                                                    Policy, observe_stats,
                                                    resolve_policy)
from ollama_operator_tpu.operator.client import (KubeClient,
                                                 fetch_replica_ps,
                                                 update_status_with_retry)
from ollama_operator_tpu.operator.pod import PORT
from ollama_operator_tpu.operator.reconciler import (DONE, POLL,
                                                     ModelReconciler,
                                                     is_condition_true)
from ollama_operator_tpu.operator.types import API_VERSION, KIND
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

from fake_kube import FakeKube, serve_http
from test_operator_reconciler import RecordingRecorder, make_model


class Clock:
    """Injected monotonic time: the control law's cooldowns, TTLs, and
    backoffs all advance only when a test says so."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _policy(**kw) -> Policy:
    base = dict(enabled=True, min_replicas=1, max_replicas=4,
                target_occupancy=0.75, low_occupancy=0.30,
                up_cooldown_s=10.0, down_cooldown_s=10.0,
                up_streak=2, down_streak=2, idle_ttl_s=30.0,
                flap_window_s=120.0, flap_max_flips=4, flap_hold_s=60.0,
                remediation_backoff_s=1.0, remediation_backoff_cap_s=4.0)
    base.update(kw)
    return Policy(**base)


def _obs(current, occ=0.0, q=0, bt=0, gp=0.0, slo=0.0, busy=None,
         fresh=True, cause="no_data"):
    if not fresh:
        return Observation(current=current, fresh=False, stale_cause=cause)
    if busy is None:
        busy = bool(q or bt or occ > 0.0)
    return Observation(current=current, fresh=True, reachable=max(current, 1),
                       occupancy=occ, queue_depth=q, backlog_tokens=bt,
                       goodput_tok_s=gp, ttft_slo_ms=slo, busy=busy)


# -- policy resolution -------------------------------------------------

class TestPolicyResolution:
    def test_defaults_disabled(self):
        pol = resolve_policy({})
        assert not pol.enabled
        assert pol.min_replicas == 1 and pol.max_replicas == 8

    def test_spec_wins_over_env(self, monkeypatch):
        monkeypatch.setenv("TPU_AUTOSCALE", "0")
        monkeypatch.setenv("TPU_AUTOSCALE_MAX", "3")
        monkeypatch.setenv("TPU_AUTOSCALE_IDLE_TTL_S", "600")
        pol = resolve_policy({"enabled": True, "maxReplicas": 6,
                              "minReplicas": 0,
                              "targetOccupancy": 0.5})
        assert pol.enabled
        assert pol.max_replicas == 6 and pol.min_replicas == 0
        assert pol.target_occupancy == 0.5
        # unset in spec -> env default flows through
        assert pol.idle_ttl_s == 600.0

    def test_env_enables(self, monkeypatch):
        monkeypatch.setenv("TPU_AUTOSCALE", "1")
        monkeypatch.setenv("TPU_AUTOSCALE_MIN", "2")
        pol = resolve_policy({})
        assert pol.enabled and pol.min_replicas == 2


# -- observation distillation ------------------------------------------

class TestObserveStats:
    POL = _policy(stale_s=30.0)

    def test_aggregates_serving_replicas(self):
        stats = [
            {"state": "serving", "occupancy": 0.8, "queueDepth": 2,
             "backlogTokens": 100, "goodputTokS": 50.0, "ttftSloMs": 400.0,
             "activeStreams": 3},
            {"state": "serving", "occupancy": 0.4, "queueDepth": 1,
             "backlogTokens": 50, "goodputTokS": 25.0, "activeStreams": 1},
            {"state": "draining", "occupancy": 1.0, "queueDepth": 9,
             "activeStreams": 2},
        ]
        o = observe_stats(3, stats, 0.0, self.POL)
        assert o.fresh and o.reachable == 3 and o.draining == 1
        # draining replicas are excluded from the sizing signal
        assert o.occupancy == pytest.approx(0.6)
        assert o.queue_depth == 3 and o.backlog_tokens == 150
        assert o.goodput_tok_s == pytest.approx(75.0)
        assert o.ttft_slo_ms == 400.0 and o.busy

    def test_missing_or_stale_is_not_fresh(self):
        assert not observe_stats(2, None, 0.0, self.POL).fresh
        assert not observe_stats(2, [], None, self.POL).fresh
        o = observe_stats(2, [{"state": "serving"}], 31.0, self.POL)
        assert not o.fresh and o.stale_cause == "stale"

    def test_all_unreachable_fails_static(self):
        stats = [{"state": "unreachable"}, {"state": "unreachable"}]
        o = observe_stats(2, stats, 0.0, self.POL)
        assert not o.fresh and o.stale_cause == "no_data"
        # ...but a fleet of zero pods is legitimately idle, not a fault
        assert observe_stats(0, [], 0.0, self.POL).fresh


# -- the damped control law --------------------------------------------

class TestControlLaw:
    def setup_method(self):
        self.clock = Clock()
        self.asc = Autoscaler(now=self.clock)
        self.key = ("default", "phi")

    def test_hysteresis_needs_sustained_hot(self):
        pol = _policy(up_streak=2, up_cooldown_s=0.0)
        d = self.asc.observe(self.key, pol, _obs(1, occ=0.9))
        assert d.action == "steady" and d.desired == 1
        d = self.asc.observe(self.key, pol, _obs(1, occ=0.9))
        assert d.action == "up" and d.desired == 2

    def test_up_cooldown_holds(self):
        pol = _policy(up_streak=1, up_cooldown_s=10.0)
        hold0 = METRICS.get("tpu_model_autoscale_holds_total",
                            '{cause="cooldown"}')
        assert self.asc.observe(self.key, pol, _obs(1, occ=0.9)).action == "up"
        d = self.asc.observe(self.key, pol, _obs(2, occ=0.9))
        assert d.action == "hold" and d.desired == 2
        assert METRICS.get("tpu_model_autoscale_holds_total",
                           '{cause="cooldown"}') == hold0 + 1
        self.clock.advance(10.1)
        assert self.asc.observe(self.key, pol,
                                _obs(2, occ=0.9)).action == "up"

    def test_max_replicas_clamps(self):
        pol = _policy(up_streak=1, up_cooldown_s=0.0, max_replicas=2)
        assert self.asc.observe(self.key, pol, _obs(1, occ=0.9)).desired == 2
        assert self.asc.observe(self.key, pol, _obs(2, occ=0.9)).desired == 2

    def test_backlog_and_slo_risk_count_as_hot(self):
        pol = _policy(up_streak=1, up_cooldown_s=0.0,
                      backlog_tokens_per_replica=100)
        d = self.asc.observe(self.key, pol, _obs(1, occ=0.1, bt=500))
        assert d.action == "up"
        # predicted TTFT = backlog/goodput = 2s >> 500ms SLO, low occupancy
        asc2 = Autoscaler(now=self.clock)
        d = asc2.observe(("default", "o"),
                         _policy(up_streak=1, up_cooldown_s=0.0),
                         _obs(1, occ=0.1, bt=200, gp=100.0, slo=500.0))
        assert d.action == "up"

    def test_scale_down_floor_and_streak(self):
        pol = _policy(down_streak=2, down_cooldown_s=0.0, min_replicas=1,
                      idle_ttl_s=0.0)
        self.asc.seed_desired(self.key, 3)
        cold = _obs(3, occ=0.05, busy=True)
        assert self.asc.observe(self.key, pol, cold).action == "steady"
        assert self.asc.observe(self.key, pol, cold).desired == 2
        self.asc.observe(self.key, pol, cold)
        assert self.asc.observe(self.key, pol, cold).desired == 1
        # at the floor: cold forever, never below max(minReplicas, 1)
        for _ in range(5):
            assert self.asc.observe(self.key, pol, cold).desired == 1

    def test_idle_ttl_scales_to_zero_and_wake_restores(self):
        pol = _policy(idle_ttl_s=30.0, down_cooldown_s=0.0, down_streak=99)
        self.asc.seed_desired(self.key, 1)
        idle = _obs(1, occ=0.0, busy=False)
        assert self.asc.observe(self.key, pol, idle).action == "steady"
        self.clock.advance(29.0)
        assert self.asc.observe(self.key, pol, idle).action == "steady"
        self.clock.advance(1.5)
        d = self.asc.observe(self.key, pol, idle)
        assert d.action == "to_zero" and d.desired == 0
        # a sleeping fleet with no pods is steady, not a hold
        d = self.asc.observe(self.key, pol, _obs(0, fresh=False))
        assert d.action == "steady" and d.reason == "sleeping"
        # wake beats everything
        d = self.asc.observe(self.key, pol, _obs(0, fresh=False), wake=True)
        assert d.action == "wake" and d.desired == 1

    def test_fail_static_holds_last_decision(self):
        pol = _policy(up_streak=1, up_cooldown_s=0.0)
        hold0 = METRICS.get("tpu_model_autoscale_holds_total",
                            '{cause="no_data"}')
        assert self.asc.observe(self.key, pol, _obs(1, occ=0.9)).desired == 2
        for _ in range(3):
            d = self.asc.observe(self.key, pol, _obs(2, fresh=False))
            assert d.action == "hold" and d.desired == 2
        assert METRICS.get("tpu_model_autoscale_holds_total",
                           '{cause="no_data"}') == hold0 + 3
        d = self.asc.observe(self.key, pol,
                             _obs(2, fresh=False, cause="stale"))
        assert d.action == "hold" and d.desired == 2

    def test_flap_detector_freezes(self):
        pol = _policy(up_streak=1, down_streak=1, up_cooldown_s=0.0,
                      down_cooldown_s=0.0, idle_ttl_s=0.0,
                      flap_max_flips=2, flap_hold_s=60.0)
        hot = _obs(2, occ=0.9)
        cold = _obs(2, occ=0.05, busy=True)
        self.asc.seed_desired(self.key, 2)
        assert self.asc.observe(self.key, pol, hot).action == "up"      # +1
        self.clock.advance(1)
        assert self.asc.observe(self.key, pol, cold).action == "down"   # flip
        self.clock.advance(1)
        assert self.asc.observe(self.key, pol, hot).action == "up"      # flip
        self.clock.advance(1)
        d = self.asc.observe(self.key, pol, cold)
        assert d.action == "hold" and "flap" in d.reason
        # frozen for flap_hold_s regardless of signal
        self.clock.advance(30)
        assert self.asc.observe(self.key, pol, hot).action == "hold"
        self.clock.advance(31)
        # window (120s) still holds the old moves but the freeze expired
        # and the flip count decays as moves age out
        d = self.asc.observe(self.key, pol, hot)
        assert d.action in ("up", "hold")

    def test_remediation_backoff_doubles_to_cap(self):
        pol = _policy(remediation_backoff_s=1.0, remediation_backoff_cap_s=4.0)
        assert self.asc.remediation_due(self.key, pol)
        self.asc.note_remediation(self.key, pol, "unreachable")
        assert self.asc.remediation_backoff_s(self.key) == 1.0
        hold0 = METRICS.get("tpu_model_remediation_backoff_holds_total")
        assert not self.asc.remediation_due(self.key, pol)
        assert METRICS.get(
            "tpu_model_remediation_backoff_holds_total") == hold0 + 1
        for expect in (2.0, 4.0, 4.0):           # doubles, then caps
            self.clock.advance(5.0)
            assert self.asc.remediation_due(self.key, pol)
            self.asc.note_remediation(self.key, pol, "crash_loop")
            assert self.asc.remediation_backoff_s(self.key) == expect
        # a clean pass resets the ladder
        self.asc.note_clean_pass(self.key)
        assert self.asc.remediation_due(self.key, pol)
        self.asc.note_remediation(self.key, pol, "unreachable")
        assert self.asc.remediation_backoff_s(self.key) == 1.0


# -- fleet harness ------------------------------------------------------

class _Stream:
    __slots__ = ("left",)

    def __init__(self, ttl: int):
        self.left = ttl


class _Replica:
    """One fake model server: bounded slots, a local queue, and the
    /api/ps body shape the PR 10 mirror scrapes."""

    CAP = 4

    def __init__(self, pod: str, ip: str):
        self.pod, self.ip = pod, ip
        self.active, self.queued = [], []
        self.draining = False
        self.alive = True

    def ps_body(self):
        occ = min(1.0, len(self.active) / self.CAP)
        nq = len(self.queued)
        return {"models": [{
            "name": "phi",
            "lifecycle": {"state": "draining" if self.draining else "serving",
                          "active_streams": len(self.active), "queued": nq},
            "utilization": {"mfu": 0.5, "occupancy": occ, "waste_pct": 0.0,
                            "goodput_tok_s": 50.0 * len(self.active),
                            "recompiles": {}},
            "admission": {
                "queued_by_class": {"default": nq} if nq else {},
                "backlog_tokens_by_class": {"default": 64 * nq} if nq else {},
                "ttft_slo_ms": 0.0},
        }]}


class FleetHarness:
    STREAM_TICKS = 2          # ticks a stream occupies a slot

    def __init__(self, kube: FakeKube, name="phi", namespace="default"):
        self.kube, self.name, self.namespace = kube, name, namespace
        self.app = workload.model_app_name(name)
        self.by_ip = {}
        self.by_pod = {}
        self._seq = 0
        self.error_frames = 0    # streams killed on a live serving replica
        self.completed = 0
        self.replayed = 0
        self.offered = 0
        self.replay_pool = []    # PR 9: streams rescued from a dead replica
        self.pending = []        # gateway queue while the fleet sleeps

    # -- reconciler wiring (mirrors client.fetch_replica_ps's contract) --
    def ps_fetch(self, url):
        try:
            FAULTS.check("operator.scrape")
        except Exception:        # noqa: BLE001 — collapses to None
            return None
        r = self.by_ip.get(url.split("//", 1)[1].split(":", 1)[0])
        if r is None or not r.alive:
            return None
        return r.ps_body()

    def drain_post(self, url):
        r = self.by_ip.get(url.split("//", 1)[1].split(":", 1)[0])
        if r is None or not r.alive:
            return False
        r.draining = True
        return True

    # -- cluster actors ---------------------------------------------------
    def _spawn(self):
        self._seq += 1
        pod, ip = f"{self.app}-{self._seq:04d}", f"10.1.0.{self._seq}"
        self.kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod, "namespace": self.namespace,
                         "labels": {"app": self.app}},
            "status": {"phase": "Running", "podIP": ip}})
        r = _Replica(pod, ip)
        while self.replay_pool:          # replacement adopts replayed work
            s = self.replay_pool.pop()
            (r.active if len(r.active) < r.CAP else r.queued).append(s)
            self.replayed += 1
        self.by_pod[pod], self.by_ip[ip] = r, r

    def sync(self):
        """Play ReplicaSet + kubelet: pods converge on spec.replicas; a
        deleted pod's replica dies with it (streams it was actively
        serving become error frames unless drained or replayed)."""
        dep = self.kube.get("apps/v1", "Deployment", self.namespace, self.app)
        if dep is None:
            return
        want = int(dep["spec"].get("replicas", 1) or 0)
        pods = self.kube.list("v1", "Pod", self.namespace,
                              label_selector=f"app={self.app}")
        names = {(p.get("metadata") or {}).get("name") for p in pods}
        for pod_name in list(self.by_pod):
            if pod_name not in names:
                r = self.by_pod.pop(pod_name)
                self.by_ip.pop(r.ip, None)
                if r.alive and not r.draining:
                    self.error_frames += len(r.active) + len(r.queued)
        while len(self.by_pod) < want:
            self._spawn()
        # kubelet: draining servers fail readiness (readyz flips 503);
        # a hung server keeps its lagging Ready (pod.py's 2500-failure
        # probe tolerance) — the scrape path is the fast detector.
        n = len(self.by_pod)
        ready = sum(1 for r in self.by_pod.values() if not r.draining)
        self.kube.set_status("apps/v1", "Deployment", self.namespace,
                             self.app, {"replicas": n, "readyReplicas": ready,
                                        "availableReplicas": ready})

    def targets(self):
        return [r for r in self.by_pod.values()
                if r.alive and not r.draining]

    def route(self):
        ts = self.targets()
        if not ts:
            if self.pending:
                self.set_wake()
            return
        while self.pending:
            t = min(ts, key=lambda r: len(r.active) + len(r.queued))
            s = self.pending.pop(0)
            (t.active if len(t.active) < t.CAP else t.queued).append(s)

    def offer(self, n: int):
        self.offered += n
        self.pending.extend(_Stream(self.STREAM_TICKS) for _ in range(n))
        self.route()

    def step(self):
        """One serving tick: streams progress and complete, queues drain."""
        for r in self.by_pod.values():
            if not r.alive:
                continue
            self.completed += sum(1 for s in r.active if s.left <= 1)
            for s in r.active:
                s.left -= 1
            r.active = [s for s in r.active if s.left > 0]
            while r.queued and len(r.active) < r.CAP:
                r.active.append(r.queued.pop(0))
        self.route()

    def kill(self, pod_name: str):
        """Crash a replica mid-stream. PR 9's transcript replay rescues
        its in-flight work onto the replacement — not error frames."""
        r = self.by_pod[pod_name]
        r.alive = False
        self.replay_pool.extend(r.active + r.queued)
        r.active, r.queued = [], []

    def set_wake(self):
        m = self.kube.get(API_VERSION, KIND, self.namespace, self.name)
        anns = m.setdefault("metadata", {}).setdefault("annotations", {})
        if anns.get(workload.WAKE_ANNOTATION) != "true":
            anns[workload.WAKE_ANNOTATION] = "true"
            self.kube.update(m)

    @property
    def in_flight(self) -> int:
        return (len(self.pending) + len(self.replay_pool)
                + sum(len(r.active) + len(r.queued)
                      for r in self.by_pod.values()))

    @property
    def replica_count(self) -> int:
        return len(self.by_pod)


def boot(recon, kube, harness, steps=12):
    """Drive the ladder up (store, services) until the fleet serves."""
    res = None
    for _ in range(steps):
        res = recon.reconcile(harness.namespace, harness.name)
        if kube.get("apps/v1", "StatefulSet", harness.namespace,
                    workload.IMAGE_STORE_NAME):
            kube.set_status("apps/v1", "StatefulSet", harness.namespace,
                            workload.IMAGE_STORE_NAME, {"readyReplicas": 1})
        for svc_name, ip in ((workload.IMAGE_STORE_SERVICE, "10.0.0.1"),
                             (harness.app, "10.0.0.2")):
            svc = kube.get("v1", "Service", harness.namespace, svc_name)
            if svc is not None and not svc["spec"].get("clusterIP"):
                svc["spec"]["clusterIP"] = ip
                kube.update(svc)
        harness.sync()
    return res


def tick(recon, harness, clock, dt=1.0, passes=3):
    """One wall-clock tick: serve, then let the control loop breathe."""
    clock.advance(dt)
    harness.step()
    for _ in range(passes):
        recon.reconcile(harness.namespace, harness.name)
        harness.sync()


DIURNAL_SPEC = {
    "enabled": True, "minReplicas": 1, "maxReplicas": 4,
    "targetOccupancy": 0.6, "lowOccupancy": 0.3,
    "upCooldownSeconds": 2, "downCooldownSeconds": 2,
    "upStreak": 2, "downStreak": 2, "idleTTLSeconds": 3,
    "staleSeconds": 10000, "flapWindowSeconds": 10000,
    "flapMaxFlips": 99, "remediationBackoffSeconds": 1,
}


def make_fleet(spec_autoscale=DIURNAL_SPEC, **model_kw):
    kube = FakeKube()
    rec = RecordingRecorder()
    harness = FleetHarness(kube)
    make_model(kube, autoscale=dict(spec_autoscale), **model_kw)
    clock = Clock()
    recon = ModelReconciler(kube, rec, server_image="runtime:test",
                            ps_fetch=harness.ps_fetch,
                            drain_post=harness.drain_post,
                            autoscaler=Autoscaler(now=clock))
    return kube, rec, harness, clock, recon


# -- disaggregated pools (ISSUE 20) -------------------------------------

class _PoolReplica(_Replica):
    """A pool-labeled fake server: prefill replicas report prompt-token
    backlog (their slots turn over every tick, so occupancy is noise),
    decode replicas report slot occupancy (their backlog queues
    upstream) — the two native demand signals pool_policy scales on."""

    PROMPT_TOKENS = 256

    def __init__(self, pod: str, ip: str, pool: str):
        super().__init__(pod, ip)
        self.pool = pool

    def ps_body(self):
        body = super().ps_body()
        m = body["models"][0]
        if self.pool == "prefill":
            nq = len(self.queued)
            m["utilization"]["occupancy"] = 0.0
            m["admission"]["backlog_tokens_by_class"] = (
                {"default": self.PROMPT_TOKENS * nq} if nq else {})
        else:
            m["admission"]["queued_by_class"] = {}
            m["admission"]["backlog_tokens_by_class"] = {}
        return body


class PoolFleetHarness(FleetHarness):
    """FleetHarness over a split fleet: two pool Deployments share the
    fleet-wide app label, pods carry workload.POOL_LABEL, and a request
    flows prefill slot -> KV handoff -> decode slot (the ISSUE 20
    pipeline at control-plane granularity)."""

    def __init__(self, kube: FakeKube, name="phi", namespace="default"):
        super().__init__(kube, name, namespace)
        self.pool_apps = {p: workload.pool_app_name(name, p)
                          for p in workload.DISAGG_POOLS}
        self.decode_pending = []   # prefilled, awaiting a decode slot

    def _spawn_pool(self, pool: str):
        self._seq += 1
        pod = f"{self.pool_apps[pool]}-{self._seq:04d}"
        ip = f"10.1.0.{self._seq}"
        self.kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod, "namespace": self.namespace,
                         "labels": {"app": self.app,
                                    workload.POOL_LABEL: pool}},
            "status": {"phase": "Running", "podIP": ip}})
        r = _PoolReplica(pod, ip, pool)
        if pool == "decode":          # replayed streams are decode work
            while self.replay_pool:
                s = self.replay_pool.pop()
                (r.active if len(r.active) < r.CAP else r.queued).append(s)
                self.replayed += 1
        self.by_pod[pod], self.by_ip[ip] = r, r

    def pool_count(self, pool: str) -> int:
        return sum(1 for r in self.by_pod.values() if r.pool == pool)

    def sync(self):
        pods = self.kube.list("v1", "Pod", self.namespace,
                              label_selector=f"app={self.app}")
        names = {(p.get("metadata") or {}).get("name") for p in pods}
        for pod_name in list(self.by_pod):
            if pod_name not in names:
                r = self.by_pod.pop(pod_name)
                self.by_ip.pop(r.ip, None)
                if r.alive and not r.draining:
                    self.error_frames += len(r.active) + len(r.queued)
        for pool, papp in self.pool_apps.items():
            dep = self.kube.get("apps/v1", "Deployment",
                                self.namespace, papp)
            if dep is None:
                continue
            want = int(dep["spec"].get("replicas", 1) or 0)
            while self.pool_count(pool) < want:
                self._spawn_pool(pool)
            members = [r for r in self.by_pod.values() if r.pool == pool]
            ready = sum(1 for r in members if not r.draining)
            self.kube.set_status(
                "apps/v1", "Deployment", self.namespace, papp,
                {"replicas": len(members), "readyReplicas": ready,
                 "availableReplicas": ready})

    def targets(self, pool=None):
        return [r for r in self.by_pod.values()
                if r.alive and not r.draining
                and (pool is None or r.pool == pool)]

    def route(self):
        for pool, queue in (("prefill", self.pending),
                            ("decode", self.decode_pending)):
            ts = self.targets(pool)
            if not ts:
                continue
            while queue:
                t = min(ts, key=lambda r: len(r.active) + len(r.queued))
                s = queue.pop(0)
                (t.active if len(t.active) < t.CAP else t.queued).append(s)

    def step(self):
        for r in self.by_pod.values():
            if not r.alive:
                continue
            if r.pool == "prefill":
                # a prefill slot turns over every tick: the finished
                # prompt hands its KV pages off to the decode pool
                self.decode_pending.extend(r.active)
                r.active = []
                while r.queued and len(r.active) < r.CAP:
                    r.active.append(r.queued.pop(0))
            else:
                self.completed += sum(1 for s in r.active if s.left <= 1)
                for s in r.active:
                    s.left -= 1
                r.active = [s for s in r.active if s.left > 0]
                while r.queued and len(r.active) < r.CAP:
                    r.active.append(r.queued.pop(0))
        self.route()

    @property
    def in_flight(self) -> int:
        return super().in_flight + len(self.decode_pending)


DISAGG_DIURNAL = {
    "enabled": True,
    # small per-replica backlog bar so the fake fleet's queues register
    # as demand at test scale
    "prefill": {"minReplicas": 1, "maxReplicas": 3,
                "backlogTokensPerReplica": 512},
    "decode": {"minReplicas": 1, "maxReplicas": 4},
}


def make_pool_fleet():
    kube = FakeKube()
    rec = RecordingRecorder()
    harness = PoolFleetHarness(kube)
    # pool loops never sleep the fleet — drop the idle TTL so the quiet
    # tail parks both pools at their floors instead of racing a
    # whole-Model scale-to-zero that disagg doesn't do
    make_model(kube, autoscale=dict(DIURNAL_SPEC, idleTTLSeconds=0),
               disaggregate=copy.deepcopy(DISAGG_DIURNAL))
    clock = Clock()
    recon = ModelReconciler(kube, rec, server_image="runtime:test",
                            ps_fetch=harness.ps_fetch,
                            drain_post=harness.drain_post,
                            autoscaler=Autoscaler(now=clock))
    return kube, rec, harness, clock, recon


# -- end-to-end: the diurnal cycle --------------------------------------

class TestFleetAutoscaling:
    def test_diurnal_cycle_zero_error_frames(self):
        kube, rec, harness, clock, recon = make_fleet()
        d0 = {a: METRICS.get("tpu_model_autoscale_decisions_total",
                             f'{{action="{a}"}}') for a in autoscale.ACTIONS}
        assert boot(recon, kube, harness) == POLL
        assert harness.replica_count == 1

        timeline = []

        def run(ticks, load_fn):
            for i in range(ticks):
                harness.offer(load_fn(i))
                tick(recon, harness, clock)
                timeline.append({"t": clock.t, "in_flight": harness.in_flight,
                                 "replicas": harness.replica_count})

        # morning ramp: sustained pressure -> fleet grows toward max
        run(12, lambda i: max(0, 12 - harness.in_flight))
        peak = harness.replica_count
        assert 3 <= peak <= 4

        # afternoon trickle: cold but busy -> damped stepwise shrink,
        # strictly drain-first (any abrupt kill shows up as error frames)
        run(16, lambda i: 1 if i % 2 == 0 else 0)
        assert harness.replica_count == 1

        # night: fully idle past the TTL -> scale to zero
        run(10, lambda i: 0)
        assert harness.replica_count == 0
        m = kube.get(API_VERSION, KIND, "default", "phi")
        asc = m["status"]["autoscale"]
        assert asc["sleeping"] and asc["desiredReplicas"] == 0

        # dawn: demand against a sleeping fleet -> wake, serve, and
        # (the cycle closing) drift back to sleep once idle again
        dawn = len(timeline)
        run(8, lambda i: 3 if i == 0 else 0)
        assert max(e["replicas"] for e in timeline[dawn:]) >= 1
        assert not harness.pending

        assert harness.error_frames == 0
        assert harness.completed == harness.offered
        for action in autoscale.ACTIONS:
            assert METRICS.get("tpu_model_autoscale_decisions_total",
                               f'{{action="{action}"}}') > d0[action], action
        assert ("Normal", "AutoscaleUp") in rec.events
        assert ("Normal", "AutoscaleDrainStarted") in rec.events
        assert ("Normal", "AutoscaleDown") in rec.events
        assert ("Normal", "AutoscaleWake") in rec.events
        # scale events never exceeded the configured ceiling
        assert max(e["replicas"] for e in timeline) <= 4

        out = os.environ.get("AUTOSCALE_TIMELINE")
        if out:
            with open(out, "w") as f:
                json.dump(timeline, f)

    def test_disagg_diurnal_per_pool_counts(self):
        """ISSUE 20: the diurnal cycle on a DISAGGREGATED fleet — two
        pool Deployments under independent control loops (prefill on
        queued prompt-token backlog, decode on slot occupancy). The
        timeline records per-pool replica counts; the error-frame
        contract is unchanged: splitting the fleet must never cost a
        client a stream."""
        kube, rec, harness, clock, recon = make_pool_fleet()
        assert boot(recon, kube, harness) == POLL
        assert harness.pool_count("prefill") == 1
        assert harness.pool_count("decode") == 1

        timeline = []

        def run(ticks, load_fn):
            for i in range(ticks):
                harness.offer(load_fn(i))
                tick(recon, harness, clock)
                timeline.append({
                    "t": clock.t, "in_flight": harness.in_flight,
                    "prefill": harness.pool_count("prefill"),
                    "decode": harness.pool_count("decode")})

        # morning: prompt-heavy pressure — backlog queues on the
        # prefill pool, handoffs fill decode slots; BOTH pools grow,
        # each on its own signal
        run(14, lambda i: max(0, 16 - harness.in_flight))
        assert max(e["prefill"] for e in timeline) >= 2
        assert max(e["decode"] for e in timeline) >= 2

        # afternoon trickle, then a quiet tail: both pools shrink
        # drain-first back to their floors (pool loops never sleep the
        # fleet — floors are >= 1)
        run(26, lambda i: 1 if i % 2 == 0 else 0)
        run(8, lambda i: 0)
        assert harness.pool_count("prefill") == 1
        assert harness.pool_count("decode") == 1

        assert harness.error_frames == 0
        assert harness.completed == harness.offered
        assert max(e["prefill"] for e in timeline) <= 3
        assert max(e["decode"] for e in timeline) <= 4
        # per-pool intent survives in nested status.autoscale.<pool>
        m = kube.get(API_VERSION, KIND, "default", "phi")
        asc = m["status"]["autoscale"]
        for pool in workload.DISAGG_POOLS:
            assert asc[pool]["desiredReplicas"] == 1, (pool, asc)

        out = os.environ.get("AUTOSCALE_POOL_TIMELINE")
        if out:
            with open(out, "w") as f:
                json.dump(timeline, f)

    def test_desired_persisted_and_readopted_across_restart(self):
        kube, rec, harness, clock, recon = make_fleet()
        boot(recon, kube, harness)
        for _ in range(10):
            harness.offer(max(0, 12 - harness.in_flight))
            tick(recon, harness, clock)
        assert harness.replica_count >= 2
        m = kube.get(API_VERSION, KIND, "default", "phi")
        persisted = m["status"]["autoscale"]["desiredReplicas"]
        assert persisted >= 2

        # "restart": a fresh reconciler with an empty Autoscaler must
        # adopt the persisted desired count, not snap back to spec (1).
        # The scrape outage pins the law: fail-static means the adopted
        # count is exactly what survives.
        clock2 = Clock()
        recon2 = ModelReconciler(kube, rec, server_image="runtime:test",
                                 ps_fetch=harness.ps_fetch,
                                 drain_post=harness.drain_post,
                                 autoscaler=Autoscaler(now=clock2))
        FAULTS.arm("operator.scrape", "fail")
        for _ in range(3):
            recon2.reconcile("default", "phi")
            harness.sync()
        FAULTS.reset()
        assert recon2.scaler.desired(("default", "phi")) == persisted
        dep = kube.get("apps/v1", "Deployment", "default", harness.app)
        assert int(dep["spec"]["replicas"]) >= persisted

    @pytest.mark.chaos
    def test_scrape_outage_fails_static(self):
        """Chaos drill: the operator.scrape fault point takes out every
        replica scrape. The loop must hold its last decision — no scale
        action, no remediation — and count the holds."""
        kube, rec, harness, clock, recon = make_fleet()
        boot(recon, kube, harness)
        for _ in range(8):
            harness.offer(max(0, 12 - harness.in_flight))
            tick(recon, harness, clock)
        assert harness.replica_count >= 2
        pods_before = set(harness.by_pod)
        dep = kube.get("apps/v1", "Deployment", "default", harness.app)
        replicas_before = int(dep["spec"]["replicas"])
        hold0 = METRICS.get("tpu_model_autoscale_holds_total",
                            '{cause="no_data"}')
        rem0 = METRICS.get("tpu_model_remediation_replacements_total",
                           '{cause="unreachable"}')

        FAULTS.arm("operator.scrape", "fail")
        for _ in range(6):
            harness.offer(max(0, 12 - harness.in_flight))
            tick(recon, harness, clock)
        dep = kube.get("apps/v1", "Deployment", "default", harness.app)
        assert int(dep["spec"]["replicas"]) == replicas_before
        assert set(harness.by_pod) == pods_before        # nobody remediated
        assert METRICS.get("tpu_model_autoscale_holds_total",
                           '{cause="no_data"}') > hold0
        assert METRICS.get("tpu_model_remediation_replacements_total",
                           '{cause="unreachable"}') == rem0

        FAULTS.reset()
        for _ in range(4):
            tick(recon, harness, clock)
        assert harness.error_frames == 0
        m = kube.get(API_VERSION, KIND, "default", "phi")
        assert is_condition_true(m, "Available")

    @pytest.mark.chaos
    def test_drill8_replica_killed_mid_stream_is_replaced(self):
        """Chaos drill 8: kill a replica mid-stream under autoscaling.
        Remediation replaces it (delete -> ReplicaSet recreates, fleet
        size never shrinks), PR 9 replay carries its in-flight streams
        to the replacement, and the client sees zero error frames."""
        kube, rec, harness, clock, recon = make_fleet()
        boot(recon, kube, harness)
        # a steady 5 streams/tick (each living 2 ticks) keeps occupancy
        # pinned above target: the fleet grows to max and STAYS there,
        # so no pod carries a drain mark when the kill lands
        for _ in range(8):
            harness.offer(5)
            tick(recon, harness, clock)
        assert harness.replica_count >= 2
        fleet_size = harness.replica_count
        rem0 = METRICS.get("tpu_model_remediation_replacements_total",
                           '{cause="unreachable"}')

        def drain_marked(pod_name):
            p = kube.get("v1", "Pod", harness.namespace, pod_name)
            return p is None or workload.pod_is_drain_victim(p)

        victim = next(p for p, r in harness.by_pod.items()
                      if r.active and not drain_marked(p))
        harness.kill(victim)
        for _ in range(6):
            harness.offer(5)
            tick(recon, harness, clock)

        assert victim not in harness.by_pod           # replaced, not lingering
        assert harness.replica_count >= fleet_size    # floor held
        assert METRICS.get("tpu_model_remediation_replacements_total",
                           '{cause="unreachable"}') == rem0 + 1
        assert ("Warning", "ReplicaRemediated") in rec.events
        assert harness.replayed > 0

        # let everything in flight finish
        for _ in range(6):
            tick(recon, harness, clock)
        assert harness.error_frames == 0
        assert harness.completed == harness.offered

    def test_all_replicas_dead_is_fail_static_not_massacre(self):
        """Zero reachable replicas is evidence about the scrape path, not
        the fleet: remediation must not delete anything."""
        kube, rec, harness, clock, recon = make_fleet()
        boot(recon, kube, harness)
        for _ in range(8):
            harness.offer(max(0, 12 - harness.in_flight))
            tick(recon, harness, clock)
        assert harness.replica_count >= 2
        pods_before = set(harness.by_pod)
        for p in pods_before:
            harness.by_pod[p].alive = False
        for _ in range(4):
            tick(recon, harness, clock)
        assert set(harness.by_pod) == pods_before


# -- crash-loop remediation --------------------------------------------

class TestCrashLoopRemediation:
    SPEC = {"enabled": True, "minReplicas": 2, "maxReplicas": 4,
            "remediationBackoffSeconds": 1, "remediationBackoffCapSeconds": 4}

    def _crash_pod(self, kube, app, name):
        return kube.create({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "default",
                         "labels": {"app": app}},
            "status": {"phase": "Running", "containerStatuses": [
                {"name": "server", "restartCount": 5,
                 "state": {"waiting": {"reason": "CrashLoopBackOff"}}}]}})

    def test_replacement_backoff_cap_and_floor(self):
        kube, rec, harness, clock, recon = make_fleet(self.SPEC, replicas=2)
        boot(recon, kube, harness)
        app = harness.app
        dep = kube.get("apps/v1", "Deployment", "default", app)
        assert int(dep["spec"]["replicas"]) == 2
        kube.set_status("apps/v1", "Deployment", "default", app, {
            "replicas": 2, "readyReplicas": 1,
            "conditions": [{"type": "ReplicaFailure", "status": "True",
                            "message": "pods \"x\" is forbidden"}]})
        rem0 = METRICS.get("tpu_model_remediation_replacements_total",
                           '{cause="crash_loop"}')
        hold0 = METRICS.get("tpu_model_remediation_backoff_holds_total")

        # prime past the Available -> ReplicaFailure condition flip: the
        # pass right after the flip restarts the ladder with a KICKOFF
        # and never reaches the failure branch
        recon.reconcile("default", "phi")
        recon.reconcile("default", "phi")

        expected_backoff = [1.0, 2.0, 4.0, 4.0]      # doubles, then caps
        for i, backoff in enumerate(expected_backoff):
            name = f"{app}-crash-{i}"
            self._crash_pod(kube, app, name)
            assert recon.reconcile("default", "phi") == POLL
            assert kube.get("v1", "Pod", "default", name) is None
            assert recon.scaler.remediation_backoff_s(
                ("default", "phi")) == backoff
            # inside the backoff window the next victim is NOT replaced
            name2 = f"{app}-held-{i}"
            self._crash_pod(kube, app, name2)
            recon.reconcile("default", "phi")
            assert kube.get("v1", "Pod", "default", name2) is not None
            kube.delete("v1", "Pod", "default", name2)
            clock.advance(backoff + 0.1)

        assert METRICS.get("tpu_model_remediation_replacements_total",
                           '{cause="crash_loop"}') == rem0 + 4
        assert METRICS.get(
            "tpu_model_remediation_backoff_holds_total") >= hold0 + 4
        assert rec.events.count(("Warning", "ReplicaRemediated")) >= 4
        # remediation deletes pods, never the Deployment: the
        # minReplicas floor holds structurally
        dep = kube.get("apps/v1", "Deployment", "default", app)
        assert int(dep["spec"]["replicas"]) == 2

    def test_healthy_pods_not_remediated(self):
        kube, rec, harness, clock, recon = make_fleet(self.SPEC, replicas=2)
        boot(recon, kube, harness)
        app = harness.app
        kube.set_status("apps/v1", "Deployment", "default", app, {
            "replicas": 2, "readyReplicas": 1,
            "conditions": [{"type": "ReplicaFailure", "status": "True",
                            "message": "quota"}]})
        pods = kube.list("v1", "Pod", "default", label_selector=f"app={app}")
        assert pods
        recon.reconcile("default", "phi")
        assert kube.list("v1", "Pod", "default",
                         label_selector=f"app={app}") == pods


# -- status writes under churn ------------------------------------------

@pytest.fixture()
def http_kube():
    fake = FakeKube()
    httpd = serve_http(fake)
    client = KubeClient(f"http://127.0.0.1:{httpd.server_address[1]}",
                        timeout=5)
    yield fake, client
    httpd.shutdown()


class TestStatusWriteRetry:
    def _model(self, client, name="phi"):
        return client.create({"apiVersion": API_VERSION, "kind": KIND,
                              "metadata": {"name": name,
                                           "namespace": "default"},
                              "spec": {"image": "phi", "runtime": "cpu"}})

    def test_transient_blip_is_retried(self, http_kube):
        fake, client = http_kube
        obj = self._model(client)
        obj["status"] = {"autoscale": {"desiredReplicas": 3}}
        FAULTS.arm("kube.request", "fail:once")
        update_status_with_retry(client, obj, backoff=0.001)
        got = fake.get(API_VERSION, KIND, "default", "phi")
        assert got["status"]["autoscale"]["desiredReplicas"] == 3

    def test_conflict_rereads_and_reapplies(self, http_kube):
        fake, client = http_kube
        obj = self._model(client)
        stale = copy.deepcopy(obj)
        # someone else bumps the resourceVersion under us (scale churn)
        obj["metadata"]["labels"] = {"touched": "yes"}
        client.update(obj)
        stale["status"] = {"autoscale": {"desiredReplicas": 2}}
        update_status_with_retry(client, stale, backoff=0.001)
        got = fake.get(API_VERSION, KIND, "default", "phi")
        assert got["status"]["autoscale"]["desiredReplicas"] == 2
        assert got["metadata"]["labels"] == {"touched": "yes"}

    def test_vanished_resource_is_not_an_error(self, http_kube):
        fake, client = http_kube
        obj = self._model(client)
        client.delete(API_VERSION, KIND, "default", "phi")
        obj["status"] = {"x": 1}
        assert update_status_with_retry(client, obj,
                                        backoff=0.001) is obj


# -- the scrape fault point ---------------------------------------------

class TestScrapeFaultPoint:
    @pytest.mark.chaos
    def test_fetch_replica_ps_fault_collapses_to_none(self):
        body = json.dumps({"models": []}).encode()

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}/api/ps"
        try:
            assert fetch_replica_ps(url) == {"models": []}
            FAULTS.arm("operator.scrape", "fail")
            assert fetch_replica_ps(url) is None
            FAULTS.reset()
            assert fetch_replica_ps(url) == {"models": []}
        finally:
            httpd.shutdown()
