"""Seeded chaos-campaign engine (runtime/chaos.py) + the fleet harness
(tools/chaos_campaign). The schedule's prefix property is what makes a
red campaign reproducible: `--seed S --events N` replays exactly the
failing prefix, so the unit tier pins it alongside the repro string and
a small in-suite campaign against the real fleet harness.
"""

import random

import pytest

from ollama_operator_tpu.runtime.chaos import (FAULT_SPECS, ChaosEvent,
                                               InvariantViolation,
                                               next_event, run_campaign)
from ollama_operator_tpu.runtime.faults import FAULTS


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


POINTS = ("engine.step", "gateway.route", "pages.alloc")
ACTIONS = ("kill_replica", "revive_replica")


def schedule(seed, n):
    rng = random.Random(seed)
    return [next_event(rng, i, POINTS, ACTIONS) for i in range(1, n + 1)]


class TestSchedule:
    def test_prefix_property(self):
        """The first N events of a longer campaign ARE the N-event
        campaign — the repro contract of every InvariantViolation."""
        assert schedule(7, 10)[:5] == schedule(7, 5)

    def test_deterministic_and_seed_sensitive(self):
        assert schedule(7, 20) == schedule(7, 20)
        assert schedule(7, 20) != schedule(8, 20)

    def test_events_are_well_formed(self):
        for ev in schedule(3, 60):
            if ev.kind == "fault":
                assert ev.point in POINTS
                assert ev.spec in FAULT_SPECS
            else:
                assert ev.kind in ACTIONS
                assert ev.point == "" and ev.spec == ""

    def test_mix_includes_faults_and_actions(self):
        kinds = {ev.kind for ev in schedule(11, 60)}
        assert "fault" in kinds
        assert kinds & set(ACTIONS)

    def test_no_actions_means_all_faults(self):
        rng = random.Random(5)
        evs = [next_event(rng, i, POINTS, ()) for i in range(1, 30)]
        assert all(ev.kind == "fault" for ev in evs)


class TestInvariantViolation:
    def test_carries_seed_prefix_and_repro_command(self):
        events = [ChaosEvent(idx=1, kind="fault", point="engine.step",
                             spec="fail:once"),
                  ChaosEvent(idx=2, kind="kill_replica")]
        err = InvariantViolation(9, events, AssertionError("journal leak"))
        msg = str(err)
        assert "--seed 9" in msg and "--events 2" in msg
        assert "fault engine.step fail:once" in msg
        assert "action kill_replica" in msg
        assert "journal leak" in msg
        assert err.seed == 9 and len(err.events) == 2


@pytest.mark.chaos
def test_small_campaign_against_real_fleet_runs_green(tmp_path):
    """A short seeded campaign against the real ChaosFleet harness (fake
    replicas + real gateway + real control plane) completes with every
    invariant intact and an honest report."""
    from tools.chaos_campaign.harness import ChaosFleet

    fleet = ChaosFleet(n_replicas=2, persist_dir=str(tmp_path))
    try:
        report = run_campaign(fleet, seed=5, n_events=6)
    finally:
        fleet.close()
        FAULTS.reset()
    assert report.seed == 5 and report.n_events == 6
    assert report.traffic_rounds == 6
    assert report.checks == 7                # per-event + final
    total = sum(report.faults_by_point.values()) \
        + sum(report.actions.values())
    assert total == 6
    assert report.summary_lines()[0].endswith("green")
