"""Numerical-safety harness: the TPU analog of the reference's (absent)
race detector (SURVEY.md §5 — `go test -race` → jax.checkify + determinism
checks). checkify instruments the jitted forward for NaN/inf and
out-of-bounds indexing; determinism is asserted across repeated jitted runs
on identical inputs (XLA reductions are deterministic on a fixed platform;
a data race in donated-buffer reuse would surface as run-to-run drift)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import checkify

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.ops import sampling
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

F32 = jnp.float32


def test_prefill_checkify_clean():
    """No NaN/inf and no OOB indexing anywhere in the jitted prefill."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)))

    def fwd(params, tokens):
        logits, ks, vs = decoder.prefill_chunk(params, cfg, tokens)
        return logits

    checked = checkify.checkify(
        jax.jit(fwd), errors=checkify.float_checks | checkify.index_checks)
    err, logits = checked(params, tokens)
    err.throw()  # raises if any NaN/inf/OOB fired
    assert bool(jnp.isfinite(logits).all())


def test_decode_step_checkify_clean():
    """Cached decode step (the serving hot loop) under float+index checks."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, S = 2, 64
    L, KvH, hd = cfg.n_layers, cfg.n_kv_heads, cfg.head_dim
    k_cache = jnp.zeros((L, B, KvH, S, hd), F32)
    v_cache = jnp.zeros((L, B, KvH, S, hd), F32)
    tokens = jnp.ones((B, 1), jnp.int32)
    lengths = jnp.array([5, 9], jnp.int32)

    def step(params, tokens, k_cache, v_cache, lengths):
        logits, kc, vc = decoder.forward_with_cache(
            params, cfg, tokens, k_cache, v_cache, lengths, attn_len=32)
        return logits

    checked = checkify.checkify(
        jax.jit(step), errors=checkify.float_checks | checkify.index_checks)
    err, logits = checked(params, tokens, k_cache, v_cache, lengths)
    err.throw()
    assert bool(jnp.isfinite(logits).all())


def test_sampler_checkify_clean():
    cfg = cfglib.PRESETS["tiny"]
    B, V = 4, cfg.vocab_size
    logits = jnp.asarray(
        np.random.default_rng(1).standard_normal((B, V)), F32)
    counts = jnp.zeros((B, V), jnp.int32).at[:, 3].set(2)
    sp = sampling.SamplingParams.make(B, temperature=0.7)
    keys = jax.vmap(jax.random.fold_in)(
        jnp.broadcast_to(jax.random.key(0), (B,)), jnp.arange(B))

    def samp(logits, counts, sp, keys):
        return sampling.sample(logits, counts, sp, keys)

    checked = checkify.checkify(
        jax.jit(samp), errors=checkify.float_checks | checkify.index_checks)
    err, toks = checked(logits, counts, sp, keys)
    err.throw()
    assert toks.shape == (B,)


def test_engine_decode_deterministic_across_runs():
    """Two engines over the same params/prompts must emit identical
    streams — donated-buffer reuse or nondeterministic reductions would
    show up as drift here."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    opts = SlotOptions(temperature=0.8, seed=42)
    prompt = np.array([5, 9, 2, 11], np.int32)

    def run():
        eng = Engine(cfg, params,
                     ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                       cache_dtype=F32,
                                       min_prefill_bucket=16))
        out = [eng.admit(0, prompt, opts)]
        for _ in range(6):
            out.append(int(eng.decode()[0]))
        return out

    assert run() == run()
