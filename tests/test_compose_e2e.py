"""Compose e2e without a container runtime: the reconciler's pod specs
are EXECUTED as local processes by a mini-kubelet.

Round-1 VERDICT missing #1: nothing asserted that installer + reconciler
+ server compose end to end. tests/e2e/test_kind_e2e.py does the full
container version in CI; this tier runs everywhere the unit tests run by
honouring the actual container contract instead of a container runtime:

  * the store StatefulSet's pod spec (args ["serve"], TPU_STORE_ONLY=1)
    becomes a real `python -m ollama_operator_tpu.server` process,
  * the model Deployment's init container (args ["pull", <image>])
    becomes the real pull CLI pointed at the store process,
  * the server container becomes the real model server, preloading the
    CR's image through transcode,
  * readiness is only reported after each pod's REAL readinessProbe path
    answers on its local port,

so a Model CR driven by the real Manager must reach Available and the
"Service" must answer /api/generate — the reference's product promise
(ref test/e2e/e2e_test.go only asserts the manager pod runs).
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.operator.manager import Manager
from ollama_operator_tpu.operator.types import API_VERSION, KIND

from fake_kube import FakeKube
from fake_registry import FakeRegistry, add_tiny_model

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _probe_ok(port: int, path: str) -> bool:
    try:
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5).status == 200
    except Exception:
        return False


class ExecKubelet:
    """Executes workload pod specs as local processes (container args
    vocabulary + env, service DNS rewritten to local ports)."""

    def __init__(self, fake, pvc_dir: str):
        self.fake = fake
        self.pvc = pvc_dir
        os.makedirs(pvc_dir, exist_ok=True)
        self.procs = {}
        self.ports = {}            # workload name -> local http port
        self.failures = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
        for p in self.procs.values():
            p.kill()

    # -- container contract ------------------------------------------------
    def _env_for(self, spec_env, port):
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("TPU_", "OLLAMA_"))}
        env.update({e["name"]: e.get("value", "")
                    for e in spec_env if "value" in e})
        # the "volume mount": PVC paths land in our tmp dir
        env["OLLAMA_MODELS"] = os.path.join(self.pvc, "models")
        env["TPU_WEIGHT_CACHE"] = os.path.join(self.pvc, "tpu-cache")
        env.update({
            "OLLAMA_HOST_BIND": "127.0.0.1",
            "OLLAMA_PORT": str(port),
            "JAX_PLATFORMS": "cpu",
            "TPU_WARM_BUCKETS": "0",
            "TPU_MAX_SEQ_LEN": "128",
            "TPU_MAX_SLOTS": "2",
            "PYTHONPATH": REPO,
        })
        # store-service DNS -> the local store process
        if "OLLAMA_HOST" in env and "ollama-models-store" in env["OLLAMA_HOST"]:
            env["OLLAMA_HOST"] = \
                f"127.0.0.1:{self.ports['ollama-models-store']}"
        return env

    def _run_container(self, c, port, extra_env=None):
        args = c.get("args") or []
        if args[:1] == ["serve"]:
            cmd = [sys.executable, "-m", "ollama_operator_tpu.server"]
        elif args[:1] == ["pull"]:
            cmd = [sys.executable, "-m",
                   "ollama_operator_tpu.server.pull"] + args[1:]
        else:
            raise AssertionError(f"unknown container args {args}")
        env = self._env_for(c.get("env") or [], port)
        env.update(extra_env or {})
        log_path = os.path.join(
            self.pvc, f"{c['name']}-{port}-{len(self.procs)}.log")
        with open(log_path, "wb") as log:
            proc = subprocess.Popen(
                cmd, env=env, cwd=REPO,
                stdout=subprocess.DEVNULL, stderr=log)
        proc.log_path = log_path
        return proc

    @staticmethod
    def _tail(proc, n=2000):
        try:
            with open(proc.log_path, "rb") as f:
                f.seek(0, 2)
                f.seek(max(0, f.tell() - n))
                return f.read().decode("utf-8", "replace")
        except Exception:  # noqa: BLE001
            return "<no stderr captured>"

    # -- reconcile-created workloads --------------------------------------
    def _ensure_workload(self, kind, obj):
        name = obj["metadata"]["name"]
        if name in self.procs:
            return
        tmpl = obj["spec"]["template"]["spec"]
        env_names = {e["name"] for c in tmpl["containers"]
                     for e in (c.get("env") or [])}
        if kind == "StatefulSet" and "TPU_DIST_HOSTS" in env_names:
            return self._ensure_multihost(obj)
        port = _free_port()
        self.ports[name] = port
        inits = tmpl.get("initContainers") or []
        for ic in inits:
            p = self._run_container(ic, port)
            rc = p.wait(timeout=600)
            if rc != 0:
                self.failures.append(
                    (name, ic["name"], self._tail(p)))
                return
        server = tmpl["containers"][0]
        self.procs[name] = self._run_container(server, port)

    def _ensure_multihost(self, obj):
        """A multi-host slice StatefulSet: run `hosts` pods, each its own
        process with the operator's jax.distributed env rewritten to
        loopback ports (what cluster DNS would resolve). Pod 0 is the
        serving leader (build_model_service selects pod-index 0); the
        rest replay its control stream (runtime/follower.py)."""
        name = obj["metadata"]["name"]
        tmpl = obj["spec"]["template"]["spec"]
        hosts = int(obj["spec"]["replicas"])
        coord, ctl = _free_port(), _free_port()
        ports = [_free_port() for _ in range(hosts)]
        self.ports[name] = ports[0]
        for i in range(hosts):
            extra = {
                "TPU_DIST_POD_NAME": f"{name}-{i}",
                "TPU_DIST_COORDINATOR": f"127.0.0.1:{coord}",
                "TPU_DIST_CONTROL": f"127.0.0.1:{ctl}",
                # two virtual CPU chips per "host": a 2-process tp=4 world
                "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
                "TPU_EXPECT_PLATFORM": "cpu",
                # OLLAMA_MODELS stays the SHARED pvc/models dir (the
                # store writes layers there; all slice pods read them);
                # only the transcode/XLA cache is per-pod to avoid
                # concurrent-write races
                "TPU_WEIGHT_CACHE": os.path.join(self.pvc, f"cache-{i}"),
            }
            for ic in tmpl.get("initContainers") or []:
                p = self._run_container(ic, ports[i], extra)
                rc = p.wait(timeout=600)
                if rc != 0:
                    self.failures.append((name, ic["name"], self._tail(p)))
                    return
            server = tmpl["containers"][0]
            key = name if i == 0 else f"{name}#{i}"
            self.procs[key] = self._run_container(server, ports[i], extra)

    def _mark_ready(self, kind, obj):
        name = obj["metadata"]["name"]
        proc = self.procs.get(name)
        if proc is None or proc.poll() is not None:
            if proc is not None and proc.poll() is not None:
                self.failures.append((name, "server", self._tail(proc)))
            return
        ready_path = (obj["spec"]["template"]["spec"]["containers"][0]
                      .get("readinessProbe", {})
                      .get("httpGet", {}).get("path", "/healthz"))
        if not _probe_ok(self.ports[name], ready_path):
            return
        n = obj["spec"].get("replicas", 1)
        status = {"replicas": n, "readyReplicas": n}
        if kind == "Deployment":
            status["availableReplicas"] = n
        self.fake.set_status("apps/v1", kind, "default", name, status)

    def _loop(self):
        from fake_kube import Conflict
        while not self._stop.is_set():
            for kind in ("StatefulSet", "Deployment"):
                for obj in self.fake.list("apps/v1", kind, "default"):
                    try:
                        self._ensure_workload(kind, obj)
                        self._mark_ready(kind, obj)
                    except Exception as e:  # noqa: BLE001
                        self.failures.append((kind, "kubelet", repr(e)))
            for svc in self.fake.list("v1", "Service", "default"):
                if not svc["spec"].get("clusterIP"):
                    svc["spec"]["clusterIP"] = "10.0.0.9"
                    try:
                        self.fake.update(svc)
                    except Conflict:
                        pass
            self._stop.wait(0.2)


def test_model_cr_to_serving_tokens(tmp_path):
    # fixture registry with the deterministic tiny model (shared recipe
    # with the kind e2e's in-cluster registry)
    reg = FakeRegistry()
    url = reg.start()
    short = add_tiny_model(reg, gguf_path=str(tmp_path / "tiny.gguf"))
    image = f"{url}/{short}"

    fake = FakeKube()
    kubelet = ExecKubelet(fake, str(tmp_path / "pvc"))
    kubelet.start()
    mgr = Manager(fake, namespace="default", server_image="runtime:e2e")
    mgr.start(workers=2, serve_health=False)
    try:
        fake.create({
            "apiVersion": API_VERSION, "kind": KIND,
            "metadata": {"name": "tiny", "namespace": "default"},
            "spec": {"image": image, "runtime": "cpu"},
        })
        deadline = time.time() + 420
        while time.time() < deadline:
            assert not kubelet.failures, kubelet.failures
            m = fake.get(API_VERSION, KIND, "default", "tiny")
            conds = {c["type"]: c["status"]
                     for c in (m.get("status") or {}).get("conditions", [])}
            if conds.get("Available") == "True":
                break
            time.sleep(0.5)
        else:
            raise AssertionError(
                f"Model never Available: {m.get('status')} "
                f"failures={kubelet.failures}")

        # the Service answers the Ollama API (port resolved like a
        # ClusterIP would resolve to the backing pod)
        port = kubelet.ports["ollama-model-tiny"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/generate",
            data=json.dumps({"model": image, "prompt": "hi",
                             "stream": False,
                             "options": {"num_predict": 4}}).encode(),
            headers={"Content-Type": "application/json"})
        res = json.loads(urllib.request.urlopen(req, timeout=300).read())
        assert res.get("done") is True and "response" in res, res

        # the zero-config CR serves the RESOLVED defaults (VERDICT r4 #3):
        # nothing in the CR set dtype/chunk/paged, so the CPU pod must
        # report the auto-resolved config (f32 weights, chunk 8, dense) —
        # on a TPU pod the same CR resolves int8/int4 + chunk 32
        ps = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/ps", timeout=60).read())
        details = ps["models"][0]["details"]
        assert details["serving_dtype"] == "float32", details
        assert details["decode_chunk"] == 8, details
        assert details["paged"] is False, details
    finally:
        mgr.stop()
        kubelet.stop()
        reg.stop()


def test_multihost_model_cr_serves(tmp_path):
    """Multi-host serving e2e (SURVEY §7 risk 3 / round-2 VERDICT next-8):
    a 2-host StatefulSet group whose pods form a REAL jax.distributed
    world (2 processes × 2 virtual CPU chips = a tp4 mesh) behind one
    service — pod 0 serves HTTP and broadcasts engine calls, pod 1
    replays them (runtime/follower.py) — and the Model CR still drives
    CR→Available→/api/generate end to end."""
    reg = FakeRegistry()
    url = reg.start()
    short = add_tiny_model(reg, gguf_path=str(tmp_path / "tiny.gguf"))
    image = f"{url}/{short}"

    fake = FakeKube()
    kubelet = ExecKubelet(fake, str(tmp_path / "pvc"))
    kubelet.start()
    mgr = Manager(fake, namespace="default", server_image="runtime:e2e")
    mgr.start(workers=2, serve_health=False)
    try:
        fake.create({
            "apiVersion": API_VERSION, "kind": KIND,
            "metadata": {"name": "tiny", "namespace": "default"},
            "spec": {"image": image, "runtime": "tpu",
                     "tpu": {"topology": "v5e-8"}},   # 2 hosts
        })
        deadline = time.time() + 600
        m = {}
        while time.time() < deadline:
            assert not kubelet.failures, kubelet.failures
            m = fake.get(API_VERSION, KIND, "default", "tiny")
            conds = {c["type"]: c["status"]
                     for c in (m.get("status") or {}).get("conditions", [])}
            if conds.get("Available") == "True":
                break
            time.sleep(0.5)
        else:
            raise AssertionError(
                f"Model never Available: {m.get('status')} "
                f"failures={kubelet.failures}")

        port = kubelet.ports["ollama-model-tiny"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/generate",
            data=json.dumps({"model": image, "prompt": "hi",
                             "stream": False,
                             "options": {"num_predict": 6,
                                         "temperature": 0.0}}).encode(),
            headers={"Content-Type": "application/json"})
        res = json.loads(urllib.request.urlopen(req, timeout=300).read())
        assert res.get("done") is True and res.get("response"), res

        # embeddings are mirrored to the followers too (the embed jit is
        # its own SPMD program — round 3 first refused it with a 501)
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/api/embeddings",
            data=json.dumps({"model": image,
                             "prompt": "hello world"}).encode(),
            headers={"Content-Type": "application/json"})
        emb = json.loads(urllib.request.urlopen(req, timeout=300).read())
        assert len(emb["embedding"]) > 0

        # it must actually be a 2-process world serving one sharded model,
        # not two independent servers
        leader = kubelet.procs["ollama-model-tiny"]
        follower = kubelet.procs["ollama-model-tiny#1"]
        leader_log = ExecKubelet._tail(leader, 40000)
        follower_log = ExecKubelet._tail(follower, 40000)
        assert "joining 2-process world as 0" in leader_log, leader_log
        assert "joining 2-process world as 1" in follower_log, follower_log
        assert "replaying" in follower_log, follower_log
        assert follower.poll() is None, follower_log   # still replaying
    finally:
        mgr.stop()
        kubelet.stop()
        reg.stop()
