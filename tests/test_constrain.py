"""Grammar-constrained decoding (format: "json"): the byte-level JSON PDA,
the packed token masks, the native kernel's equivalence with the Python
reference, and the engine/scheduler integration (masked on-device sampling
must only ever emit grammar-legal tokens)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.ops import constrain as C
from ollama_operator_tpu.ops.constrain import (
    INITIAL_STATE, JsonConstraint, TokenTable, advance_bytes, eos_ok)
from ollama_operator_tpu.runtime.engine import (
    Engine, EngineConfig, SlotOptions, unpack_mask)
from ollama_operator_tpu.runtime.scheduler import Scheduler

F32 = jnp.float32


# --- PDA ---------------------------------------------------------------------

VALID = [
    '{}', '[]', '"x"', '0', '-0.5', '1e9', '2E-10', 'true', 'false', 'null',
    '{"a": 1}', '{"a": {"b": [1, 2, 3]}}', '[{"x": "y\\n"}, null, -3.25]',
    ' { "k" : [ true , false ] } ', '"\\u00e9\\\\"', '[[[[[]]]]]',
    '{"a":1,"b":[2,{"c":"d"}],"e":null}', '123.456e+7', '""',
]

INVALID = [
    '{,}', '[1,]', "{'a':1}", '{"a" 1}', '{"a":}', '01', '1.', '1e',
    '+1', 'tru ', '{"a": 1,}', '[1 2]', '"ab\x01c"', '{"a"}', '--1',
    ']', '}', ',', ':', '{]',
]


@pytest.mark.parametrize("doc", VALID)
def test_pda_accepts_valid(doc):
    st = advance_bytes(INITIAL_STATE, doc.encode())
    assert st is not None
    assert eos_ok(st), doc
    json.loads(doc)  # sanity: stdlib agrees it parses


@pytest.mark.parametrize("doc", INVALID)
def test_pda_rejects_invalid(doc):
    st = advance_bytes(INITIAL_STATE, doc.encode())
    # either a byte was rejected, or the doc is an incomplete/illegal value
    assert st is None or not eos_ok(st), doc


def test_pda_incomplete_not_eos():
    for prefix in ['{', '[1,', '"ab', '{"a":', '-', '1e', '[{}']:
        st = advance_bytes(INITIAL_STATE, prefix.encode())
        assert st is not None and not eos_ok(st), prefix


# --- token table / masks -----------------------------------------------------

EOS = 0
PIECES = ([b""] +  # id 0: EOS (control tokens have no bytes)
          [c.encode() for c in '{}[]":,-. \n'] +
          [str(d).encode() for d in range(10)] +
          [b"true", b"false", b"null", b'"name"', b'": "', b"},", b'"a',
           b'b"', b"\\", b"u00", b"12", b"e+", b"ab", b"cd"])


def make_table():
    return TokenTable(PIECES, eog_ids=[EOS])


def brute_force_mask(table, state):
    mask = np.zeros(table.n_words, np.uint32)
    for tid, piece in enumerate(table.pieces):
        if piece and advance_bytes(state, piece) is not None:
            mask[tid >> 5] |= np.uint32(1 << (tid & 31))
    if eos_ok(state):
        if state[0] == C.M_AFTER:
            mask = table._eog_packed.copy()
        else:
            mask = mask | table._eog_packed
    return mask


STATES = [INITIAL_STATE] + [
    advance_bytes(INITIAL_STATE, p.encode()) for p in
    ['{', '{"a"', '{"a":', '{"a": 1', '{"a": 1,', '[', '[1', '[1,',
     '"x', '"x\\', '"x\\u0', '12', '12.', '12.5e', 'tr', '{"a": {"b": [',
     '{"a": [1, {"b": 2}', '3']]


@pytest.mark.parametrize("state", STATES, ids=range(len(STATES)))
def test_mask_matches_brute_force(state):
    table = make_table()
    got = table.mask_for(state)
    np.testing.assert_array_equal(got, brute_force_mask(table, state))


def test_native_kernel_matches_python():
    if C._load_native() is None:
        pytest.skip("no native grammar kernel (g++ unavailable)")
    # fresh tables so caches don't mix the two paths
    native_table = make_table()
    for state in STATES:
        native = np.zeros(native_table.n_words, np.uint32)
        key = native_table._cache_key(state)
        st = np.frombuffer(key, np.uint8).copy()
        C._load_native().json_fill_mask(
            st, np.int32(len(key)), native_table._flat, native_table._off,
            np.int32(native_table.n_vocab), native)
        expect = np.zeros(native_table.n_words, np.uint32)
        for tid, piece in enumerate(native_table.pieces):
            if piece and advance_bytes(state, piece) is not None:
                expect[tid >> 5] |= np.uint32(1 << (tid & 31))
        np.testing.assert_array_equal(native, expect)


def test_mask_cache_stack_suffix_is_exact():
    """Two states that differ only below the reachable stack suffix must
    (and do) share a mask; states differing within it must not collide."""
    table = make_table()
    deep_obj = advance_bytes(INITIAL_STATE, b'{"a":' * 40 + b"[")
    deeper = advance_bytes(INITIAL_STATE, b'{"a":' * 50 + b"[")
    assert table._cache_key(deep_obj) == table._cache_key(deeper)
    in_arr = advance_bytes(INITIAL_STATE, b"[")
    in_obj_arr = advance_bytes(INITIAL_STATE, b'{"a": [')
    assert table._cache_key(in_arr) != table._cache_key(in_obj_arr)


def test_constraint_lifecycle():
    table = make_table()
    c = JsonConstraint(table)
    tid = PIECES.index(b"{")
    assert c.advance(tid)
    assert not c.done
    assert c.advance(PIECES.index(b"}"))
    assert c.done
    # complete object → only EOS remains legal
    mask = c.mask_row()
    bits = np.unpackbits(mask.view(np.uint8), bitorder="little")
    assert bits[EOS] == 1 and bits.sum() == 1


def test_unpack_mask_roundtrip():
    V = 77
    rng = np.random.default_rng(0)
    dense = rng.integers(0, 2, V).astype(bool)
    packed = np.zeros(((V + 31) // 32,), np.uint32)
    for i in np.nonzero(dense)[0]:
        packed[i >> 5] |= np.uint32(1 << (i & 31))
    got = np.asarray(unpack_mask(jnp.asarray(packed[None]), V))[0]
    np.testing.assert_array_equal(got, dense)


# --- engine / scheduler integration ------------------------------------------

def test_scheduler_constrained_decode_emits_json():
    """End to end on the tiny model: every sampled token must be grammar-
    legal (valid JSON prefix), and an EOS stop implies a complete value."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                   cache_dtype=F32, min_prefill_bucket=16))
    sched = Scheduler(eng)
    table = make_table()
    try:
        outputs = 0
        for seed in range(4):
            c = JsonConstraint(table)
            req = sched.submit(
                [5, 9, 2], SlotOptions(temperature=0.9, seed=seed,
                                       repeat_penalty=1.0),
                max_tokens=100, eog_ids=frozenset([EOS]), constraint=c)
            toks = list(req.tokens())
            data = b"".join(table.pieces[t] for t in toks)
            st = advance_bytes(INITIAL_STATE, data)
            assert st is not None, (seed, data)
            if req.stats.n_generated < 100:  # stopped via EOS
                json.loads(data.decode())
                outputs += 1
        assert outputs >= 1  # at least one run must complete a value
    finally:
        sched.shutdown()


def test_constrained_and_free_slots_coexist():
    """A constrained slot must not leak its mask into other slots."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = Engine(cfg, params,
                 ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                   cache_dtype=F32, min_prefill_bucket=16))
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    prompt = np.array([3, 1, 4], np.int32)
    free_ref = [eng.admit(0, prompt, greedy)]
    for _ in range(5):
        free_ref.append(int(eng.decode()[0]))
    eng.release(0)

    table = make_table()
    c = JsonConstraint(table)
    eng2 = Engine(cfg, params,
                  ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                    cache_dtype=F32, min_prefill_bucket=16))
    got = [eng2.admit(0, prompt, greedy)]
    # constrained request in the other slot
    first = eng2.admit(1, np.array([7, 7], np.int32),
                       SlotOptions(temperature=0.9, seed=1,
                                   repeat_penalty=1.0),
                       mask_row=c.mask_row())
    assert c.advance(first)
    eng2.set_mask(1, c.mask_row())
    for _ in range(5):
        toks = eng2.decode()
        got.append(int(toks[0]))
        if c.advance(int(toks[1])):
            eng2.set_mask(1, c.mask_row())
    assert got == free_ref


def test_constrained_slot_does_not_collapse_batch_throughput():
    """Round-1 weak #5: one constrained slot used to force the whole batch
    to n=1 per dispatch. Per-slot step budgets now freeze ONLY the
    constrained slot after the chunk's first step — the free slot must
    advance decode_chunk tokens per decode_n() call, and its token stream
    must be unchanged by the constrained neighbour."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    greedy = SlotOptions(temperature=0.0, repeat_penalty=1.0)
    prompt = np.array([3, 1, 4, 1, 5], np.int32)
    chunk = 4

    def make():
        return Engine(cfg, params,
                      ecfg=EngineConfig(max_slots=2, max_seq_len=128,
                                        cache_dtype=F32,
                                        min_prefill_bucket=16,
                                        decode_chunk=chunk))

    # reference: free slot alone
    ref_eng = make()
    ref = [ref_eng.admit(0, prompt, greedy)]
    ref.extend(int(t) for t in ref_eng.decode_n(chunk)[:, 0])
    ref.extend(int(t) for t in ref_eng.decode_n(chunk)[:, 0])

    eng = make()
    got = [eng.admit(0, prompt, greedy)]
    table = make_table()
    c = JsonConstraint(table)
    eng.admit(1, np.array([7, 7], np.int32),
              SlotOptions(temperature=0.9, seed=3, repeat_penalty=1.0),
              mask_row=c.mask_row())
    eng.set_mask(1, c.mask_row())
    len0 = eng._host_lengths.copy()
    for _ in range(2):
        toks = eng.decode_n(chunk)
        got.extend(int(t) for t in toks[:, 0])
        # constrained slot: only row 0 is real; advance its PDA + mask
        c.advance(int(toks[0, 1]))
        eng.set_mask(1, c.mask_row())
    # free slot advanced a full chunk per call, constrained slot 1/call
    assert eng._host_lengths[0] - len0[0] == 2 * chunk
    assert eng._host_lengths[1] - len0[1] == 2
    assert got == ref, (got, ref)
