"""Request deadlines over the HTTP surface (ISSUE 2 acceptance tests).

A `deadline_ms` exceeded while QUEUED must map to 503 + Retry-After (the
client never got a byte, retrying elsewhere is correct); exceeded
MID-GENERATION must end the already-started stream with a clean terminal
frame (`done_reason: "timeout"`) and leave the slot reusable.  Slot
contention is produced with the deterministic `engine.step` delay fault,
not wall-clock luck.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import EngineConfig
from ollama_operator_tpu.runtime.errors import BadRequest
from ollama_operator_tpu.runtime.faults import FAULTS
from ollama_operator_tpu.runtime.service import resolve_deadline_s
from ollama_operator_tpu.server.app import ModelManager, serve

from fake_registry import FakeRegistry
from test_transcode import write_tiny_llama_gguf


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """Single-slot server: one in-flight request saturates the engine,
    so queue-wait behaviour is deterministic."""
    tmp = tmp_path_factory.mktemp("deadlines")
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0),
                                 dtype=jnp.float32)
    gguf_path = str(tmp / "tiny.gguf")
    write_tiny_llama_gguf(gguf_path, cfg, params)
    with open(gguf_path, "rb") as f:
        gguf_bytes = f.read()

    reg = FakeRegistry()
    url = reg.start()
    reg.add_model("library", "tiny", "latest", gguf_bytes,
                  template="{{ .System }}|{{ .Prompt }}",
                  params={"temperature": 0.0, "repeat_penalty": 1.0,
                          "num_predict": 8})

    manager = ModelManager(str(tmp / "store"), cache_dir=str(tmp / "cache"),
                           ecfg=EngineConfig(max_slots=1, max_seq_len=192,
                                             cache_dtype=jnp.float32,
                                             min_prefill_bucket=16),
                           engine_dtype="float32")
    httpd = serve(manager, "127.0.0.1", 0)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    host = url.split("://")[1]
    model = f"http://{host}/library/tiny:latest"
    req = urllib.request.Request(
        base + "/api/pull", data=json.dumps({"model": model}).encode(),
        headers={"Content-Type": "application/json"})
    urllib.request.urlopen(req, timeout=120).read()
    yield {"base": base, "model": model, "manager": manager}
    httpd.shutdown()
    reg.stop()


def _post_stream(base, payload, timeout=120):
    """POST /api/generate, return parsed NDJSON lines."""
    req = urllib.request.Request(
        base + "/api/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    resp = urllib.request.urlopen(req, timeout=timeout)
    return [json.loads(l) for l in resp.read().decode().splitlines()
            if l.strip()]


def _open_stream(base, payload, timeout=120):
    req = urllib.request.Request(
        base + "/api/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


# -- resolve_deadline_s unit surface -----------------------------------

def test_resolve_deadline_precedence(monkeypatch):
    monkeypatch.delenv("TPU_REQUEST_DEADLINE_MS", raising=False)
    assert resolve_deadline_s(None, None) is None
    assert resolve_deadline_s({}, {"deadline_ms": 1500}) == 1.5
    # request option beats modelfile default beats env
    assert resolve_deadline_s({"deadline_ms": 9000},
                              {"deadline_ms": 250}) == 0.25
    assert resolve_deadline_s({"deadline_ms": 9000}, {}) == 9.0
    monkeypatch.setenv("TPU_REQUEST_DEADLINE_MS", "2000")
    assert resolve_deadline_s(None, None) == 2.0
    assert resolve_deadline_s(None, {"deadline_ms": 100}) == 0.1
    # 0 disables, even over a nonzero env default
    assert resolve_deadline_s(None, {"deadline_ms": 0}) is None


def test_resolve_deadline_invalid():
    with pytest.raises(BadRequest):
        resolve_deadline_s(None, {"deadline_ms": "soon"})
    with pytest.raises(BadRequest):
        resolve_deadline_s(None, {"deadline_ms": -5})


def test_bad_deadline_is_400(stack):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_stream(stack["base"],
                     {"model": stack["model"], "prompt": "x",
                      "options": {"deadline_ms": "soon"}})
    assert ei.value.code == 400


# -- queued expiry → 503 + Retry-After ---------------------------------

@pytest.mark.chaos
def test_deadline_while_queued_is_503_with_retry_after(stack):
    """Saturate the single slot with a slow request; a queued request
    whose deadline lapses is shed with 503 + Retry-After, while the
    in-flight holder streams to completion untouched."""
    FAULTS.arm("engine.step", "delay:80ms")
    holder_lines = []
    holder_err = []

    def run_holder(resp):
        try:
            holder_lines.extend(
                json.loads(l) for l in resp.read().decode().splitlines()
                if l.strip())
        except Exception as e:          # surfaced in the main thread
            holder_err.append(e)

    # open the holder and wait for its FIRST frame => it owns the slot
    resp = _open_stream(stack["base"],
                        {"model": stack["model"], "prompt": "hold",
                         "options": {"num_predict": 96}})
    first = json.loads(resp.readline())
    assert not first.get("done")
    t = threading.Thread(target=run_holder, args=(resp,))
    t.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_stream(stack["base"],
                         {"model": stack["model"], "prompt": "hurry",
                          "options": {"deadline_ms": 60,
                                      "num_predict": 4}})
        assert ei.value.code == 503
        retry_after = ei.value.headers.get("Retry-After")
        assert retry_after is not None and int(retry_after) >= 1
    finally:
        t.join(timeout=120)
    assert not holder_err
    assert holder_lines and holder_lines[-1]["done"]
    assert holder_lines[-1]["done_reason"] in ("stop", "length")


# -- mid-generation expiry → terminal timeout frame --------------------

@pytest.mark.chaos
def test_deadline_mid_generation_terminal_frame_and_slot_reuse(stack):
    """Once streaming has started the deadline can't become a status
    code; the stream must end with done_reason:"timeout" — and the slot
    must be immediately reusable afterwards."""
    FAULTS.arm("engine.step", "delay:120ms")
    lines = _post_stream(stack["base"],
                         {"model": stack["model"], "prompt": "long one",
                          "options": {"deadline_ms": 300,
                                      "num_predict": 150}})
    final = lines[-1]
    assert final["done"] is True
    assert final["done_reason"] == "timeout"
    # partial output was streamed before the cut
    assert any(l.get("response") for l in lines[:-1])
    # fewer tokens than asked: the deadline, not num_predict, ended it
    assert final["eval_count"] < 150

    FAULTS.reset()
    lines = _post_stream(stack["base"],
                         {"model": stack["model"], "prompt": "after",
                          "options": {"num_predict": 5}})
    assert lines[-1]["done"] is True
    assert lines[-1]["done_reason"] in ("stop", "length")
    assert lines[-1]["eval_count"] == 5


# -- detok fault: kills one stream, not the server ---------------------

@pytest.mark.chaos
def test_detok_fault_errors_one_stream_slot_reusable(stack):
    """A detokeniser fault before the first byte maps to a 500 for that
    request only; generator cleanup cancels it and frees the slot."""
    FAULTS.arm("detok.feed", "fail:once")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_stream(stack["base"],
                     {"model": stack["model"], "prompt": "boom",
                      "options": {"num_predict": 4}})
    assert ei.value.code == 500
    lines = _post_stream(stack["base"],
                         {"model": stack["model"], "prompt": "fine",
                          "options": {"num_predict": 4}})
    assert lines[-1]["done"] is True
    assert lines[-1]["eval_count"] == 4


# -- /api/ps surfaces failure counters ---------------------------------

def test_ps_reports_failure_block(stack):
    # ensure the model is loaded regardless of which tests ran before
    _post_stream(stack["base"], {"model": stack["model"], "prompt": "warm",
                                 "options": {"num_predict": 1}})
    body = urllib.request.urlopen(stack["base"] + "/api/ps",
                                  timeout=30).read()
    models = json.loads(body)["models"]
    assert models, "model should be loaded"
    fb = models[0]["failures"]
    assert fb["broken"] is False
    assert isinstance(fb["engine_restarts"], int)
    assert isinstance(fb["request_timeouts"], int)
    assert isinstance(fb["requests_shed"], int)
    assert isinstance(fb["followers_lost"], int)
