"""Decoder correctness: prefill/decode equivalence, arch variants, masks."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder

F32 = jnp.float32


def tiny(**kw):
    base = cfglib.PRESETS["tiny"]
    return cfglib.ModelConfig(**{**base.__dict__, **kw}).validate()


def make_cache(cfg, B, S, dtype=F32):
    # head-first layout [L, B, KvH, S, hd] (models/decoder.py)
    shape = (cfg.n_layers, B, cfg.n_kv_heads, S, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


@pytest.mark.parametrize("name,kw", [
    ("llama", {}),
    ("gqa1", dict(n_kv_heads=1)),
    ("mistral-window", dict(sliding_window=8, n_kv_heads=2)),
    ("qwen-bias", dict(attn_bias=True)),
    ("gemma-ish", dict(act="gelu_tanh", emb_scale=True, tie_embeddings=True,
                       norm_weight_offset=1.0)),
    ("phi2-ish", dict(norm_type="layernorm", mlp_type="plain", act="gelu_tanh",
                      parallel_block=True, attn_bias=True, out_bias=True,
                      rotary_pct=0.5)),
    ("softcap", dict(logit_softcap=30.0, attn_softcap=50.0)),
    ("qknorm", dict(qk_norm=True)),
])
def test_prefill_decode_equivalence(name, kw):
    """Prefill of N tokens must equal prefill(N-k) + k decode steps."""
    cfg = tiny(**kw)
    key = jax.random.PRNGKey(0)
    params = decoder.init_params(cfg, key, dtype=F32)
    B, T = 2, 12
    split = 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                cfg.vocab_size)

    ref_logits, _, _ = decoder.prefill_chunk(params, cfg, tokens)

    # prefill first `split`, then decode the rest one token at a time
    logits_p, ks, vs = decoder.prefill_chunk(params, cfg, tokens[:, :split])
    S = 32
    k_cache, v_cache = make_cache(cfg, B, S)
    k_cache = k_cache.at[:, :, :, :split].set(ks)
    v_cache = v_cache.at[:, :, :, :split].set(vs)
    lengths = jnp.full((B,), split, jnp.int32)

    np.testing.assert_allclose(np.asarray(logits_p),
                               np.asarray(ref_logits[:, :split]),
                               rtol=2e-4, atol=2e-4)

    for t in range(split, T):
        step_logits, k_cache, v_cache = decoder.forward_with_cache(
            params, cfg, tokens[:, t:t + 1], k_cache, v_cache, lengths)
        np.testing.assert_allclose(np.asarray(step_logits[:, 0]),
                                   np.asarray(ref_logits[:, t]),
                                   rtol=2e-4, atol=2e-4,
                                   err_msg=f"{name} step {t}")
        lengths = lengths + 1


def test_chunked_prefill_matches_full():
    """forward_with_cache with T>1 (chunk continuation) matches full prefill."""
    cfg = tiny(n_kv_heads=2)
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    B, T = 1, 16
    tokens = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0,
                                cfg.vocab_size)
    ref_logits, _, _ = decoder.prefill_chunk(params, cfg, tokens)

    _, ks, vs = decoder.prefill_chunk(params, cfg, tokens[:, :8])
    k_cache, v_cache = make_cache(cfg, B, 32)
    k_cache = k_cache.at[:, :, :, :8].set(ks)
    v_cache = v_cache.at[:, :, :, :8].set(vs)
    logits2, _, _ = decoder.forward_with_cache(
        params, cfg, tokens[:, 8:], k_cache, v_cache,
        jnp.full((B,), 8, jnp.int32))
    np.testing.assert_allclose(np.asarray(logits2),
                               np.asarray(ref_logits[:, 8:]),
                               rtol=2e-4, atol=2e-4)


def test_ragged_batch_decode():
    """Slots with different lengths decode independently and correctly."""
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    t_a = jax.random.randint(jax.random.PRNGKey(3), (1, 10), 0, cfg.vocab_size)
    t_b = jax.random.randint(jax.random.PRNGKey(4), (1, 6), 0, cfg.vocab_size)

    # references computed per-sequence
    ref_a, _, _ = decoder.prefill_chunk(params, cfg, t_a)
    ref_b, _, _ = decoder.prefill_chunk(params, cfg, t_b)

    S = 32
    k_cache, v_cache = make_cache(cfg, 2, S)
    _, ka, va = decoder.prefill_chunk(params, cfg, t_a[:, :9])
    _, kb, vb = decoder.prefill_chunk(params, cfg, t_b[:, :5])
    k_cache = k_cache.at[:, 0:1, :, :9].set(ka)
    v_cache = v_cache.at[:, 0:1, :, :9].set(va)
    k_cache = k_cache.at[:, 1:2, :, :5].set(kb)
    v_cache = v_cache.at[:, 1:2, :, :5].set(vb)
    lengths = jnp.array([9, 5], jnp.int32)
    step_tokens = jnp.stack([t_a[0, 9], t_b[0, 5]])[:, None]
    logits, _, _ = decoder.forward_with_cache(params, cfg, step_tokens,
                                              k_cache, v_cache, lengths)
    np.testing.assert_allclose(np.asarray(logits[0, 0]),
                               np.asarray(ref_a[0, 9]), rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(logits[1, 0]),
                               np.asarray(ref_b[0, 5]), rtol=2e-4, atol=2e-4)


def test_padding_does_not_leak():
    """Right-padding a prefill chunk must not change valid-position logits."""
    cfg = tiny()
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (1, 6), 0,
                                cfg.vocab_size)
    ref, _, _ = decoder.prefill_chunk(params, cfg, tokens)
    padded = jnp.pad(tokens, ((0, 0), (0, 10)))
    out, _, _ = decoder.prefill_chunk(params, cfg, padded)
    np.testing.assert_allclose(np.asarray(out[:, :6]), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_param_count_sane():
    cfg = cfglib.get_config("llama2")
    assert 6.5e9 < cfg.n_params < 7.1e9
    cfg70 = cfglib.get_config("llama2:70b")
    assert 6.5e10 < cfg70.n_params < 7.2e10
