"""Deploy-config validation: CRD schema ↔ operator objects ↔ installer.

The reference trusts controller-gen to keep the CRD schema and Go types in
sync; with a hand-maintained schema that invariant needs a test — every
spec field the operator reads must be declared in the CRD schema (and vice
versa), sample CRs must validate, and the installer bundle must be
self-consistent (RBAC subjects point at objects it creates, image pinned).
"""

import os
import subprocess
import sys

import pytest

yaml = pytest.importorskip("yaml")

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load(path):
    with open(os.path.join(ROOT, path)) as f:
        return list(yaml.safe_load_all(f))


@pytest.fixture(scope="module")
def crd():
    (doc,) = load("config/crd/ollama.ayaka.io_models.yaml")
    return doc


@pytest.fixture(scope="module")
def spec_schema(crd):
    v1 = next(v for v in crd["spec"]["versions"] if v["name"] == "v1")
    return v1["schema"]["openAPIV3Schema"]["properties"]["spec"]


class TestCrdSchema:
    def test_identity_matches_reference(self, crd):
        assert crd["metadata"]["name"] == "models.ollama.ayaka.io"
        assert crd["spec"]["group"] == "ollama.ayaka.io"
        assert crd["spec"]["names"]["kind"] == "Model"
        v1 = next(v for v in crd["spec"]["versions"] if v["name"] == "v1")
        assert v1["storage"] and v1["served"]
        assert v1["subresources"] == {"status": {}}
        cols = {c["jsonPath"] for c in v1["additionalPrinterColumns"]}
        # the reference's printcolumns (crd.yaml:17-23) survive
        assert ".spec.image" in cols
        assert ".status.conditions[0].type" in cols

    def test_schema_covers_every_field_the_operator_reads(self, spec_schema):
        """ModelSpecView's accessors define what the operator consumes;
        each must be declared (else the apiserver silently prunes it)."""
        declared = set(spec_schema["properties"])
        consumed = {"image", "replicas", "imagePullPolicy",
                    "imagePullSecrets", "storageClassName",
                    "persistentVolumeClaim", "persistentVolume",
                    "runtime", "tpu", "contextLength", "sharding",
                    "quantization", "serverImage"}
        missing = consumed - declared
        assert not missing, f"CRD schema missing: {missing}"
        assert spec_schema["required"] == ["image"]

    def test_topologies_in_schema_docs_match_catalog(self, spec_schema):
        from ollama_operator_tpu.operator.types import TPU_TOPOLOGIES
        desc = spec_schema["properties"]["tpu"]["properties"][
            "topology"]["description"]
        for t in ("v5e-1", "v5e-4", "v5e-16"):
            assert t in TPU_TOPOLOGIES and t in desc

    def test_samples_validate_against_schema(self, spec_schema):
        from ollama_operator_tpu.operator.types import ModelSpecView
        for doc in load("config/samples/ollama_v1_model.yaml"):
            declared = set(spec_schema["properties"])
            assert set(doc["spec"]) <= declared, doc["metadata"]["name"]
            view = ModelSpecView(doc)
            assert view.image
            view.tpu_placement()  # raises on an unknown topology


class TestInstaller:
    @pytest.fixture(scope="class")
    def bundle(self, tmp_path_factory):
        out = tmp_path_factory.mktemp("dist") / "install.yaml"
        subprocess.run(
            [sys.executable, os.path.join(ROOT, "hack/build_installer.py"),
             "--image", "example.com/runtime:v9", "-o", str(out)],
            check=True, capture_output=True)
        with open(out) as f:
            return list(yaml.safe_load_all(f))

    def test_bundle_contents(self, bundle):
        kinds = [(d["kind"], d["metadata"]["name"]) for d in bundle]
        assert ("CustomResourceDefinition", "models.ollama.ayaka.io") in kinds
        assert ("Namespace", "ollama-operator-system") in kinds
        assert ("Deployment", "ollama-operator-controller-manager") in kinds

    def test_rbac_subjects_resolve(self, bundle):
        by_kind = {}
        for d in bundle:
            by_kind.setdefault(d["kind"], []).append(d)
        sas = {(d["metadata"]["name"], d["metadata"].get("namespace"))
               for d in by_kind["ServiceAccount"]}
        for b in by_kind["ClusterRoleBinding"] + by_kind["RoleBinding"]:
            for s in b["subjects"]:
                assert (s["name"], s.get("namespace")) in sas, b
            roles = {d["metadata"]["name"]
                     for d in by_kind.get(b["roleRef"]["kind"], [])}
            assert b["roleRef"]["name"] in roles, b

    def test_image_is_pinned(self, bundle):
        dep = next(d for d in bundle if d["kind"] == "Deployment")
        c = dep["spec"]["template"]["spec"]["containers"][0]
        assert c["image"] == "example.com/runtime:v9"
        assert c["args"][0] == "operator"

    def test_manager_rbac_covers_reconciler_verbs(self, bundle):
        """Every (group, resource) the reconciler touches is granted."""
        role = next(d for d in bundle if d["kind"] == "ClusterRole")
        granted = set()
        for rule in role["rules"]:
            for g in rule["apiGroups"]:
                for r in rule["resources"]:
                    granted.add((g, r))
        needed = [("ollama.ayaka.io", "models"),
                  ("ollama.ayaka.io", "models/status"),
                  ("apps", "deployments"), ("apps", "statefulsets"),
                  ("", "services"), ("", "persistentvolumeclaims"),
                  ("", "events")]
        for pair in needed:
            assert pair in granted, pair
