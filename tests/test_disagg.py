"""Disaggregated prefill/decode serving (ISSUE 20).

Coverage, bottom-up:

- the kv_wire codec: round-trip fidelity, the version guard, and every
  structural rejection (a bad blob is "no warm start", never a crash);
- engine-level KV transfer parity: a prefill engine exports a request's
  radix pages, a decode engine imports them and continues — the client
  stream (prefill's first token + decode's continuation) must be
  bit-identical to a unified single-engine run, greedy AND seeded,
  cross-checked against a dense engine; the no-transfer arms (dense
  export, cold decode re-prefill, already-warm decode) land on the
  same bytes; byte-budget cuts keep the shipped chain rooted; the
  pages.{export,import} fault points leave page tables clean;
- gateway-level handoff: the prefill leg streams the first token, the
  decode pool serves the splice — transferred / replayed (prefill dies
  before the handoff frame, or exactly at the KV export pull) /
  unified_fallback all keep the client stream byte-identical with zero
  error frames, with tpu_model_disagg_handoffs_total telling the truth;
- the chaos drill: a seeded campaign over the pooled fleet where
  kill_prefill_mid_handoff fires stays green — journal drained, every
  stream terminal exactly once (run_campaign's final check).
"""

import dataclasses
import pickle

import numpy as np
import pytest

from ollama_operator_tpu.runtime import kv_wire
from ollama_operator_tpu.runtime.faults import FAULTS, InjectedFault
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


def metric(name, labels=""):
    return METRICS.get(name, labels)


# ---------------------------------------------------------------------------
# kv_wire codec (no engine, no jax)
# ---------------------------------------------------------------------------

def _rec(parent, chunk, fill):
    kv = ({"l0": np.full((1, 1, 2, 4), fill, np.float32)},
          {"l0": np.full((1, 1, 2, 4), -fill, np.float32)})
    return kv_wire.record(parent, chunk, kv)


class TestWireCodec:
    def test_round_trip(self):
        recs = [_rec(-1, [1, 2, 3, 4], 1.0), _rec(0, [5, 6, 7, 8], 2.0)]
        blob = kv_wire.encode(recs, page_size=4)
        out = kv_wire.decode(blob, page_size=4)
        assert len(out) == 2
        assert [r["p"] for r in out] == [-1, 0]
        np.testing.assert_array_equal(out[1]["c"],
                                      np.array([5, 6, 7, 8], np.int32))
        np.testing.assert_array_equal(out[0]["k"]["l0"],
                                      recs[0]["k"]["l0"])
        assert kv_wire.kv_spec((out[0]["k"], out[0]["v"])) == \
            kv_wire.kv_spec((recs[0]["k"], recs[0]["v"]))

    def test_kv_nbytes_counts_both_trees(self):
        r = _rec(-1, [1], 1.0)
        assert kv_wire.kv_nbytes((r["k"], r["v"])) == 2 * 8 * 4

    @pytest.mark.parametrize("blob", [
        b"",
        b"not a pickle at all",
        pickle.dumps([1, 2, 3]),                               # root not dict
        pickle.dumps({"v": 999, "ps": 4, "recs": []}),         # version skew
        pickle.dumps({"v": kv_wire.WIRE_VERSION, "ps": 8,
                      "recs": []}),                            # page-size skew
        pickle.dumps({"v": kv_wire.WIRE_VERSION, "ps": 4,
                      "recs": {"not": "a list"}}),
        pickle.dumps({"v": kv_wire.WIRE_VERSION, "ps": 4,
                      "recs": [{"p": -1}]}),                   # malformed rec
    ])
    def test_structural_rejections(self, blob):
        with pytest.raises(kv_wire.WireError):
            kv_wire.decode(blob, page_size=4)

    def test_forward_parent_rejected(self):
        # a record may only point at an EARLIER record: every decodable
        # chain is rooted by construction
        recs = [_rec(0, [1], 1.0)]
        blob = kv_wire.encode(recs, page_size=4)
        with pytest.raises(kv_wire.WireError):
            kv_wire.decode(blob, page_size=4)


# ---------------------------------------------------------------------------
# engine-level transfer parity (real tiny engines, CPU jax)
# ---------------------------------------------------------------------------

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ollama_operator_tpu.models import decoder  # noqa: E402
from ollama_operator_tpu.models.config import PRESETS  # noqa: E402
from ollama_operator_tpu.runtime.engine import (Engine,  # noqa: E402
                                                EngineConfig, SlotOptions)

XLA = dataclasses.replace(PRESETS["tiny"], kernels="xla")
GREEDY = SlotOptions(temperature=0.0)
SEEDED = SlotOptions(temperature=0.9, top_k=40, seed=7)
DENSE = EngineConfig(max_slots=4, max_seq_len=64, cache_dtype=jnp.float32,
                     min_prefill_bucket=16)
PAGED = dataclasses.replace(DENSE, paged=True, page_size=8)

PROMPT = np.arange(1, 25, dtype=np.int32)        # 24 tokens = 3 full pages
N_STEPS = 4


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(XLA, jax.random.key(0), jnp.float32)


def _gen(eng, slot, full, opts, n):
    first = eng.admit(slot, np.asarray(full, np.int32), opts)
    return [first] + [int(eng.decode()[slot]) for _ in range(n)]


def _export_blob(eng, opts):
    """Run the prefill side on ``eng``: admit PROMPT, take the first
    token, park the prompt pages, export the request chain. Returns
    (first_token, blob)."""
    first = eng.admit(0, PROMPT, opts)
    eng.donate_prefix(0, list(PROMPT))
    blob = eng.export_request_kv(list(PROMPT) + [first])
    return first, blob


@pytest.mark.parametrize("key,opts", [("greedy", GREEDY), ("seeded", SEEDED)])
def test_handoff_stream_parity(params, key, opts):
    """The disagg client stream — prefill replica's first token, then
    the decode replica's continuation over transferred pages — must be
    bit-identical to a unified run (and to a dense engine: the paged
    transfer machinery may not perturb sampling)."""
    ref_eng = Engine(XLA, params, ecfg=PAGED)
    ref = _gen(ref_eng, 0, PROMPT, opts, N_STEPS)
    dense_ref = _gen(Engine(XLA, params, ecfg=DENSE), 0, PROMPT, opts,
                     N_STEPS)
    assert ref == dense_ref, f"paged-vs-dense unified drift ({key})"

    pre = Engine(XLA, params, ecfg=PAGED)
    first, blob = _export_blob(pre, opts)
    assert first == ref[0], f"prefill first-token drift ({key})"
    assert blob is not None
    assert len(kv_wire.decode(blob, PAGED.page_size)) == 3

    dec = Engine(XLA, params, ecfg=PAGED)
    assert dec.import_request_kv(blob) == 3
    assert dec.radix_pages == 3
    want, tier = dec.prefix_probe_tier(PROMPT)
    assert tier == 0 and want >= 16          # at least the full pages
    got = dec.stitch(0, PROMPT, want)
    assert got >= 16                         # the transfer really served
    out = [dec.extend(0, PROMPT, got, opts)] \
        + [int(dec.decode()[0]) for _ in range(N_STEPS)]
    assert out == ref, f"transferred stream drift ({key})"
    for eng in (pre, dec):
        eng._pt.check()


@pytest.mark.parametrize("key,opts", [("greedy", GREEDY), ("seeded", SEEDED)])
def test_replay_without_transfer_is_bit_identical(params, key, opts):
    """The 'replayed' rung: no pages moved (transfer failed, dense
    engine, cold decode replica) — the decode side re-prefills from the
    prompt and must land on the same bytes."""
    ref = _gen(Engine(XLA, params, ecfg=PAGED), 0, PROMPT, opts, N_STEPS)
    cold = _gen(Engine(XLA, params, ecfg=PAGED), 0, PROMPT, opts, N_STEPS)
    assert cold == ref, f"cold replay drift ({key})"


def test_dense_engine_export_is_a_soft_none(params):
    """A dense engine has no page pool: export answers None (the
    gateway downgrades to replay), never an error."""
    eng = Engine(XLA, params, ecfg=DENSE)
    _gen(eng, 0, PROMPT, GREEDY, 1)
    assert eng.export_request_kv(list(PROMPT)) is None


def test_export_without_parked_prefix_is_none(params):
    eng = Engine(XLA, params, ecfg=PAGED)
    assert eng.export_request_kv(list(PROMPT)) is None


def test_import_rejects_garbage_and_geometry_skew(params):
    """A bad blob imports 0 pages and leaves the table untouched — a
    transfer is a warm start, never a correctness dependency."""
    eng = Engine(XLA, params, ecfg=PAGED)
    free0 = eng.free_pages
    assert eng.import_request_kv(b"") == 0
    assert eng.import_request_kv(b"garbage bytes") == 0
    # structurally valid blob whose page geometry misses this engine
    blob = kv_wire.encode([_rec(-1, list(range(PAGED.page_size)), 1.0)],
                          PAGED.page_size)
    assert eng.import_request_kv(blob) == 0
    assert eng.free_pages == free0 and eng.radix_pages == 0
    eng._pt.check()


def test_byte_budget_cut_keeps_rooted_chain(params):
    """An export that hits its byte budget stops at the cut (never
    skips a page): the shipped chain stays rooted and imports as a
    usable shorter prefix."""
    pre = Engine(XLA, params, ecfg=PAGED)
    _first, blob = _export_blob(pre, GREEDY)
    recs = kv_wire.decode(blob, PAGED.page_size)
    per_page = kv_wire.kv_nbytes((recs[0]["k"], recs[0]["v"]))
    cut = pre.export_request_kv(list(PROMPT), max_bytes=2 * per_page + 64)
    short = kv_wire.decode(cut, PAGED.page_size)
    assert len(short) == 2
    assert [r["p"] for r in short] == [-1, 0]
    dec = Engine(XLA, params, ecfg=PAGED)
    assert dec.import_request_kv(cut) == 2
    want, tier = dec.prefix_probe_tier(PROMPT)
    assert tier == 0 and want == 16
    dec._pt.check()


def test_import_skips_pages_already_resident(params):
    """A decode replica that already holds the prefix HBM-hot keeps its
    own pages (nothing uploaded) and still serves the stream."""
    pre = Engine(XLA, params, ecfg=PAGED)
    _first, blob = _export_blob(pre, GREEDY)
    dec = Engine(XLA, params, ecfg=PAGED)
    warm_first, _ = _export_blob(dec, GREEDY)   # parks the same prefix
    assert dec.radix_pages == 3
    free0 = dec.free_pages
    assert dec.import_request_kv(blob) == 0     # all resident: no uploads
    assert dec.free_pages == free0 and dec.radix_pages == 3
    ref = _gen(Engine(XLA, params, ecfg=PAGED), 0, PROMPT, GREEDY, N_STEPS)
    got = dec.stitch(0, PROMPT, 16)
    out = [dec.extend(0, PROMPT, got, GREEDY)] \
        + [int(dec.decode()[0]) for _ in range(N_STEPS)]
    assert out == ref and warm_first == ref[0]
    dec._pt.check()


def test_pages_export_fault_raises_before_any_gather(params):
    """An armed pages.export fault surfaces as a typed error before any
    page is touched — the serving layer maps it to a 503 and the
    gateway downgrades the handoff."""
    pre = Engine(XLA, params, ecfg=PAGED)
    first, _ = _export_blob(pre, GREEDY)
    FAULTS.arm("pages.export", "fail:once")
    with pytest.raises(InjectedFault):
        pre.export_request_kv(list(PROMPT) + [first])
    # disarmed: the very next export works
    assert pre.export_request_kv(list(PROMPT) + [first]) is not None
    pre._pt.check()


def test_pages_import_fault_leaves_table_untouched(params):
    pre = Engine(XLA, params, ecfg=PAGED)
    _first, blob = _export_blob(pre, GREEDY)
    dec = Engine(XLA, params, ecfg=PAGED)
    free0 = dec.free_pages
    FAULTS.arm("pages.import", "fail:once")
    with pytest.raises(InjectedFault):
        dec.import_request_kv(blob)
    assert dec.free_pages == free0 and dec.radix_pages == 0
    assert dec.import_request_kv(blob) == 3     # disarmed: imports fine
    dec._pt.check()


# ---------------------------------------------------------------------------
# gateway-level handoff (pooled fake replicas, real Gateway)
# ---------------------------------------------------------------------------

from ollama_operator_tpu.operator.gateway import Gateway  # noqa: E402
from tools.chaos_campaign.harness import (DeterministicReplica,  # noqa: E402
                                          expected_text)

GW_GREEDY = {"temperature": 0, "num_predict": 8}
GW_SEEDED = {"temperature": 0.9, "seed": 42, "num_predict": 8}
GW_SAMPLED = {"temperature": 0.9, "num_predict": 8}


@pytest.fixture()
def pool_fleet(monkeypatch):
    monkeypatch.setenv("TPU_GATEWAY_EJECT_FAILURES", "2")
    monkeypatch.setenv("TPU_GATEWAY_EJECT_S", "0.05")
    monkeypatch.setenv("TPU_GATEWAY_SLOW_SCRAPE_MS", "5000")
    monkeypatch.setenv("TPU_DISAGG_HANDOFF_TIMEOUT_S", "5")
    reps = [DeterministicReplica(pool=p)
            for p in ("prefill", "decode", "decode")]
    gw = Gateway(replicas=[(f"rep-{i}", r.url, r.pool)
                           for i, r in enumerate(reps)],
                 scrape_period_s=0, port=0).start()
    yield gw, reps
    gw.stop()
    for r in reps:
        r.stop()


def stream_frames(base_url, body):
    import json
    import urllib.request
    req = urllib.request.Request(
        f"{base_url}/api/generate", data=json.dumps(body).encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        raw = resp.read().decode()
    import json as _j
    return [_j.loads(ln) for ln in raw.splitlines() if ln.strip()]


def assert_clean_stream(frames, body):
    assert not any("error" in f for f in frames), frames
    dones = [f for f in frames if f.get("done")]
    assert len(dones) == 1 and frames[-1] is dones[0]
    text = "".join(f.get("response") or "" for f in frames)
    assert text == expected_text(body)


class TestGatewayHandoff:
    @pytest.mark.parametrize("opts", [GW_GREEDY, GW_SEEDED],
                             ids=["greedy", "seeded"])
    def test_transferred_stream_is_bit_identical(self, pool_fleet, opts):
        gw, (pre, d1, d2) = pool_fleet
        body = {"model": "phi", "prompt": "handoff " * 20,
                "options": dict(opts), "stream": True}
        before = metric("tpu_model_disagg_handoffs_total",
                        '{result="transferred"}')
        frames = stream_frames(gw.base_url, body)
        assert_clean_stream(frames, body)
        assert metric("tpu_model_disagg_handoffs_total",
                      '{result="transferred"}') == before + 1
        # the prefill replica really took the prefill leg, and a decode
        # replica re-served the full request for the splice
        assert pre.seen and pre.seen[0].startswith("handoff")
        assert any(r.seen for r in (d1, d2))
        assert gw.journal_stats()["live"] == 0

    def test_prefill_death_before_handoff_frame_replays(self, pool_fleet):
        """The acceptance drill, timing 1: first token out, stream
        severed before the handoff frame — journal replay on the decode
        pool, zero client error frames."""
        gw, (pre, d1, d2) = pool_fleet
        pre.ctl["die_after"] = 1
        body = {"model": "phi", "prompt": "mid-flight " * 20,
                "options": dict(GW_GREEDY), "stream": True}
        before = metric("tpu_model_disagg_handoffs_total",
                        '{result="replayed"}')
        frames = stream_frames(gw.base_url, body)
        assert_clean_stream(frames, body)
        assert metric("tpu_model_disagg_handoffs_total",
                      '{result="replayed"}') == before + 1
        assert gw.journal_stats()["live"] == 0

    def test_prefill_death_at_export_pull_replays(self, pool_fleet):
        """Timing 2: the handoff frame arrived but the prefill replica
        is a corpse by the time the decode replica pulls its pages —
        the import 502s and the stream replays, still byte-identical."""
        gw, (pre, d1, d2) = pool_fleet
        pre.ctl["export_down"] = True
        body = {"model": "phi", "prompt": "corpse pull " * 20,
                "options": dict(GW_SEEDED), "stream": True}
        before = metric("tpu_model_disagg_handoffs_total",
                        '{result="replayed"}')
        frames = stream_frames(gw.base_url, body)
        assert_clean_stream(frames, body)
        assert metric("tpu_model_disagg_handoffs_total",
                      '{result="replayed"}') == before + 1

    def test_injected_gateway_handoff_fault_replays(self, pool_fleet):
        gw, _reps = pool_fleet
        FAULTS.arm("gateway.handoff", "fail:once")
        body = {"model": "phi", "prompt": "drill " * 20,
                "options": dict(GW_GREEDY), "stream": True}
        before = metric("tpu_model_disagg_handoffs_total",
                        '{result="replayed"}')
        frames = stream_frames(gw.base_url, body)
        assert_clean_stream(frames, body)
        assert metric("tpu_model_disagg_handoffs_total",
                      '{result="replayed"}') == before + 1

    def test_decode_pool_loss_downgrades_to_unified(self, pool_fleet):
        """A non-replayable stream skips the handoff and lives on the
        decode pool; when that pool is gone it downgrades to unified
        serving (the prefill replica picks it up) — pool topology is
        never worth a client-visible failure."""
        gw, (pre, d1, d2) = pool_fleet
        d1.ctl["down"] = True
        d2.ctl["down"] = True
        body = {"model": "phi", "prompt": "fallback " * 20,
                "options": dict(GW_SAMPLED), "stream": True}
        before = metric("tpu_model_disagg_handoffs_total",
                        '{result="unified_fallback"}')
        frames = stream_frames(gw.base_url, body)
        assert_clean_stream(frames, body)
        assert metric("tpu_model_disagg_handoffs_total",
                      '{result="unified_fallback"}') == before + 1
        # unified serving = the full request, no disagg_prefill cap
        assert pre.seen and pre.seen[-1].startswith("fallback")

    def test_kill_switch_serves_unified(self, pool_fleet, monkeypatch):
        monkeypatch.setenv("TPU_DISAGG", "0")
        gw, _reps = pool_fleet
        body = {"model": "phi", "prompt": "plain " * 20,
                "options": dict(GW_GREEDY), "stream": True}
        befores = {r: metric("tpu_model_disagg_handoffs_total",
                             f'{{result="{r}"}}')
                   for r in ("transferred", "replayed", "unified_fallback")}
        frames = stream_frames(gw.base_url, body)
        assert_clean_stream(frames, body)
        for r, b in befores.items():
            assert metric("tpu_model_disagg_handoffs_total",
                          f'{{result="{r}"}}') == b


# ---------------------------------------------------------------------------
# the chaos drill: a pooled campaign with mid-handoff prefill kills
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_disagg_campaign_with_mid_handoff_kills_runs_green(tmp_path):
    """Seed 8 fires kill_prefill_mid_handoff against the pooled fleet;
    the campaign must stay green: every stream terminal exactly once
    (byte-identical when complete — zero error frames for replayable
    traffic), gateway journal drained at quiesce, thread census flat."""
    from ollama_operator_tpu.runtime.chaos import run_campaign
    from tools.chaos_campaign.harness import ChaosFleet

    fleet = ChaosFleet(n_replicas=3, persist_dir=str(tmp_path), disagg=True)
    try:
        report = run_campaign(fleet, seed=8, n_events=10)
    finally:
        fleet.close()
        FAULTS.reset()
    assert report.actions.get("kill_prefill_mid_handoff", 0) >= 1
    out = fleet.outcomes()
    assert out.get("ok", 0) > 0
    assert not out.get("lost") and not out.get("in-flight")
    assert report.summary_lines()[0].endswith("green")
