"""Multi-host init glue: env contract between operator/pod.py and
parallel/distributed.py (the jax.distributed world wiring)."""

import pytest

from ollama_operator_tpu.parallel import distributed as D


def test_process_index_from_pod_name():
    assert D.process_index_from_pod_name("ollama-model-x-0") == 0
    assert D.process_index_from_pod_name("ollama-model-llama2-70b-13") == 13
    with pytest.raises(ValueError):
        D.process_index_from_pod_name("nodash")


def test_single_host_noop():
    assert D.maybe_initialize({}) is False
    assert D.maybe_initialize({"TPU_DIST_HOSTS": "1"}) is False


def test_missing_coordinator_rejected():
    with pytest.raises(ValueError, match="COORDINATOR"):
        D.maybe_initialize({"TPU_DIST_HOSTS": "2",
                            "TPU_DIST_POD_NAME": "m-1"})


def test_operator_env_contract():
    """The env the operator renders must be exactly what the runtime
    parses (names + coordinator shape)."""
    from ollama_operator_tpu.operator import pod as podf
    env = {e["name"]: e.get("value") for e in podf.multihost_env(
        "svc-headless", "ns1", hosts=4, chips_per_host=4)}
    assert env["TPU_DIST_HOSTS"] == "4"
    assert env["TPU_DIST_COORDINATOR"].endswith(".ns1.svc:8476")
    assert "TPU_DIST_POD_NAME" in env
