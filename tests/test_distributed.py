"""Multi-host init glue: env contract between operator/pod.py and
parallel/distributed.py (the jax.distributed world wiring)."""

import os

import numpy as np
import pytest

from ollama_operator_tpu.parallel import distributed as D


def test_process_index_from_pod_name():
    assert D.process_index_from_pod_name("ollama-model-x-0") == 0
    assert D.process_index_from_pod_name("ollama-model-llama2-70b-13") == 13
    with pytest.raises(ValueError):
        D.process_index_from_pod_name("nodash")


def test_single_host_noop():
    assert D.maybe_initialize({}) is False
    assert D.maybe_initialize({"TPU_DIST_HOSTS": "1"}) is False


def test_missing_coordinator_rejected():
    with pytest.raises(ValueError, match="COORDINATOR"):
        D.maybe_initialize({"TPU_DIST_HOSTS": "2",
                            "TPU_DIST_POD_NAME": "m-1"})


def test_operator_env_contract():
    """The env the operator renders must be exactly what the runtime
    parses (names + coordinator shape)."""
    from ollama_operator_tpu.operator import pod as podf
    env = {e["name"]: e.get("value") for e in podf.multihost_env(
        "svc-headless", "ns1", hosts=4, chips_per_host=4)}
    assert env["TPU_DIST_HOSTS"] == "4"
    assert env["TPU_DIST_COORDINATOR"].endswith(".ns1.svc:8476")
    assert "TPU_DIST_POD_NAME" in env


def test_two_process_world_sharded_forward(tmp_path):
    """SURVEY §7 risk 3 / round-1 weak #8: actually form a two-process
    jax.distributed world (CPU backend, 2 local devices each) through
    maybe_initialize + the StatefulSet env contract, run a tp=4 sharded
    forward over the GLOBAL mesh, and match the single-process logits."""
    import socket
    import subprocess
    import sys as _sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = os.path.join(os.path.dirname(__file__), "distributed_worker.py")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_", "TPU_DIST"))}
    procs = [subprocess.Popen(
                [_sys.executable, worker, str(port), str(i), str(tmp_path)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed workers timed out forming the world")
        outs.append((p.returncode, out, err))
    for rc, out, err in outs:
        assert rc == 0, err[-2000:]

    import json as _json
    for i in range(2):
        with open(tmp_path / f"ok{i}.json") as f:
            info = _json.load(f)
        assert info == {"processes": 2, "devices": 4}

    # single-process reference (this process: 8-device CPU mesh, no dist)
    import jax
    import jax.numpy as jnp
    from ollama_operator_tpu.models import config as cfglib, decoder
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.key(0), jnp.float32)
    tokens = np.arange(1, 17, dtype=np.int32).reshape(2, 8) % cfg.vocab_size
    ref = decoder.prefill_chunk(params, cfg, jnp.asarray(tokens))[0]
    got = np.load(tmp_path / "logits.npy")
    np.testing.assert_allclose(got, np.asarray(ref), rtol=2e-4, atol=2e-4)
