"""Embedding (encoder) models: BERT-family forward + WordPiece tokenizer
+ the embedding-only serving route.

The reference serves embedding images (ollama `all-minilm`,
`mxbai-embed-large`, …) via llama.cpp's BERT path in the delegated
container; this tier pins our encoder against transformers BertModel on
identical weights, the WordPiece encoder against BertTokenizer, and the
server contract (embed works, generate 400s) over real sockets.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ollama_operator_tpu.gguf import writer as W
from ollama_operator_tpu.gguf.reader import GGUFFile
from ollama_operator_tpu.gguf.transcode import (encoder_config_from_gguf,
                                                is_encoder_arch,
                                                load_encoder_params)
from ollama_operator_tpu.models import encoder as E
from ollama_operator_tpu.tokenizer import Tokenizer

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


# ---------------------------------------------------------------------------
# synthetic BERT GGUF (llama.cpp conversion layout)
# ---------------------------------------------------------------------------

VOCAB = (["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"]
         + ["the", "sky", "is", "blue", "why", "deep",
            "##s", "##ing", "##ed", "un", "##believ", "##able",
            "hello", "world", ",", ".", "!", "a", "b", "c"]
         + [f"w{i}" for i in range(7)])      # 32 pieces


def _write_bert(path, hf_cfg, sd, pooling=1):
    w = W.GGUFWriter(path)
    w.add_meta("general.architecture", "bert")
    w.add_meta("bert.block_count", hf_cfg.num_hidden_layers)
    w.add_meta("bert.embedding_length", hf_cfg.hidden_size)
    w.add_meta("bert.attention.head_count", hf_cfg.num_attention_heads)
    w.add_meta("bert.feed_forward_length", hf_cfg.intermediate_size)
    w.add_meta("bert.context_length", hf_cfg.max_position_embeddings)
    w.add_meta("bert.attention.layer_norm_epsilon",
               float(hf_cfg.layer_norm_eps))
    w.add_meta("bert.pooling_type", pooling)  # 1=mean, 2=cls
    w.add_meta("tokenizer.ggml.model", "bert")
    w.add_meta("tokenizer.ggml.tokens", VOCAB)
    w.add_meta("tokenizer.ggml.token_type", [1] * len(VOCAB))
    w.add_meta("tokenizer.ggml.cls_token_id", 2)
    w.add_meta("tokenizer.ggml.seperator_token_id", 3)
    w.add_meta("tokenizer.ggml.unknown_token_id", 1)
    w.add_tensor_f32("token_embd.weight",
                     sd["embeddings.word_embeddings.weight"])
    w.add_tensor_f32("position_embd.weight",
                     sd["embeddings.position_embeddings.weight"])
    w.add_tensor_f32("token_types.weight",
                     sd["embeddings.token_type_embeddings.weight"])
    w.add_tensor_f32("token_embd_norm.weight",
                     sd["embeddings.LayerNorm.weight"])
    w.add_tensor_f32("token_embd_norm.bias", sd["embeddings.LayerNorm.bias"])
    for i in range(hf_cfg.num_hidden_layers):
        p, b = f"encoder.layer.{i}.", f"blk.{i}."
        w.add_tensor_f32(b + "attn_q.weight",
                         sd[p + "attention.self.query.weight"])
        w.add_tensor_f32(b + "attn_q.bias",
                         sd[p + "attention.self.query.bias"])
        w.add_tensor_f32(b + "attn_k.weight",
                         sd[p + "attention.self.key.weight"])
        w.add_tensor_f32(b + "attn_k.bias",
                         sd[p + "attention.self.key.bias"])
        w.add_tensor_f32(b + "attn_v.weight",
                         sd[p + "attention.self.value.weight"])
        w.add_tensor_f32(b + "attn_v.bias",
                         sd[p + "attention.self.value.bias"])
        w.add_tensor_f32(b + "attn_output.weight",
                         sd[p + "attention.output.dense.weight"])
        w.add_tensor_f32(b + "attn_output.bias",
                         sd[p + "attention.output.dense.bias"])
        w.add_tensor_f32(b + "attn_output_norm.weight",
                         sd[p + "attention.output.LayerNorm.weight"])
        w.add_tensor_f32(b + "attn_output_norm.bias",
                         sd[p + "attention.output.LayerNorm.bias"])
        w.add_tensor_f32(b + "ffn_up.weight",
                         sd[p + "intermediate.dense.weight"])
        w.add_tensor_f32(b + "ffn_up.bias", sd[p + "intermediate.dense.bias"])
        w.add_tensor_f32(b + "ffn_down.weight", sd[p + "output.dense.weight"])
        w.add_tensor_f32(b + "ffn_down.bias", sd[p + "output.dense.bias"])
        w.add_tensor_f32(b + "layer_output_norm.weight",
                         sd[p + "output.LayerNorm.weight"])
        w.add_tensor_f32(b + "layer_output_norm.bias",
                         sd[p + "output.LayerNorm.bias"])
    w.write()


def _tiny_bert():
    cfg = transformers.BertConfig(
        vocab_size=len(VOCAB), hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=48,
        max_position_embeddings=64, pad_token_id=0,
        hidden_act="gelu", attn_implementation="eager")
    torch.manual_seed(13)
    return cfg, transformers.BertModel(cfg).eval()


def test_bert_forward_matches_transformers(tmp_path):
    """GGUF→transcode→encoder forward must reproduce transformers
    BertModel last_hidden_state mean-pooling, including padded rows of a
    mixed-length batch (bidirectional padding mask)."""
    hf_cfg, model = _tiny_bert()
    sd = {k: v.detach().numpy().astype(np.float32)
          for k, v in model.state_dict().items()}
    path = str(tmp_path / "bert.gguf")
    _write_bert(path, hf_cfg, sd)
    with GGUFFile(path) as f:
        assert is_encoder_arch(f.arch)
        cfg = encoder_config_from_gguf(f)
        params = load_encoder_params(f, cfg)
    assert cfg.n_layers == 2 and cfg.pooling == "mean"

    batch = [[2, 5, 6, 7, 8, 3],            # [CLS] the sky is blue [SEP]
             [2, 17, 18, 3]]                 # [CLS] hello world [SEP]
    got = E.embed_batch(jax.tree_util.tree_map(jnp.asarray, params),
                        cfg, batch)

    T = max(len(b) for b in batch)
    ids = torch.zeros((2, T), dtype=torch.long)
    mask = torch.zeros((2, T), dtype=torch.long)
    for i, b in enumerate(batch):
        ids[i, :len(b)] = torch.tensor(b)
        mask[i, :len(b)] = 1
    with torch.no_grad():
        hs = model(input_ids=ids, attention_mask=mask).last_hidden_state
    m = mask[:, :, None].float()
    ref = (hs * m).sum(1) / m.sum(1)
    np.testing.assert_allclose(got, ref.numpy(), rtol=2e-4, atol=2e-4)


def test_bert_cls_pooling(tmp_path):
    """bge-family GGUFs carry pooling_type=2 (CLS): the embedding must be
    the [CLS] position's final hidden state, not the mean."""
    hf_cfg, model = _tiny_bert()
    sd = {k: v.detach().numpy().astype(np.float32)
          for k, v in model.state_dict().items()}
    path = str(tmp_path / "bge.gguf")
    _write_bert(path, hf_cfg, sd, pooling=2)
    with GGUFFile(path) as f:
        cfg = encoder_config_from_gguf(f)
        params = load_encoder_params(f, cfg)
    assert cfg.pooling == "cls"
    batch = [[2, 5, 6, 7, 8, 3]]
    got = E.embed_batch(jax.tree_util.tree_map(jnp.asarray, params),
                        cfg, batch)
    ids = torch.tensor(batch)
    with torch.no_grad():
        hs = model(input_ids=ids).last_hidden_state
    np.testing.assert_allclose(got, hs[:, 0, :].numpy(),
                               rtol=2e-4, atol=2e-4)


def test_wordpiece_matches_bert_tokenizer(tmp_path):
    """WordPiece encode (lowercase, punctuation split, ##-continuations,
    [UNK] collapse) must match transformers BertTokenizer on the same
    vocab."""
    vf = tmp_path / "vocab.txt"
    vf.write_text("\n".join(VOCAB) + "\n")
    ref_tok = transformers.BertTokenizer(str(vf), do_lower_case=True)
    tok = Tokenizer.from_gguf_metadata({
        "tokenizer.ggml.model": "bert",
        "tokenizer.ggml.tokens": VOCAB,
        "tokenizer.ggml.token_type": [1] * len(VOCAB),
        "tokenizer.ggml.cls_token_id": 2,
        "tokenizer.ggml.seperator_token_id": 3,
        "tokenizer.ggml.unknown_token_id": 1,
    })
    for text in ("the sky is blue", "Why is the sky blue!",
                 "unbelievable skies", "hello, world.",
                 "zzz the qqq", "skying skied skies", ""):
        got = tok.encode(text)
        ref = ref_tok.encode(text)
        assert got == ref, (text, got, ref,
                            ref_tok.convert_ids_to_tokens(ref))


def _bert_registry(tmp_path, name="all-minilm"):
    """(hf_cfg, FakeRegistry, full ref): a started fake registry holding a
    tiny-BERT GGUF — shared by the serving-contract and keep-alive tests."""
    import sys
    import os
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from fake_registry import FakeRegistry

    hf_cfg, model = _tiny_bert()
    sd = {k: v.detach().numpy().astype(np.float32)
          for k, v in model.state_dict().items()}
    path = str(tmp_path / "minilm.gguf")
    _write_bert(path, hf_cfg, sd)
    reg = FakeRegistry()
    url = reg.start()
    reg.add_model("library", name, "latest", open(path, "rb").read())
    ref = f"http://{url.split('://')[1]}/library/{name}:latest"
    return hf_cfg, reg, ref


def test_embedding_model_serves_and_rejects_generate(tmp_path):
    """Server contract over real sockets: pull an embedding image →
    /api/embed, /api/embeddings, /v1/embeddings work; /api/generate
    rejects with 400 (embedding-only), /api/ps lists it."""
    from ollama_operator_tpu.server.app import ModelManager, serve

    hf_cfg, reg, ref = _bert_registry(tmp_path)
    manager = ModelManager(str(tmp_path / "store"))
    httpd = serve(manager, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"

    def post(p, d):
        return json.loads(urllib.request.urlopen(urllib.request.Request(
            base + p, data=json.dumps(d).encode(),
            headers={"Content-Type": "application/json"}),
            timeout=120).read())

    try:
        post("/api/pull", {"model": ref, "stream": False})
        r = post("/api/embed", {"model": ref,
                                "input": ["the sky is blue", "hello world"]})
        assert len(r["embeddings"]) == 2
        assert len(r["embeddings"][0]) == hf_cfg.hidden_size
        # distinct inputs → distinct embeddings
        assert r["embeddings"][0] != r["embeddings"][1]
        r1 = post("/api/embeddings", {"model": ref, "prompt": "the sky"})
        assert len(r1["embedding"]) == hf_cfg.hidden_size
        r2 = post("/v1/embeddings", {"model": ref, "input": "the sky"})
        assert r2["data"][0]["embedding"]
        ps = json.loads(urllib.request.urlopen(base + "/api/ps",
                                               timeout=30).read())
        det = ps["models"][0]["details"]
        assert det["family"] == "bert" and det["paged"] is False
        try:
            post("/api/generate", {"model": ref, "prompt": "hi",
                                   "stream": False})
            assert False, "generate on an embedding model must 400"
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        httpd.shutdown()
        reg.stop()


def test_embedding_model_keep_alive_reaps(tmp_path):
    """The keep-alive reaper must unload an idle embedding model: the
    idle-scheduler facade carries every field the reaper reads
    (n_active, has_pending, finished) — a missing one would kill the
    reaper thread and disable keep_alive server-wide."""
    import time as _time

    from ollama_operator_tpu.server.app import ModelManager

    hf_cfg, reg, ref = _bert_registry(tmp_path, name="mini")
    manager = ModelManager(str(tmp_path / "store"),
                           default_keep_alive=1.0)   # 1s idle unload
    try:
        manager.client.pull(ref)
        lm = manager.require_loaded(ref)
        assert lm.embed(["the sky"]).shape[1] == hf_cfg.hidden_size
        deadline = _time.time() + 15
        while manager.loaded is not None and _time.time() < deadline:
            _time.sleep(0.3)
        assert manager.loaded is None, "idle embedding model never reaped"
        # the reaper thread survived (loading again still works + re-arms)
        lm2 = manager.require_loaded(ref)
        assert lm2.embed(["blue"]).shape[1] == hf_cfg.hidden_size
        assert manager.expires_at is not None   # deadline re-armed
    finally:
        manager.shutdown()
        reg.stop()
