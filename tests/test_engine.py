"""Engine: continuous batching with slot KV cache must reproduce the
sequential greedy decode of the bare decoder."""

import jax
import jax.numpy as jnp
import numpy as np

from ollama_operator_tpu.models import config as cfglib
from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

F32 = jnp.float32


def greedy_reference(params, cfg, prompt, n_steps):
    """Sequential greedy decode with the raw decoder (no engine)."""
    tokens = jnp.asarray(prompt, jnp.int32)[None]
    logits, ks, vs = decoder.prefill_chunk(params, cfg, tokens)
    S = 128
    shape = (cfg.n_layers, 1, cfg.n_kv_heads, S, cfg.head_dim)
    k_cache = jnp.zeros(shape, F32).at[:, :, :, :tokens.shape[1]].set(ks)
    v_cache = jnp.zeros(shape, F32).at[:, :, :, :tokens.shape[1]].set(vs)
    lengths = jnp.array([tokens.shape[1]], jnp.int32)
    out = [int(jnp.argmax(logits[0, -1]))]
    tok = jnp.array([[out[0]]], jnp.int32)
    for _ in range(n_steps - 1):
        logits, k_cache, v_cache = decoder.forward_with_cache(
            params, cfg, tok, k_cache, v_cache, lengths)
        lengths = lengths + 1
        nxt = int(jnp.argmax(logits[0, 0]))
        out.append(nxt)
        tok = jnp.array([[nxt]], jnp.int32)
    return out


GREEDY = SlotOptions(temperature=0.0, repeat_penalty=1.0)


def make_engine(cfg, params, slots=4):
    return Engine(cfg, params,
                  ecfg=EngineConfig(max_slots=slots, max_seq_len=128,
                                    cache_dtype=F32, min_prefill_bucket=16))


def test_engine_matches_reference_greedy():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params)

    prompt = np.array([5, 9, 2, 11, 7], np.int32)
    ref = greedy_reference(params, cfg, prompt, 6)

    first = eng.admit(0, prompt, GREEDY)
    got = [first]
    for _ in range(5):
        toks = eng.decode()
        got.append(int(toks[0]))
    assert got == ref


def test_continuous_batching_isolation():
    """Admitting a second request mid-decode must not change the first
    request's token stream."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)

    p1 = np.array([3, 1, 4, 1, 5], np.int32)
    p2 = np.array([9, 2, 6], np.int32)
    ref1 = greedy_reference(params, cfg, p1, 7)
    ref2 = greedy_reference(params, cfg, p2, 4)

    eng = make_engine(cfg, params)
    got1 = [eng.admit(0, p1, GREEDY)]
    for _ in range(2):
        got1.append(int(eng.decode()[0]))
    # admit second request mid-stream into another slot
    got2 = [eng.admit(2, p2, GREEDY)]
    for _ in range(3):
        toks = eng.decode()
        got1.append(int(toks[0]))
        got2.append(int(toks[2]))
    eng.release(2)
    toks = eng.decode()
    got1.append(int(toks[0]))

    assert got1 == ref1
    assert got2 == ref2


def test_release_and_reuse_slot():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params, slots=2)
    p = np.array([4, 8, 15], np.int32)
    ref = greedy_reference(params, cfg, p, 4)

    eng.admit(0, p, GREEDY)
    eng.decode()
    eng.release(0)
    assert eng.free_slots() == [0, 1]

    got = [eng.admit(0, p, GREEDY)]
    for _ in range(3):
        got.append(int(eng.decode()[0]))
    assert got == ref


def test_prompt_too_long_rejected():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params, slots=2)
    try:
        eng.admit(0, np.zeros(500, np.int32), GREEDY)
        assert False, "expected ValueError"
    except ValueError:
        pass


def test_decode_n_matches_single_steps():
    """decode_n(k) must produce exactly the tokens of k decode() calls."""
    import jax.numpy as jnp
    from ollama_operator_tpu.models import config as cfglib
    from ollama_operator_tpu.models import decoder as dec
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    cfg = cfglib.PRESETS["tiny"]
    params = dec.init_params(cfg, jax.random.PRNGKey(4), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=jnp.float32)
    prompt = np.arange(1, 10, dtype=np.int32)
    opts = SlotOptions(temperature=0.7, seed=123)

    e1 = Engine(cfg, params, ecfg=ecfg)
    e1.admit(0, prompt, opts)
    singles = [int(e1.decode()[0]) for _ in range(6)]

    e2 = Engine(cfg, params, ecfg=ecfg)
    e2.admit(0, prompt, opts)
    chunk = e2.decode_n(6)
    assert chunk.shape == (6, 2)
    assert [int(t[0]) for t in chunk] == singles


def test_decode_across_attn_bucket_boundary():
    """Generations crossing a power-of-two attention bucket must be
    identical to an engine that always attends the full cache."""
    import jax.numpy as jnp
    from ollama_operator_tpu.models import config as cfglib
    from ollama_operator_tpu.models import decoder as dec
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    cfg = cfglib.PRESETS["tiny"]
    params = dec.init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    ecfg = EngineConfig(max_slots=2, max_seq_len=128, min_prefill_bucket=8,
                        cache_dtype=jnp.float32, decode_chunk=4)
    opts = SlotOptions(temperature=0.0)
    prompt = np.arange(1, 7, dtype=np.int32)   # len 6: bucket 8 → 16 → 32

    e1 = Engine(cfg, params, ecfg=ecfg)
    e1.admit(0, prompt, opts)
    bucketed = [t for _ in range(7) for t in e1.decode_n()[:, 0]]

    e2 = Engine(cfg, params, ecfg=ecfg)
    e2._bucketed_attn = False   # always full-cache attention
    e2.admit(0, prompt, opts)
    full = [t for _ in range(7) for t in e2.decode_n()[:, 0]]

    assert [int(t) for t in bucketed] == [int(t) for t in full]
    # crossed at least two bucket boundaries (6 + 28 tokens > 32 > 16 > 8)
    assert e1._attn_bucket(1) >= 32


def test_repeat_last_n_window_evicts():
    """Penalty counts must cover exactly the last repeat_last_n tokens:
    after decoding past the window, total counts stay at W (prompt tokens
    that fell out are no longer penalised — Ollama repeat_last_n)."""
    import jax.numpy as jnp
    from ollama_operator_tpu.models import config as cfglib
    from ollama_operator_tpu.models import decoder as dec
    from ollama_operator_tpu.runtime.engine import (Engine, EngineConfig,
                                                    SlotOptions)
    cfg = cfglib.PRESETS["tiny"]
    params = dec.init_params(cfg, jax.random.PRNGKey(9), dtype=jnp.float32)
    W = 8
    ecfg = EngineConfig(max_slots=2, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=jnp.float32, decode_chunk=4,
                        repeat_last_n=W)
    eng = Engine(cfg, params, ecfg=ecfg)
    r = np.random.default_rng(17)
    prompt = np.asarray(r.integers(1, cfg.vocab_size, 12), np.int32)
    eng.admit(0, prompt, SlotOptions(temperature=0.8, seed=3))
    # after admit: window = last W prompt tokens + 1 sampled = W (ring
    # wrapped: eviction keeps the total at W)
    counts0 = np.asarray(eng.counts)[0]
    assert counts0.sum() == W
    # the first sampled token must stay in the window for W steps, not be
    # evicted by the first decode (ring position off-by-one regression)
    tok0 = int(np.asarray(eng.last_tokens)[0])
    eng.decode_n(1)
    assert np.asarray(eng.counts)[0][tok0] >= 1
    for _ in range(4):
        eng.decode_n()
    counts = np.asarray(eng.counts)[0]
    assert counts.sum() == W          # stable at window size
    assert (counts >= 0).all()        # eviction never goes negative
    eng.release(0)
    assert np.asarray(eng.counts)[0].sum() == 0


def test_per_request_repeat_last_n():
    """Each request's own repeat_last_n must take effect (round-2 VERDICT
    weak #6: the API option was accepted and silently ignored) without a
    recompile — the static ring holds the engine max, the per-slot window
    is a traced modulus."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(9), dtype=F32)
    W = 8
    ecfg = EngineConfig(max_slots=3, max_seq_len=64, min_prefill_bucket=8,
                        cache_dtype=F32, decode_chunk=4, repeat_last_n=W)
    eng = Engine(cfg, params, ecfg=ecfg)
    prompt = np.asarray([5, 5, 5, 5, 5, 5], np.int32)

    # same prompt, three windows: full (counts = W-window over prompt +
    # sample), narrowed to 2, and 0 (penalties disabled entirely)
    eng.admit(0, prompt, SlotOptions(temperature=0.0, repeat_last_n=-1))
    eng.admit(1, prompt, SlotOptions(temperature=0.0, repeat_last_n=2))
    eng.admit(2, prompt, SlotOptions(temperature=0.0, repeat_last_n=0))
    counts = np.asarray(eng.counts)
    t0 = int(np.asarray(eng.last_tokens)[0])
    # full window: 6 prompt tokens + 1 sample, nothing evicted yet
    assert counts[0].sum() == len(prompt) + 1
    assert counts[0][5] == len(prompt) + (1 if t0 == 5 else 0)
    # slot 1: window of 2 = one prompt token evicted by the sample, or
    # {5, tok}; either way total counts == 2 and at most two 5s
    assert counts[1].sum() == 2
    assert counts[1][5] <= 2
    assert counts[2].sum() == 0       # window 0: penalties see nothing
    # one admission program serves every window — no per-request compile
    assert len(eng._admit_execs) == 1

    eng.decode_n()
    counts = np.asarray(eng.counts)
    assert counts[1].sum() == 2       # stays at the request's window
    assert counts[2].sum() == 0
    eng.release(1)
    # a later admit on the same slot returns to the default window
    eng.admit(1, prompt, SlotOptions(temperature=0.0))
    assert np.asarray(eng.counts)[1].sum() >= min(len(prompt), W)
    assert len(eng._admit_execs) == 1


def test_resolve_paged_default(monkeypatch):
    """Serving default (data-driven per BASELINE r3+r4): paged for GQA on
    TPU, paged for MHA since the v3 live-page kernel (dense again when
    v3 is explicitly reverted), dense for MoE/CPU/incompatible meshes;
    explicit flags resolve in the server before the engine is built."""
    from unittest import mock

    import dataclasses

    from ollama_operator_tpu.parallel import MeshPlan, make_mesh
    from ollama_operator_tpu.runtime.engine import resolve_paged_default
    gqa = cfglib.PRESETS["tiny"]                       # 4 heads, 2 kv
    # this suite runs on the CPU backend: the v5e measurement must not
    # page a 1-core dev/kind pod
    assert resolve_paged_default(gqa, None) is False
    with mock.patch("jax.default_backend", return_value="tpu"):
        assert resolve_paged_default(gqa, None) is True
        mha = dataclasses.replace(gqa, n_kv_heads=gqa.n_heads)
        assert resolve_paged_default(mha, None) is True   # v3 default
        monkeypatch.setenv("TPU_PAGED_V3", "0")           # v2 revert
        assert resolve_paged_default(mha, None) is False
        assert resolve_paged_default(gqa, None) is True
        monkeypatch.delenv("TPU_PAGED_V3")
        moe = dataclasses.replace(gqa, n_experts=4)
        assert resolve_paged_default(moe, None) is False
        assert resolve_paged_default(
            gqa, make_mesh(MeshPlan(sp=2))) is False
        assert resolve_paged_default(
            gqa, make_mesh(MeshPlan(tp=2))) is True
        assert resolve_paged_default(
            gqa, make_mesh(MeshPlan(dp=2))) is True


def test_resolve_serving_defaults():
    """Tri-state knob resolution incl. the pool-ceiling guarantee: the
    auto-paged default must NOT grow HBM past the old dense-8 footprint."""
    from unittest import mock

    from ollama_operator_tpu.runtime.engine import resolve_serving_defaults
    gqa = cfglib.PRESETS["tiny"]                       # max_seq_len 128
    base = EngineConfig(max_slots=0, max_seq_len=4096, paged=None,
                        page_size=16)
    with mock.patch("jax.default_backend", return_value="tpu"):
        r = resolve_serving_defaults(base, gqa, None)
        # GQA paged on TPU defaults to 64 slots since r5 (ladder: 3902
        # tok/s at 64 vs 2848 at 32) with a dense-24-equivalent pool
        # ceiling (dense-8/16 caps measured pool-dry under 64 mixed
        # slots at design load, r5 window 3)
        assert r.paged is True and r.max_slots == 64
        # ceiling uses the SERVING seq (engine clamps to the model's 128)
        # and preserves dense-24 BYTES: the pool pads head_dim to the
        # 128-lane tile (tiny: hd 16 → 8× padding), so the page count
        # shrinks by hd/hd_pool (round-3 advisor finding)
        assert r.n_pages == 24 * 128 * 16 // 128 // 16
        # a hd=128 model keeps the full token count
        r128 = resolve_serving_defaults(
            base, cfglib.PRESETS["llama3.2:3b"], None)
        assert r128.n_pages == 24 * 4096 // 16
        # explicit slots: user asked for scale — dense-equivalent pool
        r2 = resolve_serving_defaults(
            EngineConfig(max_slots=16, max_seq_len=4096, paged=None,
                         page_size=16), gqa, None)
        assert r2.paged is True and r2.max_slots == 16
        assert r2.n_pages is None
        # explicit dense stays dense with 8 slots
        r3 = resolve_serving_defaults(
            EngineConfig(max_slots=0, max_seq_len=4096, paged=False),
            gqa, None)
        assert r3.paged is False and r3.max_slots == 8
    # CPU backend: auto resolves dense
    r4 = resolve_serving_defaults(base, gqa, None)
    assert r4.paged is False and r4.max_slots == 8


def test_resolve_page_size_and_mha_slots():
    """page_size=0 resolves to 128 when paged on TPU (r5 ladder: +10.5%
    over 64 at B=32, 256 regresses) and 64 elsewhere; MHA models keep 32
    slots (their paged step is ~3x GQA's — 64 is unmeasured there)."""
    import dataclasses as dc
    from unittest import mock

    from ollama_operator_tpu.runtime.engine import resolve_serving_defaults
    gqa = cfglib.PRESETS["tiny"]
    mha = dc.replace(gqa, n_kv_heads=gqa.n_heads)
    auto = EngineConfig(max_slots=0, max_seq_len=4096, paged=None,
                        page_size=0)
    with mock.patch("jax.default_backend", return_value="tpu"):
        r = resolve_serving_defaults(auto, gqa, None)
        assert r.page_size == 128 and r.max_slots == 64
        m = resolve_serving_defaults(auto, mha, None)
        assert m.paged is True and m.max_slots == 32
        assert m.page_size == 64    # ps=128 measured -2% on MHA (phi)
        # explicit page size passes through, incl. via the early return
        pinned = EngineConfig(max_slots=8, max_seq_len=4096, paged=True,
                              page_size=64)
        assert resolve_serving_defaults(pinned, gqa, None).page_size == 64
        early = EngineConfig(max_slots=8, max_seq_len=4096, paged=True,
                             page_size=0)
        assert resolve_serving_defaults(early, gqa, None).page_size == 128
    # CPU: dense anyway, page size resolves to the classic 64
    c = resolve_serving_defaults(auto, gqa, None)
    assert c.page_size == 64 and c.paged is False


def test_resolve_decode_chunk_default():
    """decode_chunk=0 resolves per backend (32 TPU / 8 CPU — BASELINE.md's
    measured serving config vs round-1's chunk-8); an explicit chunk always
    passes through, including when paged/slots are explicit too (the early
    return must still resolve the chunk)."""
    from unittest import mock

    from ollama_operator_tpu.runtime.engine import resolve_serving_defaults
    gqa = cfglib.PRESETS["tiny"]
    auto = EngineConfig(max_slots=0, max_seq_len=4096, paged=None,
                        decode_chunk=0)
    with mock.patch("jax.default_backend", return_value="tpu"):
        assert resolve_serving_defaults(auto, gqa, None).decode_chunk == 32
        # explicit paged+slots takes the early return — chunk still resolves
        explicit = EngineConfig(max_slots=8, max_seq_len=4096, paged=False,
                                decode_chunk=0)
        assert resolve_serving_defaults(explicit, gqa,
                                        None).decode_chunk == 32
        pinned = EngineConfig(max_slots=8, max_seq_len=4096, paged=False,
                              decode_chunk=16)
        assert resolve_serving_defaults(pinned, gqa, None).decode_chunk == 16
    # CPU backend: streaming-latency default
    assert resolve_serving_defaults(auto, gqa, None).decode_chunk == 8


def test_resolve_engine_dtype():
    """Zero-config weight dtype per model size (VERDICT r4 #3): a bare
    Model CR must serve the measured config — int8 ≤4B, int4 7B+, bf16
    MoE on TPU; f32 on CPU. Explicit spec/env wins upstream (ModelManager
    only consults this when engine_dtype is None)."""
    import dataclasses

    from ollama_operator_tpu.runtime.engine import (resolve_engine_dtype,
                                                    resolve_kv_dtype_default)
    tiny = cfglib.PRESETS["tiny"]
    assert resolve_engine_dtype(tiny, "cpu") == "float32"
    assert resolve_engine_dtype(tiny, "tpu") == "int8"
    small = cfglib.PRESETS["llama3.2:3b"]
    assert resolve_engine_dtype(small, "tpu") == "int8"
    big = cfglib.PRESETS["mistral"]          # 7B class
    assert big.n_params >= 4e9
    assert resolve_engine_dtype(big, "tpu") == "int4"
    moe = dataclasses.replace(tiny, n_experts=4)
    assert resolve_engine_dtype(moe, "tpu") == "bfloat16"
    assert resolve_kv_dtype_default("tpu") == "int8"
    assert resolve_kv_dtype_default("cpu") == "float32"


def test_fused_qkv_matches_separate(monkeypatch):
    """Engine-side fused single-matmul QKV (models/decoder.fuse_qkv_params)
    must decode bitwise-identically to the separate projections — every
    output column of the (q)mm is independent, so fusion is pure op-count
    reduction. Covers biases (attn_bias) and GQA."""
    import dataclasses

    import numpy as np

    from ollama_operator_tpu.runtime.engine import Engine, SlotOptions
    cfg = dataclasses.replace(cfglib.PRESETS["tiny"], attn_bias=True,
                              kernels="xla")
    params = decoder.init_params(cfg, jax.random.PRNGKey(3), jnp.float32)
    prompt = np.array([5, 6, 7, 8, 9, 2], np.int32)
    g = SlotOptions(temperature=0.0, repeat_penalty=1.0)

    def run():
        eng = Engine(cfg, params,
                     ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                       cache_dtype=jnp.float32,
                                       min_prefill_bucket=16))
        toks = [eng.admit(0, prompt, g)]
        toks += [int(eng.decode()[0]) for _ in range(6)]
        return toks, "wqkv" in eng.params["layers"]

    monkeypatch.setenv("TPU_FUSED_QKV", "0")
    ref, fused0 = run()
    assert not fused0
    monkeypatch.setenv("TPU_FUSED_QKV", "1")
    got, fused1 = run()
    assert fused1, "fusion did not engage on a single-device engine"
    assert got == ref, (got, ref)


def test_mirostat_mu_threads_through_decode_chunks():
    """A mirostat slot's surprise budget must (a) re-seed to 2*tau at
    admission, (b) keep evolving across decode_n chunk boundaries, and
    (c) stay frozen for non-mirostat slots sharing the batch."""
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(0), dtype=F32)
    eng = make_engine(cfg, params)

    tau = 5.0
    miro = SlotOptions(temperature=0.7, repeat_penalty=1.0, mirostat=2,
                       mirostat_tau=tau, mirostat_eta=0.3, seed=3)
    eng.admit(0, np.array([5, 9, 2], np.int32), miro)
    eng.admit(1, np.array([4, 1, 8], np.int32), GREEDY)
    mu_after_admit = np.asarray(eng._fetch(eng.mu))
    # the admission sample already applied one update off the 2*tau seed
    assert mu_after_admit[0] != 0.0
    assert abs(mu_after_admit[0] - 2 * tau) < tau  # one eta-sized step
    # non-mirostat slots carry the inert 2*tau seed (never read)
    assert mu_after_admit[1] == 2 * 5.0

    eng.decode_n(4)
    mu_mid = np.asarray(eng._fetch(eng.mu))
    assert mu_mid[0] != mu_after_admit[0]          # evolved inside chunk
    assert mu_mid[1] == 2 * 5.0                    # frozen: mirostat off

    eng.decode_n(4)
    assert np.asarray(eng._fetch(eng.mu))[0] != mu_mid[0]

    eng.release(0)
    assert np.asarray(eng._fetch(eng.mu))[0] == 0.0


def test_mirostat_generation_stays_in_vocab():
    cfg = cfglib.PRESETS["tiny"]
    params = decoder.init_params(cfg, jax.random.PRNGKey(1), dtype=F32)
    eng = make_engine(cfg, params)
    opts = SlotOptions(temperature=0.9, repeat_penalty=1.1, mirostat=1,
                       seed=11)
    first = eng.admit(2, np.array([3, 7, 1, 2], np.int32), opts)
    toks = [first]
    for _ in range(3):
        toks.extend(int(t) for t in eng.decode_n(2)[:, 2])
    assert all(0 <= t < cfg.vocab_size for t in toks)
