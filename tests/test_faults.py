"""Fault injection (runtime/faults.py) and the recovery paths it drives.

The `chaos`-marked tests are the CI chaos-smoke set: each injects a real
fault at a named point and asserts the corresponding recovery path —
supervised engine restart, per-request admission error, kube client
retry — recovers within ONE restart/retry. They are also tier-1 (not
slow): every recovery path runs on every push.
"""

import time

import numpy as np
import pytest

from ollama_operator_tpu.runtime.faults import (FAULTS, FaultInjector,
                                                InjectedFault, _parse_spec)
from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

from test_scheduler import GREEDY, make_stack


# -- spec grammar ------------------------------------------------------

def test_spec_parsing():
    assert _parse_spec("fail") == ("fail", "always", 0.0, 0.0)
    assert _parse_spec("fail:once") == ("fail", "n", 1.0, 0.0)
    assert _parse_spec("fail:n=2") == ("fail", "n", 2.0, 0.0)
    assert _parse_spec("fail:every=3") == ("fail", "every", 3.0, 0.0)
    assert _parse_spec("fail:after=4") == ("fail", "after", 4.0, 0.0)
    assert _parse_spec("delay:50ms") == ("delay", "always", 0.0, 0.05)
    assert _parse_spec("delay:0.2s") == ("delay", "always", 0.0, 0.2)
    # delays take the same trigger modes as fail (a drill can wedge
    # exactly one dispatch)
    assert _parse_spec("delay:50ms:once") == ("delay", "n", 1.0, 0.05)
    assert _parse_spec("delay:1s:n=2") == ("delay", "n", 2.0, 1.0)
    assert _parse_spec("delay:5ms:every=3") == ("delay", "every", 3.0,
                                                0.005)
    assert _parse_spec("delay:5ms:after=4") == ("delay", "after", 4.0,
                                                0.005)
    for bad in ("fail:sometimes", "delay:50", "jitter:1ms", "fail:n=0",
                "delay:1ms:sometimes", "delay:1ms:n=0"):
        with pytest.raises(ValueError):
            _parse_spec(bad)


def test_injector_modes():
    f = FaultInjector()
    f.arm("p", "fail:once")
    with pytest.raises(InjectedFault):
        f.check("p")
    f.check("p")                     # disarmed after the first hit
    assert f.hits("p") == 1          # disarmed checks don't count

    f.arm("q", "fail:every=2")
    f.check("q")
    with pytest.raises(InjectedFault):
        f.check("q")
    f.check("q")
    with pytest.raises(InjectedFault):
        f.check("q")

    f.arm("r", "fail:after=1")
    f.check("r")
    with pytest.raises(InjectedFault):
        f.check("r")
    with pytest.raises(InjectedFault):
        f.check("r")

    # delay modes share the trigger grammar: :once sleeps on the first
    # hit only (the sleep itself is what fires — assert via wall clock)
    f.arm("d", "delay:30ms:once")
    t0 = time.monotonic()
    f.check("d")
    assert time.monotonic() - t0 >= 0.025
    t0 = time.monotonic()
    f.check("d")                     # disarmed: no sleep
    assert time.monotonic() - t0 < 0.025

    f.reset()
    f.check("q")                     # everything disarmed


def test_env_arming(monkeypatch):
    f = FaultInjector()
    monkeypatch.setenv("TPU_FAULTS", "a=fail:once, b=delay:1ms")
    f.arm_from_env()
    with pytest.raises(InjectedFault):
        f.check("a")
    f.check("b")                     # delays, doesn't raise
    assert f.hits("b") == 1


def test_unarmed_check_is_noop():
    f = FaultInjector()
    f.check("anything")
    assert f.hits("anything") == 0


# -- chaos: supervised engine restart ----------------------------------

@pytest.mark.chaos
def test_engine_step_fault_supervised_restart(monkeypatch):
    """ISSUE 2 acceptance: engine.step fail:once errors only the
    in-flight request, the supervisor rebuilds in-process, a subsequent
    request completes on the SAME scheduler object, and
    tpu_model_engine_restarts_total increments."""
    # replay off: this drill pins the pre-replay error path (the
    # replay-on drill lives in test_lifecycle.py)
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    cfg, params, eng, sched = make_stack(slots=2, restart_backoff=0.001)
    restarts_before = METRICS.get("tpu_model_engine_restarts_total")
    try:
        FAULTS.arm("engine.step", "fail:once")
        r1 = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=4)
        with pytest.raises(RuntimeError, match="injected fault"):
            list(r1.tokens())
        # supervisor rebuilt the engine state in-process: same scheduler
        # object, loop thread alive, not broken, restart counted
        deadline = time.monotonic() + 5
        while sched.n_restarts == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sched.n_restarts == 1
        assert sched._thread.is_alive()
        assert not sched.broken
        assert METRICS.get("tpu_model_engine_restarts_total") \
            == restarts_before + 1
        r2 = sched.submit(np.array([3, 4], np.int32), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
        assert sched.n_restarts == 1     # recovery took exactly one restart
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_engine_step_fault_spares_waiting_requests(monkeypatch):
    """Queued requests survive the restart: only the in-flight request
    errors; the waiting one is admitted after the rebuild and completes."""
    monkeypatch.setenv("TPU_RESTART_REPLAY_MAX", "0")
    cfg, params, eng, sched = make_stack(slots=1, restart_backoff=0.001)
    try:
        r1 = sched.submit(np.array([1, 2], np.int32), GREEDY,
                          max_tokens=64)
        it = r1.tokens()
        next(it)                      # r1 occupies the only slot
        r2 = sched.submit(np.array([3, 4], np.int32), GREEDY, max_tokens=3)
        FAULTS.arm("engine.step", "fail:once")
        with pytest.raises(RuntimeError, match="injected fault"):
            list(it)
        assert len(list(r2.tokens())) == 3   # never errored, just delayed
        assert not sched.broken
    finally:
        sched.shutdown()


@pytest.mark.chaos
def test_engine_admit_fault_errors_only_that_request():
    """An admission fault is a per-request error (the caller sees it),
    NOT a loop failure: no restart, and the next request admits fine."""
    cfg, params, eng, sched = make_stack(slots=2)
    try:
        FAULTS.arm("engine.admit", "fail:once")
        r1 = sched.submit(np.array([1, 2], np.int32), GREEDY, max_tokens=3)
        with pytest.raises(RuntimeError, match="injected fault"):
            list(r1.tokens())
        assert sched.n_restarts == 0
        assert not sched.broken
        r2 = sched.submit(np.array([3, 4], np.int32), GREEDY, max_tokens=3)
        assert len(list(r2.tokens())) == 3
    finally:
        sched.shutdown()


# -- chaos: kube client retries ----------------------------------------

@pytest.mark.chaos
def test_kube_request_fault_retried_on_get():
    """kube.request fail:once: the read-only GET retries transparently
    and the operator never sees the blip."""
    from ollama_operator_tpu.operator.client import KubeClient
    from fake_kube import FakeKube, serve_http
    fake = FakeKube()
    fake.create({"apiVersion": "v1", "kind": "Pod",
                 "metadata": {"name": "chaos", "namespace": "default"}})
    srv = serve_http(fake)
    try:
        host, port = srv.server_address
        c = KubeClient(f"http://{host}:{port}", timeout=5)
        FAULTS.arm("kube.request", "fail:once")
        obj = c.get("v1", "Pod", "default", "chaos")
        assert obj is not None and obj["metadata"]["name"] == "chaos"
        assert FAULTS.hits("kube.request") == 1     # fired once, then retried
    finally:
        srv.shutdown()


def test_fault_catalog_points_are_complete_and_documented():
    """Satellite 2: FAULTS.points() is the chaos campaign's draw set —
    sorted, stable, and every point carries its check site and a doc
    string (the invariant-lint fault-catalog pass enforces the same
    contract statically)."""
    from ollama_operator_tpu.runtime.faults import CATALOG, FAULTS
    pts = FAULTS.points()
    assert [p.name for p in pts] == sorted(CATALOG)
    assert len(pts) >= 12
    for p in pts:
        assert p.site, p.name
        assert p.doc, p.name


def test_chaos_metric_preseeds_mirror_fault_catalog():
    """metrics.py pre-seeds tpu_model_chaos_events_total for every
    catalogued point (rate() alerts must read 0, not absent, before the
    first campaign); the literal list there must track the CATALOG."""
    from ollama_operator_tpu.runtime.faults import FAULTS
    rendered = METRICS.render()
    for p in FAULTS.points():
        series = f'tpu_model_chaos_events_total{{point="{p.name}"}}'
        assert series in rendered, \
            f"{series} not pre-seeded in server/metrics.py"


def test_tier_metric_preseeds_cover_the_matrix():
    """metrics.py pre-seeds the tiered-KV hit/miss matrix (tier 0/1/2),
    the spill counter, and the restitch histogram so dashboards read 0,
    not absent, on engines that never spill."""
    rendered = METRICS.render()
    for fam in ("tpu_model_tier_hit_tokens_total",
                "tpu_model_tier_miss_tokens_total"):
        for tier in ("0", "1", "2"):
            series = f'{fam}{{tier="{tier}"}}'
            assert series in rendered, f"{series} not pre-seeded"
    assert "\ntpu_model_spilled_pages_total " in "\n" + rendered
    assert "tpu_model_restitch_seconds_bucket" in rendered
    assert "tpu_model_restitch_seconds_count 0" in rendered


def test_retry_transient_backoff_and_classification():
    from ollama_operator_tpu.operator.client import (ApiError, Conflict,
                                                     NotFound,
                                                     retry_transient)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ApiError(503, "apiserver hiccup")
        return "ok"

    assert retry_transient(flaky, backoff=0.001) == "ok"
    assert calls["n"] == 3

    # 4xx are real answers — never retried
    for exc in (NotFound(404, "gone"), Conflict(409, "rv"),
                ApiError(400, "bad")):
        calls["n"] = 0

        def fail_4xx(exc=exc):
            calls["n"] += 1
            raise exc

        with pytest.raises(ApiError):
            retry_transient(fail_4xx, backoff=0.001)
        assert calls["n"] == 1

    # exhausted attempts re-raise the transient error
    def always_503():
        calls["n"] += 1
        raise ApiError(500, "down")

    calls["n"] = 0
    with pytest.raises(ApiError):
        retry_transient(always_503, attempts=3, backoff=0.001)
    assert calls["n"] == 3
