"""Feature-combination grid (round-1 VERDICT weak #4: int8 KV, prefix
cache, sp, paged, and multimodal used to exclude each other in pairs).

Every supported (cache dtype × cache mode × mesh) combination must produce
the SAME greedy tokens as the plainest config that shares its quantization
(quantization legitimately changes tokens; nothing else may), and its
prefix-cache support flag must match the documented matrix.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ollama_operator_tpu.models import decoder
from ollama_operator_tpu.models.config import PRESETS
from ollama_operator_tpu.parallel.mesh import MeshPlan, make_mesh
from ollama_operator_tpu.runtime.engine import Engine, EngineConfig, SlotOptions

CFG = dataclasses.replace(PRESETS["tiny"], kernels="xla")
GREEDY = SlotOptions(temperature=0.0)
PROMPT = np.array([3, 1, 4, 1, 5, 9, 2, 6, 10, 11], np.int32)


@pytest.fixture(scope="module")
def params():
    return decoder.init_params(CFG, jax.random.key(0), jnp.float32)


def _run(params, cache_dtype, paged=False, mesh_plan=None):
    mesh = make_mesh(mesh_plan) if mesh_plan else None
    eng = Engine(CFG, params, mesh=mesh,
                 ecfg=EngineConfig(max_slots=2, max_seq_len=64,
                                   cache_dtype=cache_dtype, paged=paged,
                                   page_size=8, min_prefill_bucket=16))
    seq = [eng.admit(0, PROMPT, GREEDY)]
    for _ in range(5):
        seq.append(int(eng.decode()[0]))
    return seq, eng


MATRIX = [
    # (name, cache_dtype, paged, mesh_plan, supports_extend)
    ("dense-f32", jnp.float32, False, None, True),
    ("dense-int8", jnp.int8, False, None, True),
    ("paged-f32", jnp.float32, True, None, True),
    ("paged-int8", jnp.int8, True, None, True),
    ("dense-f32-tp2", jnp.float32, False, MeshPlan(tp=2), True),
    ("dense-int8-tp2", jnp.int8, False, MeshPlan(tp=2), True),
    ("paged-int8-tp2", jnp.int8, True, MeshPlan(tp=2), True),
    # paged×dp (round-2 VERDICT next-4): per-shard page sub-pools.
    # Extends work here too since round 3 (decoder.paged_extend_dp:
    # replicated tail, owner-real/others-trash table rows, owner-select
    # psum) — every cache mode now prefix-caches.
    ("paged-f32-dp2", jnp.float32, True, MeshPlan(dp=2), True),
    ("paged-int8-dp2", jnp.int8, True, MeshPlan(dp=2), True),
    ("paged-int8-dp2tp2", jnp.int8, True, MeshPlan(dp=2, tp=2), True),
    # sp caches extend too since round 3 (_make_extend_sp: the tail's
    # compute replicates across sp, writes scatter to the owning shard)
    ("dense-f32-sp2", jnp.float32, False, MeshPlan(sp=2, tp=2), True),
    ("dense-int8-sp2", jnp.int8, False, MeshPlan(sp=2, tp=2), True),
]


@pytest.mark.parametrize("name,dtype,paged,plan,extendable", MATRIX,
                         ids=[m[0] for m in MATRIX])
def test_matrix_combination(params, name, dtype, paged, plan, extendable):
    ref, _ = _run(params, dtype)                     # same-dtype baseline
    got, eng = _run(params, dtype, paged=paged, mesh_plan=plan)
    assert got == ref, (name, got, ref)
    assert eng.supports_extend == extendable, name
