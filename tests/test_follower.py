"""Multi-host control plane unit tier (runtime/follower.py): framing,
FIFO broadcast, broadcast-before-execute ordering, address resolution.
The full 2-process serving e2e lives in
tests/test_compose_e2e.py::test_multihost_model_cr_serves."""

import socket
import threading

import numpy as np

from ollama_operator_tpu.runtime import follower as F


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_framing_roundtrip():
    a, b = socket.socketpair()
    msgs = [("load", "m:latest"),
            ("call", "admit", (np.arange(5, dtype=np.int32),), {}),
            ("lm_call", "embed", (["x" * 5000],)),
            ("unload",)]
    for m in msgs:
        F._send(a, m)
    for m in msgs:
        got = F._recv(b)
        assert got[0] == m[0]
        if m[0] == "call":
            np.testing.assert_array_equal(got[2][0], m[2][0])
    a.close()
    try:
        F._recv(b)
        raise AssertionError("expected ConnectionError on closed stream")
    except ConnectionError:
        pass
    b.close()


def test_control_plane_fifo_and_ready_gate():
    port = _free_port()
    cp = F.ControlPlane(2, port, bind="127.0.0.1")
    sent = []

    def producer():
        for i in range(50):
            cp.broadcast(("call", "decode_n", (i,), {}))
            sent.append(i)

    t = threading.Thread(target=producer)
    t.start()
    # broadcast must BLOCK until both followers join (a call dispatched
    # into a partial world would desync the SPMD programs)
    assert not sent, "broadcast ran before the follower set was complete"
    c1 = socket.create_connection(("127.0.0.1", port))
    assert not sent
    c2 = socket.create_connection(("127.0.0.1", port))
    t.join(timeout=10)
    assert len(sent) == 50
    for conn in (c1, c2):
        got = [F._recv(conn)[2][0] for _ in range(50)]
        assert got == list(range(50))      # FIFO, no loss, per follower
        conn.close()
    cp.close()


def test_mirrored_engine_broadcasts_before_execute():
    events = []

    class FakeCP:
        dispatch_lock = threading.RLock()

        def broadcast(self, msg):
            events.append(("bcast", msg[1]))

    class FakeEngine:
        n_slots = 4

        def decode_n(self, n=None):
            events.append(("exec", "decode_n"))
            return "toks"

        def admissible(self, n):
            return True

    me = F.MirroredEngine(FakeEngine(), FakeCP())
    assert me.decode_n(8) == "toks"
    assert events == [("bcast", "decode_n"), ("exec", "decode_n")]
    # non-mirrored attributes delegate without broadcasting
    assert me.n_slots == 4 and me.admissible(3) is True
    assert len(events) == 2


def test_control_address_resolution():
    assert F.control_address({"TPU_DIST_CONTROL": "sts-0.svc:8477"}) == \
        ("sts-0.svc", 8477)
    assert F.control_address(
        {"TPU_DIST_COORDINATOR": "sts-0.svc:8476"}) == ("sts-0.svc", 8477)
    assert F.control_address({}) is None


def test_dead_follower_marks_degraded_and_raises():
    """A send to a closed follower socket raises typed FollowerLost and
    marks the world degraded; later broadcasts fail FAST (no blocking on
    a half-dead world) until the pod is restarted."""
    import pytest
    from ollama_operator_tpu.runtime.errors import FollowerLost
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

    lost_before = METRICS.get("tpu_model_followers_lost_total")
    port = _free_port()
    cp = F.ControlPlane(1, port, bind="127.0.0.1", heartbeat_s=0)
    c1 = socket.create_connection(("127.0.0.1", port))
    cp.broadcast(("call", "decode_n", (1,), {}))
    assert F._recv(c1)[1] == "decode_n"
    c1.close()
    try:
        # closed peer: first or second send hits the broken pipe (the
        # first may land in the kernel buffer before the RST arrives)
        with pytest.raises(FollowerLost):
            for _ in range(50):
                cp.broadcast(("call", "decode_n", (2,), {}))
        assert cp.degraded
        assert cp.degraded_reason
        assert METRICS.get("tpu_model_followers_lost_total") \
            == lost_before + 1
        # degraded world: fail fast, don't half-dispatch
        with pytest.raises(FollowerLost):
            cp.broadcast(("ping",))
        # counted once, not per failed broadcast
        assert METRICS.get("tpu_model_followers_lost_total") \
            == lost_before + 1
    finally:
        cp.close()


def test_follower_send_fault_marks_degraded():
    """The follower.send fault point drives the same degraded path as a
    real socket error — InjectedFault is caught like OSError."""
    import pytest
    from ollama_operator_tpu.runtime.errors import FollowerLost
    from ollama_operator_tpu.runtime.faults import FAULTS

    port = _free_port()
    cp = F.ControlPlane(1, port, bind="127.0.0.1", heartbeat_s=0)
    c1 = socket.create_connection(("127.0.0.1", port))
    try:
        FAULTS.arm("follower.send", "fail:once")
        with pytest.raises(FollowerLost):
            cp.broadcast(("call", "decode_n", (1,), {}))
        assert cp.degraded
    finally:
        c1.close()
        cp.close()


def test_heartbeat_pings_and_follower_ignores_them():
    """The leader's heartbeat thread broadcasts pings; a follower's op
    loop must treat them as liveness-only no-ops between real ops."""
    port = _free_port()
    cp = F.ControlPlane(1, port, bind="127.0.0.1", heartbeat_s=0.02)
    c1 = socket.create_connection(("127.0.0.1", port))
    try:
        got = [F._recv(c1) for _ in range(3)]
        assert ("ping",) in [tuple(m[:1]) for m in got] or \
            all(m[0] == "ping" for m in got)
        # interleave a real broadcast between pings: FIFO preserved
        cp.broadcast(("call", "decode_n", (7,), {}))
        while True:
            m = F._recv(c1)
            if m[0] != "ping":
                break
        assert m[0] == "call" and m[2][0] == 7
    finally:
        c1.close()
        cp.close()


def test_silent_leader_fails_static_with_clean_exit(monkeypatch):
    """Partition drill: a follower whose leader goes silent past
    TPU_CP_LEADER_TIMEOUT_S must fail static — count the loss, leave a
    breadcrumb, and EXIT cleanly (the pod restarts and rejoins the next
    world) instead of hanging on the broadcast socket forever."""
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

    monkeypatch.setenv("TPU_CP_LEADER_TIMEOUT_S", "0.3")
    lost_before = METRICS.get("tpu_model_leader_lost_total")
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    accepted = []

    def accept():
        conn, _ = srv.accept()
        accepted.append(conn)    # accept the join, then say nothing

    threading.Thread(target=accept, daemon=True).start()
    t = threading.Thread(target=F.run_follower,
                         args=(None, "127.0.0.1", port), daemon=True)
    t.start()
    t.join(timeout=5)
    try:
        assert not t.is_alive(), "follower must fail static, not hang"
        assert METRICS.get("tpu_model_leader_lost_total") \
            == lost_before + 1
    finally:
        for c in accepted:
            c.close()
        srv.close()


def test_slow_follower_trips_backpressure_bound(monkeypatch):
    """Slow-vs-dead verdict: a follower that stops draining its socket
    wedges a dispatch for at most one TPU_CP_SEND_TIMEOUT_S window, then
    the world degrades with the typed backpressure diagnosis."""
    import time as _time

    import pytest
    from ollama_operator_tpu.runtime.errors import FollowerLost

    monkeypatch.setenv("TPU_CP_SEND_TIMEOUT_S", "0.3")
    port = _free_port()
    cp = F.ControlPlane(1, port, bind="127.0.0.1", heartbeat_s=0)
    c1 = socket.create_connection(("127.0.0.1", port))
    big = ("call", "embed", (b"x" * (1 << 20),), {})
    t0 = _time.monotonic()
    try:
        with pytest.raises(FollowerLost) as ei:
            # never read from c1: the kernel buffers fill and the send
            # window expires on a wedged — not merely slow — peer
            for _ in range(64):
                cp.broadcast(big)
        assert "backpressure bound" in str(ei.value)
        assert cp.degraded
        assert _time.monotonic() - t0 < 10
    finally:
        c1.close()
        cp.close()


def test_follower_lag_gauge_reports_worst_live_lag():
    """Sends that complete within the bound are the SLOW case: dispatch
    proceeds and the lag surfaces in tpu_model_follower_lag_seconds so
    operators see a follower eating into the backpressure window."""
    from ollama_operator_tpu.server.metrics import GLOBAL as METRICS

    port = _free_port()
    cp = F.ControlPlane(1, port, bind="127.0.0.1", heartbeat_s=0)
    c1 = socket.create_connection(("127.0.0.1", port))
    try:
        cp.broadcast(("ping",))
        assert cp.lag_s >= 0.0
        cp.lag_s = 1.25        # the gauge reads live control planes
        samples = [ln for ln in METRICS.render().splitlines()
                   if ln.startswith("tpu_model_follower_lag_seconds")]
        assert samples, "lag gauge missing from the scrape"
        assert max(float(ln.split()[-1]) for ln in samples) >= 1.25
    finally:
        c1.close()
        cp.close()


def test_heartbeat_detects_silent_follower_death():
    """With no traffic at all, the heartbeat alone must discover a dead
    follower and flip the world degraded — this is the watchdog that
    turns a wedged follower into a fast typed failure."""
    port = _free_port()
    cp = F.ControlPlane(1, port, bind="127.0.0.1", heartbeat_s=0.02)
    c1 = socket.create_connection(("127.0.0.1", port))
    import time as _time
    # wait until the heartbeat has started flowing, then kill the peer
    F._recv(c1)
    c1.close()
    deadline = _time.monotonic() + 5
    while not cp.degraded and _time.monotonic() < deadline:
        _time.sleep(0.01)
    try:
        assert cp.degraded
    finally:
        cp.close()
