"""Multi-host control plane unit tier (runtime/follower.py): framing,
FIFO broadcast, broadcast-before-execute ordering, address resolution.
The full 2-process serving e2e lives in
tests/test_compose_e2e.py::test_multihost_model_cr_serves."""

import socket
import threading

import numpy as np

from ollama_operator_tpu.runtime import follower as F


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_framing_roundtrip():
    a, b = socket.socketpair()
    msgs = [("load", "m:latest"),
            ("call", "admit", (np.arange(5, dtype=np.int32),), {}),
            ("lm_call", "embed", (["x" * 5000],)),
            ("unload",)]
    for m in msgs:
        F._send(a, m)
    for m in msgs:
        got = F._recv(b)
        assert got[0] == m[0]
        if m[0] == "call":
            np.testing.assert_array_equal(got[2][0], m[2][0])
    a.close()
    try:
        F._recv(b)
        raise AssertionError("expected ConnectionError on closed stream")
    except ConnectionError:
        pass
    b.close()


def test_control_plane_fifo_and_ready_gate():
    port = _free_port()
    cp = F.ControlPlane(2, port, bind="127.0.0.1")
    sent = []

    def producer():
        for i in range(50):
            cp.broadcast(("call", "decode_n", (i,), {}))
            sent.append(i)

    t = threading.Thread(target=producer)
    t.start()
    # broadcast must BLOCK until both followers join (a call dispatched
    # into a partial world would desync the SPMD programs)
    assert not sent, "broadcast ran before the follower set was complete"
    c1 = socket.create_connection(("127.0.0.1", port))
    assert not sent
    c2 = socket.create_connection(("127.0.0.1", port))
    t.join(timeout=10)
    assert len(sent) == 50
    for conn in (c1, c2):
        got = [F._recv(conn)[2][0] for _ in range(50)]
        assert got == list(range(50))      # FIFO, no loss, per follower
        conn.close()
    cp.close()


def test_mirrored_engine_broadcasts_before_execute():
    events = []

    class FakeCP:
        dispatch_lock = threading.RLock()

        def broadcast(self, msg):
            events.append(("bcast", msg[1]))

    class FakeEngine:
        n_slots = 4

        def decode_n(self, n=None):
            events.append(("exec", "decode_n"))
            return "toks"

        def admissible(self, n):
            return True

    me = F.MirroredEngine(FakeEngine(), FakeCP())
    assert me.decode_n(8) == "toks"
    assert events == [("bcast", "decode_n"), ("exec", "decode_n")]
    # non-mirrored attributes delegate without broadcasting
    assert me.n_slots == 4 and me.admissible(3) is True
    assert len(events) == 2


def test_control_address_resolution():
    assert F.control_address({"TPU_DIST_CONTROL": "sts-0.svc:8477"}) == \
        ("sts-0.svc", 8477)
    assert F.control_address(
        {"TPU_DIST_COORDINATOR": "sts-0.svc:8476"}) == ("sts-0.svc", 8477)
    assert F.control_address({}) is None
